"""pht-lint flow rules PHT006–PHT008 (catalog: docs/STATIC_ANALYSIS.md).

PHT006  donation-safety         — a value donated to a jitted program
        (``donate_argnums``/``donate_argnames``) is READ again after the
        donating call: on TPU the buffer was invalidated in place, so
        the read raises a deleted-buffer error at best and — via a
        cached alias — reuses garbage at worst; on CPU (donation
        unsupported) the read silently sees STALE pre-update bytes,
        which is the harder bug to find.  Rebinding the name/attribute
        is the clean shape; the flow pass clears the mark on rebind.
PHT007  tracer-escape           — (a) inside jitted and shard_map
        bodies: traced values written to ``self``/globals/outer-scope
        containers leak tracers (error at best, a frozen trace-time
        value at worst); (b) at ``run_shard_map``-style cached-program
        call sites: a per-call closure with no ``cache_key``, or a
        ``cache_key`` that does not fold in some mutable outer variable
        the closure captures — the cache then serves a STALE program
        compiled against the old captured value (the ``ring_attention``
        ``seq_local`` hazard, generalized).
PHT008  sharding-spec drift     — at ``shard_map``/``run_shard_map``/
        ``NamedSharding`` sites where the mesh's axis names are
        statically known (literal ``Mesh(...)``, ``create_mesh({...})``,
        module constants): a spec/axis name missing from the mesh, or an
        ``in_specs`` tuple whose arity disagrees with the body's
        parameters / the ``args`` tuple.  These otherwise surface as
        trace-time XLA aborts long after the edit that caused them.

Same design rules as rules.py: pure stdlib ``ast``, conservative
resolution (a shape we cannot prove is NOT flagged — misses are
acceptable, false positives are not), per-function flow sensitivity
with branch intersection so an ``if``-guarded donation never flags the
other branch.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import FuncInfo, ModuleInfo
from .rules import Finding, _call_dotted, _is_jit_ctor, _jit_targets

# wrappers that return the (possibly jitted) callable they were given:
# fn = wrapper(jax.jit(f, donate_argnums=...), ...) must still read as a
# donating binding.  sanitize_donation additionally RESTATES the donated
# positions as its own kwarg (the runtime half needs them), so the info
# is read from whichever call carries it.
_TRANSPARENT_TAILS = ("instrument_jit", "sanitize_donation")

_DONATE_KWARGS = ("donate_argnums", "donate_argnames")


def _tail(dotted: Optional[str]) -> str:
    return dotted.rsplit(".", 1)[-1] if dotted else ""


def _literal_ints(v) -> Optional[Set[int]]:
    if isinstance(v, ast.Constant) and isinstance(v.value, int):
        return {v.value}
    if isinstance(v, (ast.Tuple, ast.List)):
        out = set()
        for e in v.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, int)):
                return None
            out.add(e.value)
        return out
    return None


def _literal_strs(v) -> Optional[Set[str]]:
    if isinstance(v, ast.Constant) and isinstance(v.value, str):
        return {v.value}
    if isinstance(v, (ast.Tuple, ast.List)):
        out = set()
        for e in v.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
                return None
            out.add(e.value)
        return out
    return None


class _DonateInfo:
    __slots__ = ("argnums", "argnames", "line", "fn_params")

    def __init__(self, argnums, argnames, line, fn_params=None):
        self.argnums = argnums            # Set[int]
        self.argnames = argnames          # Set[str]
        self.line = line
        self.fn_params = fn_params        # positional arg names of the
        #                                   wrapped fn, when resolvable —
        #                                   maps argnames to positions


def _donate_info_of_call(mi: ModuleInfo, call: ast.Call,
                         funcs: Dict[str, FuncInfo]) -> Optional[_DonateInfo]:
    """Donation info of ``call`` if it constructs a donating jitted
    callable — looking through transparent wrappers."""
    seen_nums: Set[int] = set()
    seen_names: Set[str] = set()
    inner = call
    for _ in range(4):          # wrapper nesting is shallow in practice
        for kw in inner.keywords:
            if kw.arg == "donate_argnums":
                nums = _literal_ints(kw.value)
                if nums:
                    seen_nums |= nums
            elif kw.arg == "donate_argnames":
                names = _literal_strs(kw.value)
                if names:
                    seen_names |= names
        if _is_jit_ctor(mi, inner):
            break
        if _tail(_call_dotted(mi, inner)) in _TRANSPARENT_TAILS \
                and inner.args and isinstance(inner.args[0], ast.Call):
            inner = inner.args[0]
            continue
        return None
    else:
        return None
    if not seen_nums and not seen_names:
        return None
    fn_params = None
    if inner.args and isinstance(inner.args[0], ast.Name):
        fi = funcs.get(inner.args[0].id)
        if fi is not None:
            a = getattr(fi.node, "args", None)
            if a is not None:
                fn_params = [x.arg for x in a.posonlyargs + a.args]
    return _DonateInfo(seen_nums, seen_names, call.lineno, fn_params)


class _DonatingBindings(ast.NodeVisitor):
    """Module scan for donating-callable bindings:
    ``g = jax.jit(f, donate_argnums=...)`` at module level, and
    ``self.attr = jax.jit(...)`` (possibly wrapped) anywhere in a class
    body or method."""

    def __init__(self, mi: ModuleInfo):
        self.mi = mi
        self.names: Dict[str, _DonateInfo] = {}
        self.attrs: Dict[Tuple[str, str], _DonateInfo] = {}
        self._class_stack: List[str] = []
        self._func_depth = 0

    def visit_ClassDef(self, node):
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def visit_FunctionDef(self, node):
        self._func_depth += 1
        self.generic_visit(node)
        self._func_depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node: ast.Assign):
        if isinstance(node.value, ast.Call):
            info = _donate_info_of_call(self.mi, node.value, self.mi.funcs)
            if info is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name) and self._func_depth == 0:
                        self.names[t.id] = info
                    elif (isinstance(t, ast.Attribute)
                          and isinstance(t.value, ast.Name)
                          and t.value.id == "self" and self._class_stack):
                        self.attrs[(self._class_stack[-1], t.attr)] = info
        self.generic_visit(node)


# --------------------------------------------------------------------------
# PHT006: per-function donation flow
# --------------------------------------------------------------------------

Path = Tuple[str, ...]


def _path_of(e: ast.expr) -> Optional[Path]:
    """("self", ".state", "[params]") style access path, or None for
    anything dynamic (a call in the chain, a non-constant subscript)."""
    parts: List[str] = []
    while True:
        if isinstance(e, ast.Attribute):
            parts.append("." + e.attr)
            e = e.value
        elif isinstance(e, ast.Subscript):
            s = e.slice
            if isinstance(s, ast.Constant) and isinstance(
                    s.value, (str, int)):
                parts.append(f"[{s.value!r}]")
                e = e.value
            else:
                return None
        elif isinstance(e, ast.Name):
            parts.append(e.id)
            return tuple(reversed(parts))
        else:
            return None


def _render_path(p: Path) -> str:
    return "".join(p)


class _DonationWalker(ast.NodeVisitor):
    """Order-preserving walk of one function body tracking which access
    paths currently refer to DONATED (dead) buffers.

    - a donating call marks the donated argument expressions' paths (and
      their recorded aliases) dead, stamped with the call line;
    - any later Load of a dead path (or an extension of one) is a
      PHT006 finding;
    - a Store to a path clears every mark at or under it (rebinding is
      the clean shape); a method call on a path conservatively clears
      everything strictly under it (``self.state.update(...)``);
    - ``if``/``try`` branches are walked independently and the marks
      INTERSECTED after (a donation only one branch performs must not
      flag the other branch's reads).
    """

    def __init__(self, mi: ModuleInfo, fi: FuncInfo,
                 names: Dict[str, _DonateInfo],
                 attrs: Dict[Tuple[str, str], _DonateInfo],
                 findings: List[Finding]):
        self.mi = mi
        self.fi = fi
        self.names = names
        self.attrs = attrs
        self.findings = findings
        self.donated: Dict[Path, Tuple[int, str]] = {}
        self.aliases: Dict[Path, Set[Path]] = {}
        self.local_names: Dict[str, _DonateInfo] = {}
        self._reported: Set[Tuple[int, Path]] = set()

    def run(self):
        for stmt in getattr(self.fi.node, "body", []):
            self.visit(stmt)

    # -- helpers ------------------------------------------------------------
    def _mark(self, path: Path, line: int, desc: str):
        for p in {path} | self.aliases.get(path, set()):
            self.donated[p] = (line, desc)

    def _clear_under(self, path: Path, strict: bool = False):
        for p in list(self.donated):
            if p[:len(path)] == path and (not strict or p != path):
                del self.donated[p]

    def _check_load(self, node: ast.expr):
        path = _path_of(node)
        if path is None:
            return
        for d, (line, desc) in self.donated.items():
            if path[:len(d)] == d:
                key = (node.lineno, d)
                if key in self._reported:
                    return
                self._reported.add(key)
                self.findings.append(Finding(
                    rule="PHT006", file=self.mi.relpath, line=node.lineno,
                    func=self.fi.qualname,
                    message=f"`{_render_path(path)}` was donated to "
                            f"{desc} (line {line}) and is read again "
                            "here — the buffer is dead: deleted-buffer "
                            "error on TPU, silently STALE bytes on "
                            "backends without donation",
                    hint="rebind the name to the program's returned "
                         "value before any further use (p, s = "
                         "step(p, s)), or drop donation for a buffer "
                         "that must stay live"))
                return

    # -- donation detection -------------------------------------------------
    def _donating_info(self, node: ast.Call) -> Optional[_DonateInfo]:
        f = node.func
        if isinstance(f, ast.Name):
            return self.local_names.get(f.id) or self.names.get(f.id)
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "self" and self.fi.class_name:
            return self.attrs.get((self.fi.class_name, f.attr))
        if isinstance(f, ast.Call):
            # jax.jit(fn, donate_argnums=...)(args): donates right here
            return _donate_info_of_call(self.mi, f, self.mi.funcs)
        return None

    def _apply_donation(self, node: ast.Call, info: _DonateInfo):
        if any(isinstance(a, ast.Starred) for a in node.args):
            return            # positional mapping unknowable
        desc = "a donating jitted call"
        f = node.func
        fp = _path_of(f) if isinstance(f, (ast.Name, ast.Attribute)) else None
        if fp is not None:
            desc = f"donating call `{_render_path(fp)}(...)`"
        positions = set(info.argnums)
        names = set(info.argnames)
        if names and info.fn_params:
            for n in names:
                if n in info.fn_params:
                    positions.add(info.fn_params.index(n))
        exprs: List[ast.expr] = []
        for pos in positions:
            if pos < len(node.args):
                exprs.append(node.args[pos])
        for kw in node.keywords:
            if kw.arg in names:
                exprs.append(kw.value)
        for e in exprs:
            parts = e.elts if isinstance(e, (ast.Tuple, ast.List)) else (
                list(e.values) if isinstance(e, ast.Dict) else [e])
            for sub in parts:
                p = _path_of(sub)
                if p is not None:
                    self._mark(p, node.lineno, desc)

    # -- statements ---------------------------------------------------------
    def _bind_target(self, t: ast.expr):
        p = _path_of(t)
        if p is not None:
            self._clear_under(p)
            # the name now refers elsewhere: stop treating it as an
            # alias of whatever it used to share a buffer with
            self.aliases.pop(p, None)
            for group in self.aliases.values():
                group.discard(p)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._bind_target(e)
        elif isinstance(t, ast.Starred):
            self._bind_target(t.value)
        elif isinstance(t, (ast.Attribute, ast.Subscript)):
            # dynamic path: visiting the receiver checks its reads
            self.visit(t.value)

    def visit_Assign(self, node: ast.Assign):
        self.visit(node.value)
        if isinstance(node.value, ast.Call):
            info = _donate_info_of_call(self.mi, node.value, self.mi.funcs)
            if info is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.local_names[t.id] = info
        for t in node.targets:
            self._bind_target(t)
        # alias record: `x = self.buf` makes x and self.buf one buffer —
        # donating either later kills both
        vp = _path_of(node.value)
        if vp is not None:
            for t in node.targets:
                tp = _path_of(t)
                if tp is not None and tp != vp:
                    self.aliases.setdefault(vp, set()).add(tp)
                    self.aliases.setdefault(tp, set()).add(vp)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self.visit(node.value)
        self._bind_target(node.target)

    def visit_AugAssign(self, node):
        self.visit(node.value)
        self._check_load(node.target)     # x += 1 reads x first
        self._bind_target(node.target)

    def visit_Delete(self, node):
        for t in node.targets:
            p = _path_of(t)
            if p is not None:
                self._clear_under(p)

    def visit_Return(self, node):
        if node.value is not None:
            self.visit(node.value)

    # -- expressions --------------------------------------------------------
    def visit_Name(self, node: ast.Name):
        if isinstance(node.ctx, ast.Load):
            self._check_load(node)

    def visit_Attribute(self, node: ast.Attribute):
        if isinstance(node.ctx, ast.Load):
            self._check_load(node)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        if isinstance(node.ctx, ast.Load):
            self._check_load(node)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        # receiver of a method call: a READ of the receiver path (a dead
        # receiver fires), then args, then donation marks, then the
        # conservative mutation-clears-children rule
        recv_path = None
        if isinstance(node.func, ast.Attribute):
            recv_path = _path_of(node.func.value)
            self._check_load(node.func.value)
            if recv_path is None:
                self.visit(node.func.value)
        elif isinstance(node.func, ast.Call):
            self.visit(node.func)
        for a in node.args:
            self.visit(a.value if isinstance(a, ast.Starred) else a)
        for kw in node.keywords:
            self.visit(kw.value)
        if recv_path is not None:
            # self.state.update(params=p): whatever lived UNDER the
            # receiver may have been rebound by the mutation.  BEFORE
            # donation marking: the call's own marks are its post-state
            # (self._jit(self.state[...]) must not clear itself)
            self._clear_under(recv_path, strict=True)
        info = self._donating_info(node)
        if info is not None:
            self._apply_donation(node, info)

    # -- control flow: branch intersection ----------------------------------
    def _branch(self, stmts) -> Dict[Path, Tuple[int, str]]:
        saved = dict(self.donated)
        for s in stmts:
            self.visit(s)
        out = self.donated
        self.donated = saved
        return out

    def visit_If(self, node: ast.If):
        self.visit(node.test)
        aliases_before = {k: set(v) for k, v in self.aliases.items()}
        a = self._branch(node.body)
        b = self._branch(node.orelse)
        self.donated = {k: v for k, v in a.items() if k in b}
        # aliases recorded inside a branch may not hold on the other
        # path — keeping them could mark a buffer donated through an
        # alias that never existed (a false positive); drop them
        self.aliases = aliases_before

    def visit_Try(self, node):
        body = self._branch(node.body)    # walked against current state
        for h in node.handlers:
            # handlers run against the PRE-try marks: the donation the
            # body performs may not have happened when the handler does
            saved = dict(self.donated)
            for s in h.body:
                self.visit(s)
            self.donated = saved
        # fall-through continues on the no-exception path's state
        self.donated = body
        for s in node.finalbody:
            self.visit(s)

    def visit_While(self, node):
        self.visit(node.test)
        for s in node.body:
            self.visit(s)
        for s in node.orelse:
            self.visit(s)

    def visit_For(self, node):
        self.visit(node.iter)
        self._bind_target(node.target)
        for s in node.body:
            self.visit(s)
        for s in node.orelse:
            self.visit(s)

    # nested defs/lambdas: separate scopes (their own FuncInfo)
    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass


# --------------------------------------------------------------------------
# PHT007: tracer escape + stale closure capture
# --------------------------------------------------------------------------

_SMAP_TAILS = ("shard_map", "run_shard_map")
_MUTATORS = frozenset(("append", "add", "extend", "insert", "update",
                       "setdefault", "put", "appendleft"))


def _bound_names(node: ast.AST) -> Set[str]:
    """Every name BOUND anywhere under ``node`` (params of nested defs
    included — over-approximating bound names shrinks the free set,
    which can only MISS)."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.add(n.name)
            a = n.args
            for x in (a.posonlyargs + a.args + a.kwonlyargs):
                out.add(x.arg)
            if a.vararg:
                out.add(a.vararg.arg)
            if a.kwarg:
                out.add(a.kwarg.arg)
        elif isinstance(n, ast.Lambda):
            a = n.args
            for x in (a.posonlyargs + a.args + a.kwonlyargs):
                out.add(x.arg)
        elif isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store,
                                                            ast.Del)):
            out.add(n.id)
        elif isinstance(n, (ast.comprehension,)):
            for t in ast.walk(n.target):
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif isinstance(n, ast.ExceptHandler) and n.name:
            out.add(n.name)
    return out


def _free_names(fn_node: ast.AST) -> Set[str]:
    bound = _bound_names(fn_node)
    free: Set[str] = set()
    for n in ast.walk(fn_node):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                and n.id not in bound:
            free.add(n.id)
    return free


def _traced_body_set(mi: ModuleInfo) -> Dict[str, str]:
    """qualname -> description, for functions whose bodies run under a
    trace: jitted functions (rules._jit_targets) and functions passed as
    the body of ``shard_map``/``run_shard_map``."""
    out: Dict[str, str] = {}
    for q in _jit_targets(mi):
        out[q] = "jitted"
    for fi in mi.funcs.values():
        for ref in fi.calls:
            node = ref.node
            if _tail(_call_dotted(mi, node)) not in _SMAP_TAILS:
                continue
            if node.args and isinstance(node.args[0], ast.Name):
                name = node.args[0].id
                # nearest enclosing scope, same rules as bare calls
                prefix = fi.qualname
                while prefix:
                    cand = f"{prefix}.{name}"
                    if cand in mi.funcs:
                        out[cand] = "shard_map body"
                        break
                    prefix = prefix.rpartition(".")[0]
                else:
                    if name in mi.funcs:
                        out[name] = "shard_map body"
    return out


class _TracerEscapeWalker(ast.NodeVisitor):
    """One traced body: flag writes of (potentially) traced values to
    ``self``, declared globals/nonlocals, and outer-scope containers.
    Inside a traced body, any value derived from a parameter or a
    jnp/lax call is traced; host constants are not.  Conservative: only
    values the taint pass can SEE as traced are flagged."""

    def __init__(self, mi: ModuleInfo, fi: FuncInfo, kind: str,
                 findings: List[Finding]):
        self.mi = mi
        self.fi = fi
        self.kind = kind
        self.findings = findings
        a = getattr(fi.node, "args", None)
        params = [x.arg for x in (a.posonlyargs + a.args + a.kwonlyargs)] \
            if a else []
        self.tainted: Set[str] = {p for p in params
                                  if p not in ("self", "cls")}
        self.locals: Set[str] = set(params) | set(fi.local_defs)
        self.outer_decl: Set[str] = set()
        for n in ast.walk(fi.node):
            if isinstance(n, (ast.Global, ast.Nonlocal)):
                self.outer_decl.update(n.names)
            elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                self.locals.add(n.id)

    def run(self):
        for stmt in getattr(self.fi.node, "body", []):
            self.visit(stmt)

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    def _is_traced(self, e: ast.expr) -> bool:
        from .rules import _DEVICE_PREFIXES, _DEVICE_EXACT
        if isinstance(e, ast.Name):
            return e.id in self.tainted
        if isinstance(e, ast.Call):
            d = _call_dotted(self.mi, e)
            if d is not None and (d.startswith(_DEVICE_PREFIXES)
                                  or d in _DEVICE_EXACT
                                  or d.startswith("jax.lax.")):
                return True
            # a call over traced inputs yields a traced output
            return any(self._is_traced(a) for a in e.args) \
                or any(self._is_traced(k.value) for k in e.keywords)
        if isinstance(e, (ast.Subscript, ast.Attribute, ast.Starred)):
            return self._is_traced(e.value)
        if isinstance(e, ast.BinOp):
            return self._is_traced(e.left) or self._is_traced(e.right)
        if isinstance(e, (ast.UnaryOp,)):
            return self._is_traced(e.operand)
        if isinstance(e, (ast.Tuple, ast.List)):
            return any(self._is_traced(x) for x in e.elts)
        if isinstance(e, ast.IfExp):
            return self._is_traced(e.body) or self._is_traced(e.orelse)
        return False

    def _emit(self, node, target_desc: str):
        self.findings.append(Finding(
            rule="PHT007", file=self.mi.relpath, line=node.lineno,
            func=self.fi.qualname,
            message=f"traced value written to {target_desc} inside a "
                    f"{self.kind} body — the tracer escapes the trace: "
                    "an error under strict checks, or a value frozen at "
                    "trace time that silently never updates",
            hint="return the value from the traced function (ride the "
                 "program's outputs) instead of writing through the "
                 "closure; host-side state belongs outside the trace"))

    def visit_Assign(self, node: ast.Assign):
        self.visit(node.value)
        traced = self._is_traced(node.value)
        for t in node.targets:
            # taint propagation: a local assigned from a traced value is
            # itself traced for everything downstream
            if isinstance(t, ast.Name):
                (self.tainted.add if traced
                 else self.tainted.discard)(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)) and traced:
                for e in t.elts:
                    if isinstance(e, ast.Name):
                        self.tainted.add(e.id)
            if not traced:
                continue
            if isinstance(t, ast.Attribute) and isinstance(
                    t.value, ast.Name) and t.value.id in ("self", "cls"):
                self._emit(node, f"`{t.value.id}.{t.attr}`")
            elif isinstance(t, ast.Name) and t.id in self.outer_decl:
                self._emit(node, f"global/nonlocal `{t.id}`")
            elif isinstance(t, ast.Subscript):
                p = _path_of(t.value)
                if p is not None and p[0] not in self.locals \
                        and p[0] not in self.mi.imports \
                        and p[0] not in ("self", "cls"):
                    self._emit(node, f"outer container "
                                     f"`{_render_path(p)}[...]`")

    def visit_Call(self, node: ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
            p = _path_of(f.value)
            if p is not None and p[0] not in self.locals \
                    and p[0] not in self.mi.imports:
                if any(self._is_traced(a) for a in node.args) or any(
                        self._is_traced(k.value) for k in node.keywords):
                    self._emit(node, f"outer container `{_render_path(p)}` "
                                     f"(.{f.attr})")
        self.generic_visit(node)


def _lint_cached_program_keys(mi: ModuleInfo, findings: List[Finding]):
    """PHT007(b): ``run_shard_map(local_closure, ..., cache_key=K)``
    sites — a fresh-per-call closure must carry a cache_key, and the key
    must mention every mutable outer variable the closure captures."""
    for fi in mi.funcs.values():
        # early exit: the per-function scans below (own-store walk,
        # call-result bindings, free-name closures) are walk-heavy and
        # only matter at run_shard_map call sites — most functions in
        # most modules have none
        if not any(_tail(_call_dotted(mi, ref.node)) == "run_shard_map"
                   for ref in fi.calls):
            continue
        # names bound in THIS function's own scope (params + stores,
        # nested subtrees excluded so a nested def's locals don't count)
        a = getattr(fi.node, "args", None)
        own: Set[str] = set(fi.local_defs)
        if a is not None:
            own |= {x.arg for x in a.posonlyargs + a.args + a.kwonlyargs}

        class _OwnStores(ast.NodeVisitor):
            def visit_FunctionDef(self, n):
                pass

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Lambda(self, n):
                pass

            def visit_Name(self, n):
                if isinstance(n.ctx, ast.Store):
                    own.add(n.id)

        w = _OwnStores()
        for stmt in getattr(fi.node, "body", []):
            w.visit(stmt)

        # names assigned from calls in this scope (per-call identity even
        # though the closure body is elsewhere, e.g. spmd = _builder(...));
        # linenos kept so a name bound BOTH ways (ring_attention's two
        # `spmd` bindings) resolves to whichever binding precedes the
        # call site, like the interpreter would
        call_made: Dict[str, List[int]] = {}
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        call_made.setdefault(t.id, []).append(n.lineno)

        for ref in fi.calls:
            node = ref.node
            if _tail(_call_dotted(mi, node)) != "run_shard_map" \
                    or not node.args:
                continue
            fn_arg = node.args[0]
            key_kw = next((kw for kw in node.keywords
                           if kw.arg == "cache_key"), None)
            local_def = None
            per_call = isinstance(fn_arg, ast.Lambda)
            if isinstance(fn_arg, ast.Name):
                cand = f"{fi.qualname}.{fn_arg.id}"
                def_line = mi.funcs[cand].lineno if cand in mi.funcs \
                    else None
                assign_lines = [ln for ln in call_made.get(fn_arg.id, ())
                                if ln < node.lineno]
                if def_line is not None or assign_lines:
                    per_call = True
                # nearest binding preceding the call wins; a call-result
                # binding has an unknowable body, so only a def binding
                # gets the capture-coverage check
                best_assign = max(assign_lines, default=-1)
                if def_line is not None and (
                        def_line < node.lineno and def_line > best_assign
                        or best_assign < 0):
                    local_def = mi.funcs[cand]
            if not per_call:
                continue
            if key_kw is None:
                findings.append(Finding(
                    rule="PHT007", file=mi.relpath, line=node.lineno,
                    func=fi.qualname,
                    message="run_shard_map called with a per-call "
                            "closure and NO cache_key — the program "
                            "cache keys on the closure's identity, which "
                            "is fresh every call: full retrace+compile "
                            "per invocation",
                    hint="pass cache_key=(<stable tag>, <every value the "
                         "closure captures>) — see ring_attention in "
                         "parallel/sequence.py"))
                continue
            if local_def is None:
                continue
            # run_shard_map folds mesh, manual_axes and the spec trees
            # into its program key itself — a capture that rides one of
            # those arguments is covered without appearing in cache_key
            key_names = {n.id for n in ast.walk(key_kw.value)
                         if isinstance(n, ast.Name)}
            for kw in node.keywords:
                if kw.arg in ("in_specs", "out_specs", "manual_axes"):
                    key_names |= {n.id for n in ast.walk(kw.value)
                                  if isinstance(n, ast.Name)}
            if len(node.args) > 1:
                key_names |= {n.id for n in ast.walk(node.args[1])
                              if isinstance(n, ast.Name)}

            def _covered(fn_node, seen: Set[str]) -> List[str]:
                """Captured own-scope names not covered by the key —
                recursing through captured LOCAL DEFS (a fresh helper
                closure is covered iff everything IT captures is)."""
                out: List[str] = []
                for name in sorted(_free_names(fn_node) & own):
                    # `self`/`cls` captures are method-closure routine;
                    # traced writes through them are PHT007(a)'s job
                    if name in key_names or name in seen \
                            or name in ("self", "cls"):
                        continue
                    seen.add(name)
                    inner = mi.funcs.get(f"{fi.qualname}.{name}")
                    if inner is not None:
                        out.extend(_covered(inner.node, seen))
                    else:
                        out.append(name)
                return out

            uncovered = _covered(local_def.node, set())
            if uncovered:
                findings.append(Finding(
                    rule="PHT007", file=mi.relpath, line=node.lineno,
                    func=fi.qualname,
                    message=f"cache_key does not cover outer "
                            f"variable(s) {', '.join(uncovered)} captured "
                            "by the closure — two calls with equal keys "
                            "but different captured values reuse ONE "
                            "cached program, silently replaying the "
                            "stale capture (the ring_attention "
                            "seq_local hazard)",
                    hint="fold every captured local into the cache_key "
                         "tuple (the run_shard_map contract: equal keys "
                         "must want the same program)"))


# --------------------------------------------------------------------------
# PHT008: sharding-spec drift
# --------------------------------------------------------------------------

def _module_constants(mi: ModuleInfo) -> Dict[str, Set[str]]:
    """Module-level NAME = ("dp", "mp") string-tuple constants."""
    out: Dict[str, Set[str]] = {}
    for node in mi.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            strs = _literal_strs(node.value)
            if strs is not None:
                out[node.targets[0].id] = strs
    return out


def _mesh_axes_of_value(mi: ModuleInfo, value: ast.expr,
                        consts: Dict[str, Set[str]]) -> Optional[Set[str]]:
    """Statically known axis-name set of a mesh-constructing expression."""
    if not isinstance(value, ast.Call):
        return None
    tail = _tail(_call_dotted(mi, value))
    if tail == "Mesh":
        ax = None
        if len(value.args) >= 2:
            ax = value.args[1]
        for kw in value.keywords:
            if kw.arg == "axis_names":
                ax = kw.value
        if ax is None:
            return None
        if isinstance(ax, ast.Name):
            return consts.get(ax.id)
        return _literal_strs(ax)
    if tail == "create_mesh":
        if value.args and isinstance(value.args[0], ast.Dict):
            keys = set()
            for k in value.args[0].keys:
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    return None
                keys.add(k.value)
            return keys or {"dp"}
    return None


def _collect_known_meshes(mi: ModuleInfo,
                          consts: Dict[str, Set[str]]) -> Dict[str, Set[str]]:
    out: Dict[str, Set[str]] = {}
    ambiguous: Set[str] = set()
    for node in ast.walk(mi.tree):
        if isinstance(node, ast.Assign):
            axes = _mesh_axes_of_value(mi, node.value, consts)
            for t in node.targets:
                if isinstance(t, ast.Name):
                    if axes is None:
                        if isinstance(node.value, ast.Call) and _tail(
                                _call_dotted(mi, node.value)) in (
                                    "Mesh", "create_mesh"):
                            ambiguous.add(t.id)
                    elif t.id in out and out[t.id] != axes:
                        ambiguous.add(t.id)
                    else:
                        out[t.id] = axes
    for name in ambiguous:
        out.pop(name, None)
    return out


def _spec_axis_names(mi: ModuleInfo, e: ast.expr) -> List[Tuple[str, int]]:
    """(axis_name, lineno) for every string inside P(...)/PartitionSpec
    calls under ``e``."""
    out: List[Tuple[str, int]] = []
    for n in ast.walk(e):
        if isinstance(n, ast.Call) and _tail(_call_dotted(mi, n)) \
                == "PartitionSpec":
            for sub in n.args:
                for c in ast.walk(sub):
                    if isinstance(c, ast.Constant) and isinstance(
                            c.value, str):
                        out.append((c.value, n.lineno))
    return out


def _fn_positional_arity(mi: ModuleInfo, fi: FuncInfo,
                         fn_arg: ast.expr) -> Optional[int]:
    if isinstance(fn_arg, ast.Lambda):
        a = fn_arg.args
        if a.vararg or a.defaults:
            return None
        return len(a.posonlyargs + a.args)
    if not isinstance(fn_arg, ast.Name):
        return None
    prefix = fi.qualname
    target = None
    while prefix:
        cand = f"{prefix}.{fn_arg.id}"
        if cand in mi.funcs:
            target = mi.funcs[cand]
            break
        prefix = prefix.rpartition(".")[0]
    if target is None:
        target = mi.funcs.get(fn_arg.id)
    if target is None:
        return None
    a = getattr(target.node, "args", None)
    if a is None or a.vararg or a.defaults or a.kwonlyargs:
        return None           # defaults/varargs make arity a range
    return len(a.posonlyargs + a.args)


_SPEC_SITE_TAILS = ("NamedSharding",) + _SMAP_TAILS


def _lint_spec_drift(mi: ModuleInfo, findings: List[Finding]):
    # early exit: the rule only checks call sites recorded in fi.calls,
    # so a module with none skips the (tree-walking) constant and mesh
    # collection entirely
    if not any(_tail(_call_dotted(mi, ref.node)) in _SPEC_SITE_TAILS
               for fi in mi.funcs.values() for ref in fi.calls):
        return
    consts = _module_constants(mi)
    known = _collect_known_meshes(mi, consts)

    def _emit(node, fi, message, hint):
        findings.append(Finding(
            rule="PHT008", file=mi.relpath, line=node.lineno,
            func=fi.qualname, message=message, hint=hint))

    for fi in mi.funcs.values():
        for ref in fi.calls:
            node = ref.node
            tail = _tail(_call_dotted(mi, node))
            kw = {k.arg: k.value for k in node.keywords if k.arg}

            def pos_or_kw(i, name):
                if name in kw:
                    return kw[name]
                if len(node.args) > i and not any(
                        isinstance(a, ast.Starred) for a in node.args[:i + 1]):
                    return node.args[i]
                return None

            if tail == "NamedSharding" and node.args:
                mesh_e = node.args[0]
                axes = known.get(mesh_e.id) if isinstance(
                    mesh_e, ast.Name) else _mesh_axes_of_value(
                        mi, mesh_e, consts)
                spec_e = pos_or_kw(1, "spec")
                if axes is not None and spec_e is not None:
                    for name, ln in _spec_axis_names(mi, spec_e):
                        if name not in axes:
                            _emit(node, fi,
                                  f"spec axis `{name}` is not an axis of "
                                  f"the mesh ({sorted(axes)}) — this "
                                  "NamedSharding aborts at trace time",
                                  "rename the spec axis to match the "
                                  "mesh (or add the axis to the mesh "
                                  "builder)")
            elif tail in _SMAP_TAILS:
                is_run = tail == "run_shard_map"
                mesh_e = pos_or_kw(1, "mesh")
                axes = None
                if isinstance(mesh_e, ast.Name):
                    axes = known.get(mesh_e.id)
                elif mesh_e is not None:
                    axes = _mesh_axes_of_value(mi, mesh_e, consts)
                in_specs = pos_or_kw(2 if is_run else 10 ** 6, "in_specs")
                out_specs = pos_or_kw(3 if is_run else 10 ** 6, "out_specs")
                manual = kw.get("manual_axes") if is_run \
                    else kw.get("axis_names")
                if is_run and manual is None:
                    manual = pos_or_kw(4, "manual_axes")
                if axes is not None:
                    for e in (in_specs, out_specs):
                        if e is None:
                            continue
                        for name, ln in _spec_axis_names(mi, e):
                            if name not in axes:
                                _emit(node, fi,
                                      f"spec axis `{name}` is not an "
                                      f"axis of the mesh "
                                      f"({sorted(axes)}) — XLA aborts "
                                      "at trace time, long after the "
                                      "rename that caused it",
                                      "keep spec axis names in lockstep "
                                      "with the mesh builder's axes")
                    if manual is not None:
                        names = _literal_strs(manual) or (
                            {e.value for e in manual.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, str)}
                            if isinstance(manual, ast.Set) else set())
                        for name in sorted(names or ()):
                            if name not in axes:
                                _emit(node, fi,
                                      f"manual axis `{name}` is not an "
                                      f"axis of the mesh "
                                      f"({sorted(axes)})",
                                      "manual_axes must name mesh axes")
                # arity: in_specs tuple vs body params vs args tuple
                if isinstance(in_specs, (ast.Tuple, ast.List)):
                    n_specs = len(in_specs.elts)
                    if node.args:
                        arity = _fn_positional_arity(mi, fi, node.args[0])
                        if arity is not None and arity != n_specs:
                            _emit(node, fi,
                                  f"in_specs has {n_specs} entries but "
                                  f"the body takes {arity} argument(s) "
                                  "— the spec tree no longer matches "
                                  "the program (added an argument "
                                  "without its spec?)",
                                  "give every body argument exactly one "
                                  "in_specs entry")
                    if is_run:
                        args_e = pos_or_kw(5, "args")
                        if isinstance(args_e, (ast.Tuple, ast.List)) \
                                and len(args_e.elts) != n_specs:
                            _emit(node, fi,
                                  f"in_specs has {n_specs} entries but "
                                  f"args passes {len(args_e.elts)} "
                                  "value(s)",
                                  "one spec per argument — arity drift "
                                  "aborts in XLA at trace time")


# --------------------------------------------------------------------------
# driver
# --------------------------------------------------------------------------

def lint_module_flow(mi: ModuleInfo) -> List[Finding]:
    findings: List[Finding] = []

    bindings = _DonatingBindings(mi)
    bindings.visit(mi.tree)
    for fi in mi.funcs.values():
        if isinstance(fi.node, ast.Lambda):
            continue
        _DonationWalker(mi, fi, bindings.names, bindings.attrs,
                        findings).run()

    traced = _traced_body_set(mi)
    for qual, kind in traced.items():
        fi = mi.funcs.get(qual)
        if fi is not None and not isinstance(fi.node, ast.Lambda):
            _TracerEscapeWalker(mi, fi, kind, findings).run()
    _lint_cached_program_keys(mi, findings)
    _lint_spec_drift(mi, findings)
    return findings
