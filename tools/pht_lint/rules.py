"""pht-lint rules PHT001–PHT005 (catalog: docs/STATIC_ANALYSIS.md; the
flow-sensitive PHT006–PHT008 live in flow.py).

PHT001  host-sync-in-hot-path   — .item() / block_until_ready /
        jax.device_get / np.asarray-on-device-value / float()/int()/
        bool()-on-device-value / implicit bool, inside functions
        reachable from a declared ``# pht-lint: hot-root``.
PHT002  retrace-hazard          — jit constructed in a loop body or a
        hot function; ``jax.jit(f)(...)`` where ``f`` has per-call
        identity (local def / lambda / local name); a list/dict/set
        literal passed at a ``static_argnums`` position; Python
        branching on a traced parameter inside a jitted body.
PHT003  lock-discipline         — cycles in the cross-module static
        lock-acquisition order graph; locks held across device dispatch
        or host syncs.
PHT004  nondeterminism-in-jit   — time.* / random.* / np.random.*
        inside jitted bodies (traced once, frozen forever).
PHT005  metric-label-cardinality — ``.labels(...)`` keyword values
        derived from request/session ids or unbounded loop indices:
        every new value mints a fresh time series, so the registry
        (and every scrape) grows without bound.  Per-request data
        belongs in spans / the flight recorder / lifecycle records,
        never in labels.

"Device value" tracking is a per-function forward taint pass: names
assigned from jax/jnp calls are device; jax.device_get launders back to
host.  No interprocedural taint — a miss is conservative, never a false
positive.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import (CallRef, FuncInfo, ModuleInfo, dotted_of, hot_set,
                        resolve_same_module)


@dataclass
class Finding:
    rule: str
    file: str
    line: int
    func: str
    message: str
    hint: str

    def key(self):
        return (self.rule, self.file, self.line)

    def render(self) -> str:
        return (f"{self.file}:{self.line}: {self.rule} [{self.func}] "
                f"{self.message}\n    hint: {self.hint}")


# --------------------------------------------------------------------------
# shared classifiers
# --------------------------------------------------------------------------

_DEVICE_PREFIXES = ("jax.numpy.", "jax.lax.", "jax.random.", "jax.nn.",
                    "jax.scipy.")
_DEVICE_EXACT = {"jax.device_put"}
_SYNC_EXACT = {"jax.device_get", "jax.block_until_ready"}
_NP_HOSTIFY = {"numpy.asarray", "numpy.array", "numpy.copy"}
_JIT_NAMES = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit",
              "pjit.pjit"}
_NONDET_ROOTS = ("time", "random")
_NONDET_PREFIXES = ("numpy.random.",)


def _is_device_call(dotted: Optional[str]) -> bool:
    if dotted is None:
        return False
    return (dotted.startswith(_DEVICE_PREFIXES) or dotted in _DEVICE_EXACT)


def _call_dotted(mi: ModuleInfo, node: ast.Call) -> Optional[str]:
    return mi.resolve_dotted(node.func)


def _is_jit_ctor(mi: ModuleInfo, node: ast.Call) -> bool:
    d = _call_dotted(mi, node)
    return d in _JIT_NAMES


def _static_positions(node: ast.Call) -> Optional[Set[int]]:
    """Literal static_argnums of a jit call, or None if non-literal."""
    for kw in node.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return {v.value}
            if isinstance(v, (ast.Tuple, ast.List)):
                out = set()
                for e in v.elts:
                    if not (isinstance(e, ast.Constant)
                            and isinstance(e.value, int)):
                        return None
                    out.add(e.value)
                return out
            return None
    return set()


# --------------------------------------------------------------------------
# PHT001 + PHT002(jit-in-loop/hot, immediate-call) + taint walker
# --------------------------------------------------------------------------

class _FuncWalker(ast.NodeVisitor):
    """Order-preserving walk of ONE function body: taint + rule checks.

    Nested defs are skipped (they are separate FuncInfo entries, linted
    on their own if reachable)."""

    def __init__(self, mi: ModuleInfo, fi: FuncInfo, hot: bool,
                 jit_bindings: Dict[str, Set[int]],
                 findings: List[Finding]):
        self.mi = mi
        self.fi = fi
        self.hot = hot
        self.jit_bindings = jit_bindings
        self.findings = findings
        self.tainted: Set[str] = set()
        # names PROVABLY holding host values (numpy-from-host results,
        # laundered fetches): three-state lattice — tainted / host /
        # unknown — so receiver-always rules (.item) can skip the
        # provably-host case without losing the unknown-receiver catch
        self.host_names: Set[str] = set()
        self.loop_depth = 0
        self.local_names: Set[str] = set(fi.local_defs)
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    for nn in ast.walk(t):
                        if isinstance(nn, ast.Name):
                            self.local_names.add(nn.id)

    # -- entry -------------------------------------------------------------
    def run(self):
        body = getattr(self.fi.node, "body", [])
        for stmt in body:
            self.visit(stmt)

    def visit_FunctionDef(self, node):   # don't descend into nested defs
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    # -- taint -------------------------------------------------------------
    def _expr_tainted(self, e: ast.expr) -> bool:
        if isinstance(e, ast.Name):
            return e.id in self.tainted
        if isinstance(e, ast.Call):
            d = _call_dotted(self.mi, e)
            if d in _SYNC_EXACT or d in _NP_HOSTIFY:
                return False      # result is back on host
            if _is_device_call(d):
                return True
            return False
        if isinstance(e, (ast.Subscript, ast.Attribute)):
            return self._expr_tainted(e.value)
        if isinstance(e, ast.BinOp):
            return (self._expr_tainted(e.left)
                    or self._expr_tainted(e.right))
        if isinstance(e, ast.UnaryOp):
            return self._expr_tainted(e.operand)
        if isinstance(e, (ast.Tuple, ast.List)):
            return any(self._expr_tainted(x) for x in e.elts)
        return False

    def _provably_host(self, e: ast.expr) -> bool:
        """True when ``e`` is definitely a host value: a constant, a
        name assigned from one, or a laundering/numpy-from-host call."""
        if isinstance(e, ast.Constant):
            return True
        if isinstance(e, ast.Name):
            return e.id in self.host_names
        if isinstance(e, ast.Call):
            d = _call_dotted(self.mi, e)
            if d in _SYNC_EXACT:
                return True
            if d is not None and d.split(".")[0] == "numpy" \
                    and not any(self._expr_tainted(a) for a in e.args):
                return True
        if isinstance(e, ast.Subscript):
            return self._provably_host(e.value)
        return False

    def _bind_target(self, target: ast.expr, t: bool, host: bool) -> None:
        """(Un)taint exactly the names this target BINDS.  Attribute and
        Subscript targets bind nothing we track — and crucially their
        RECEIVER's taint must not change: ``self.k = jnp.zeros(4)`` says
        nothing about ``self`` itself (tainting it false-fired PHT001 on
        host-data attribute reads; untainting it masked real ones)."""
        if isinstance(target, ast.Name):
            (self.tainted.add if t else self.tainted.discard)(target.id)
            (self.host_names.add if host
             else self.host_names.discard)(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind_target(e, t, host)
        elif isinstance(target, ast.Starred):
            self._bind_target(target.value, t, host)

    def visit_Assign(self, node: ast.Assign):
        self.visit(node.value)
        t = self._expr_tainted(node.value)
        host = not t and self._provably_host(node.value)
        for tgt in node.targets:
            self._bind_target(tgt, t, host)

    def visit_AugAssign(self, node: ast.AugAssign):
        self.visit(node.value)
        if isinstance(node.target, ast.Name):
            if self._expr_tainted(node.value):
                self.tainted.add(node.target.id)

    # -- control flow ------------------------------------------------------
    def _check_implicit_bool(self, test: ast.expr):
        if not self.hot:
            return
        if isinstance(test, ast.Name) and test.id in self.tainted:
            self._emit("PHT001", test,
                       f"implicit bool() on device value `{test.id}` "
                       "blocks on the device",
                       "fetch once with jax.device_get(...) outside the "
                       "hot loop, or keep the predicate on device "
                       "(jnp.where/lax.cond)")

    def visit_If(self, node: ast.If):
        self._check_implicit_bool(node.test)
        self.visit(node.test)
        for s in node.body:
            self.visit(s)
        for s in node.orelse:
            self.visit(s)

    def visit_While(self, node: ast.While):
        self._check_implicit_bool(node.test)
        self.visit(node.test)
        self.loop_depth += 1
        for s in node.body:
            self.visit(s)
        self.loop_depth -= 1
        for s in node.orelse:
            self.visit(s)

    def visit_For(self, node: ast.For):
        self.visit(node.iter)
        self.loop_depth += 1
        for s in node.body:
            self.visit(s)
        self.loop_depth -= 1
        for s in node.orelse:
            self.visit(s)

    # -- calls -------------------------------------------------------------
    def visit_Call(self, node: ast.Call):
        d = _call_dotted(self.mi, node)
        f = node.func

        # PHT002: jit constructed here
        if _is_jit_ctor(self.mi, node):
            if self.loop_depth > 0:
                self._emit("PHT002", node,
                           "jax.jit/pjit constructed inside a loop body "
                           "— a fresh program identity every iteration "
                           "defeats the jit cache (retrace per pass)",
                           "hoist the jit construction out of the loop "
                           "(build once, call many)")
            elif self.hot:
                self._emit("PHT002", node,
                           "jax.jit/pjit constructed inside a hot-path "
                           "function — per-call program construction "
                           "retraces on every invocation",
                           "build the program once at init and cache it "
                           "(see ServingEngine._prog)")
            self._check_static_literals(node, node)

        # PHT002: jax.jit(f)(...) with per-call identity of f
        if isinstance(f, ast.Call) and _is_jit_ctor(self.mi, f):
            inner = f.args[0] if f.args else None
            unstable = (isinstance(inner, ast.Lambda)
                        or (isinstance(inner, ast.Name)
                            and inner.id in self.local_names))
            if unstable:
                self._emit("PHT002", node,
                           "jax.jit(f)(...) where f is a local "
                           "function/lambda: the jit cache keys on f's "
                           "identity, which is fresh every call — this "
                           "retraces and recompiles per invocation",
                           "cache the jitted callable keyed by what the "
                           "closure actually captures (see "
                           "parallel/_smap.py run_shard_map)")
            self._check_static_literals(f, node)

        # PHT002: non-hashable literal at a static position of a bound
        # jitted callable
        if isinstance(f, ast.Name) and f.id in self.jit_bindings:
            self._check_static_args(self.jit_bindings[f.id], node)

        # PHT001 (hot functions only).  .item()/.block_until_ready fire
        # on UNKNOWN receivers too (attributes, parameters — the taint
        # pass can't see them, and a device array there is the common
        # case) but not on provably-host ones (numpy .item() is not a
        # sync).
        if self.hot:
            if isinstance(f, ast.Attribute) and f.attr == "item" \
                    and not node.args and not node.keywords \
                    and not self._provably_host(f.value):
                self._emit("PHT001", node,
                           ".item() forces a device→host sync",
                           "batch the fetch: jax.device_get once per "
                           "tick, not per element")
            elif isinstance(f, ast.Attribute) \
                    and f.attr == "block_until_ready":
                self._emit("PHT001", node,
                           ".block_until_ready() blocks the host on "
                           "device completion",
                           "only sync at designed boundaries (log_freq, "
                           "epoch end); baseline with a reason if this "
                           "IS one")
            elif d in _SYNC_EXACT:
                self._emit("PHT001", node,
                           f"{d} is a host sync",
                           "keep it to one designed fetch per tick; "
                           "baseline with a reason if this is it")
            elif d in _NP_HOSTIFY and node.args \
                    and self._expr_tainted(node.args[0]):
                self._emit("PHT001", node,
                           f"{d} on a device value is an implicit "
                           "device→host transfer",
                           "use jax.device_get(...) to make the sync "
                           "explicit (and transfer-guard-clean), or "
                           "keep the value on device")
            elif isinstance(f, ast.Name) \
                    and f.id in ("float", "int", "bool") \
                    and f.id not in self.mi.imports \
                    and node.args and self._expr_tainted(node.args[0]):
                self._emit("PHT001", node,
                           f"{f.id}() on a device value forces a "
                           "device→host sync",
                           "fetch via jax.device_get at a designed sync "
                           "point instead")

        self.generic_visit(node)

    def _check_static_literals(self, jit_call: ast.Call,
                               outer: ast.Call):
        """jit(f, static_argnums=...)(args...) direct-call form."""
        if outer is jit_call:
            return
        statics = _static_positions(jit_call)
        if statics:
            self._check_static_args(statics, outer)

    def _check_static_args(self, statics: Set[int], call: ast.Call):
        for pos in statics:
            if pos < len(call.args) and isinstance(
                    call.args[pos], (ast.List, ast.Dict, ast.Set,
                                     ast.ListComp, ast.DictComp,
                                     ast.SetComp)):
                self._emit("PHT002", call.args[pos],
                           f"non-hashable literal at static_argnums "
                           f"position {pos} — jit static args must hash "
                           "(this raises, or retraces if converted "
                           "per call)",
                           "pass a tuple / frozen value, or make the "
                           "argument traced")

    def _emit(self, rule, node, message, hint):
        self.findings.append(Finding(
            rule=rule, file=self.mi.relpath, line=node.lineno,
            func=self.fi.qualname, message=message, hint=hint))


def _collect_jit_bindings(mi: ModuleInfo) -> Dict[str, Set[int]]:
    """``g = jax.jit(f, static_argnums=<literal>)`` name bindings."""
    out: Dict[str, Set[int]] = {}
    for node in ast.walk(mi.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                and _is_jit_ctor(mi, node.value):
            statics = _static_positions(node.value)
            if statics:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = statics
    return out


# --------------------------------------------------------------------------
# jit-target discovery (PHT002 traced-branch, PHT004)
# --------------------------------------------------------------------------

def _jit_targets(mi: ModuleInfo) -> Dict[str, Set[int]]:
    """qualname -> static positions, for functions that get jitted:
    decorated with @jax.jit / @functools.partial(jax.jit, ...), or
    passed by name to jax.jit anywhere in the module.  Memoized on the
    ModuleInfo: rules (PHT002/004) and flow (PHT007) both need it, and
    the scan walks every function — computing it twice per module was
    a measurable slice of the repo-wide walk."""
    memo = getattr(mi, "_jit_targets_memo", None)
    if memo is not None:
        return memo
    out: Dict[str, Set[int]] = {}

    def _deco_statics(dec) -> Optional[Set[int]]:
        if isinstance(dec, ast.Call):
            d = _call_dotted(mi, dec)
            if d in _JIT_NAMES:
                return _static_positions(dec) or set()
            if d in ("functools.partial",) and dec.args \
                    and mi.resolve_dotted(dec.args[0]) in _JIT_NAMES:
                return _static_positions(dec) or set()
        elif mi.resolve_dotted(dec) in _JIT_NAMES:
            return set()
        return None

    for qual, fi in mi.funcs.items():
        for dec in getattr(fi.node, "decorator_list", []):
            s = _deco_statics(dec)
            if s is not None:
                out[qual] = s

    # jax.jit(f, ...) with f a plain name: resolve through the SAME
    # scope rules as any other bare call — nearest enclosing scope for
    # calls inside a function, module level otherwise.  (A suffix match
    # over all qualnames marked every same-named method as jitted,
    # false-firing PHT002/PHT004 on never-jitted host code.)
    def _attribute(targets: Set[str], statics: Set[int]):
        for tq in targets:
            out.setdefault(tq, set()).update(statics)

    for fi in mi.funcs.values():
        for ref in fi.calls:
            node = ref.node
            if _is_jit_ctor(mi, node) and node.args \
                    and isinstance(node.args[0], ast.Name):
                _attribute(
                    resolve_same_module(
                        mi, fi, CallRef("bare", node.args[0].id, node)),
                    _static_positions(node) or set())

    class _TopLevelCalls(ast.NodeVisitor):
        """Module-level jit calls only (function bodies are handled
        above, with their enclosing scope)."""

        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            pass

        def visit_Call(self, node):
            if _is_jit_ctor(mi, node) and node.args \
                    and isinstance(node.args[0], ast.Name):
                name = node.args[0].id
                if name in mi.funcs:
                    _attribute({name}, _static_positions(node) or set())
            self.generic_visit(node)

    _TopLevelCalls().visit(mi.tree)
    mi._jit_targets_memo = out
    return out


def _traced_params(fi: FuncInfo, statics: Set[int]) -> Set[str]:
    args = getattr(fi.node, "args", None)
    if args is None:
        return set()
    names = [a.arg for a in args.posonlyargs + args.args]
    return {n for i, n in enumerate(names)
            if i not in statics and n not in ("self", "cls")}


class _TracedBranchWalker(ast.NodeVisitor):
    def __init__(self, mi, fi, params, findings):
        self.mi, self.fi = mi, fi
        self.params = params
        self.findings = findings

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def _shielded(self, e: ast.expr) -> Set[int]:
        """ids of Name nodes under shape/ndim/dtype/size/len shields."""
        out: Set[int] = set()
        for n in ast.walk(e):
            shield = None
            if isinstance(n, ast.Attribute) and n.attr in (
                    "shape", "ndim", "dtype", "size"):
                shield = n
            elif isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                    and n.func.id in ("len", "isinstance", "getattr",
                                      "hasattr", "type"):
                shield = n
            if shield is not None:
                for sub in ast.walk(shield):
                    out.add(id(sub))
        return out

    def _check(self, test: ast.expr):
        shielded = self._shielded(test)
        for n in ast.walk(test):
            if isinstance(n, ast.Name) and n.id in self.params \
                    and id(n) not in shielded:
                self.findings.append(Finding(
                    rule="PHT002", file=self.mi.relpath, line=test.lineno,
                    func=self.fi.qualname,
                    message=f"Python branch on traced parameter "
                            f"`{n.id}` inside a jitted body — "
                            "concretizes the tracer (error) or bakes "
                            "one trace-time outcome in forever",
                    hint="use jnp.where / jax.lax.cond, or mark the "
                         "argument static if it is host config"))
                return

    def visit_If(self, node):
        self._check(node.test)
        self.generic_visit(node)

    def visit_While(self, node):
        self._check(node.test)
        self.generic_visit(node)

    def run(self):
        for stmt in getattr(self.fi.node, "body", []):
            self.visit(stmt)


def _nondet_calls(mi: ModuleInfo, fi: FuncInfo,
                  findings: List[Finding]):
    # own body only: a nested def is its own FuncInfo (reported under
    # its own func name if reachable — walking into it here duplicated
    # every finding under two func names, and a nested def that is
    # never called never executes at trace time anyway).  Lambdas DO
    # stay in scope: they are not FuncInfo entries, and a staged
    # `lambda: time.time()` freezes exactly like inline code.
    calls: List[ast.Call] = []

    def collect(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(child, ast.Call):
                calls.append(child)
            collect(child)

    collect(fi.node)
    for node in calls:
        d = _call_dotted(mi, node)
        if d is None:
            continue
        root = d.split(".")[0]
        # import_resolves distinguishes the resolved time.time /
        # random.random (direct, aliased, or from-imported) from a
        # LOCAL variable that merely shadows the name
        if (root in _NONDET_ROOTS and mi.import_resolves(root)) \
                or d.startswith(_NONDET_PREFIXES):
            findings.append(Finding(
                rule="PHT004", file=mi.relpath, line=node.lineno,
                func=fi.qualname,
                message=f"{d}() inside a jitted body is evaluated ONCE "
                        "at trace time — every later call replays the "
                        "frozen value (nondeterminism you can't see)",
                hint="pass timestamps/seeds in as arguments; use "
                     "jax.random with an explicit key for randomness"))


# --------------------------------------------------------------------------
# PHT005: unbounded metric-label cardinality
# --------------------------------------------------------------------------

# identifier/attribute names that are per-request/per-occurrence by
# convention — a label fed from one of these mints a series per request
_ID_NAMES = frozenset((
    "rid", "request_id", "req_id", "uid", "uuid", "session_id",
    "trace_id", "span_id",
))


def _bounded_iterable(mi: ModuleInfo, it: ast.expr) -> bool:
    """Provably-bounded iterables: literal containers, constants, and
    ``range``/``enumerate`` over them with constant arguments.  A
    ``range(self.num_x)`` is NOT provably bounded — flag it and let the
    baseline carry the justification when the bound is real (the
    workflow for every conservative rule here)."""
    if isinstance(it, (ast.Tuple, ast.List, ast.Set, ast.Dict,
                       ast.Constant)):
        return True
    if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
            and it.func.id not in mi.imports:
        if it.func.id == "range":
            return all(isinstance(a, ast.Constant) for a in it.args)
        if it.func.id in ("enumerate", "sorted", "reversed", "zip"):
            return all(_bounded_iterable(mi, a) for a in it.args)
    return False


def _target_names(target: ast.expr) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(target):
        if isinstance(n, ast.Name):
            out.add(n.id)
    return out


class _LabelCardinalityWalker:
    """PHT005 over ONE function body (nested defs are their own
    FuncInfo entries): collect names that count without bound —
    for/comprehension targets over non-provably-bounded iterables,
    loop-carried ``i += 1`` counters, names assigned from ``next(...)``
    — then flag ``.labels(...)`` keyword values mentioning one of them
    or a request-id-ish name.  ``**splat`` kwargs are skipped
    (conservative: can only MISS, never false-positive on the shared
    per-instance label dict idiom)."""

    def __init__(self, mi: ModuleInfo, fi: FuncInfo,
                 findings: List[Finding]):
        self.mi = mi
        self.fi = fi
        self.findings = findings
        self.unbounded: Set[str] = set()

    def _own_nodes(self):
        """Child-first walk of the function body, skipping nested
        defs (linted under their own FuncInfo)."""
        out = []

        def collect(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue
                out.append(child)
                collect(child)

        collect(self.fi.node)
        return out

    def _collect_unbounded(self, nodes):
        for node in nodes:
            if isinstance(node, (ast.For, ast.AsyncFor)):
                if not _bounded_iterable(self.mi, node.iter):
                    self.unbounded |= _target_names(node.target)
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.DictComp, ast.GeneratorExp)):
                for comp in node.generators:
                    if not _bounded_iterable(self.mi, comp.iter):
                        self.unbounded |= _target_names(comp.target)
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Name) \
                    and node.value.func.id == "next" \
                    and node.value.func.id not in self.mi.imports:
                # x = next(counter): a fresh value per call
                for t in node.targets:
                    self.unbounded |= _target_names(t)
        # loop-carried counters: `i += <const>` anywhere under a loop
        for node in nodes:
            if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.AugAssign) \
                            and isinstance(sub.target, ast.Name) \
                            and isinstance(sub.value, ast.Constant):
                        self.unbounded.add(sub.target.id)

    def _suspect(self, value: ast.expr):
        """(kind, name) when the label-value expression mentions an
        unbounded name or a request-id-ish identifier, else None."""
        for n in ast.walk(value):
            if isinstance(n, ast.Name):
                if n.id in _ID_NAMES:
                    return ("a request-id-shaped name", n.id)
                if n.id in self.unbounded:
                    return ("an unbounded loop index/counter", n.id)
            elif isinstance(n, ast.Attribute) and n.attr in _ID_NAMES:
                return ("a request-id-shaped attribute", n.attr)
        return None

    def run(self):
        # early exit: no `.labels(...)` call recorded in this function
        # means nothing to check — skip the (walk-heavy) unbounded-name
        # collection entirely (most functions, most modules)
        if not any(ref.name == "labels" for ref in self.fi.calls):
            return
        nodes = self._own_nodes()
        self._collect_unbounded(nodes)
        for node in nodes:
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "labels"):
                continue
            for kw in node.keywords:
                if kw.arg is None:
                    continue   # **splat: skipped (see class docstring)
                hit = self._suspect(kw.value)
                if hit is None:
                    continue
                what, name = hit
                self.findings.append(Finding(
                    rule="PHT005", file=self.mi.relpath,
                    line=node.lineno, func=self.fi.qualname,
                    message=f"metric label `{kw.arg}` takes a value "
                            f"derived from {what} (`{name}`) — every "
                            "new value mints a fresh time series, "
                            "growing the registry and every scrape "
                            "without bound",
                    hint="keep labels a bounded enum (mode/flavor/"
                         "phase/engine); per-request ids belong in "
                         "spans, the flight recorder, or the request "
                         "lifecycle record — and per-instance labels "
                         "must drop on teardown "
                         "(registry.drop_labels)"))


# --------------------------------------------------------------------------
# per-module driver (PHT001/002/004/005)
# --------------------------------------------------------------------------

def lint_module(mi: ModuleInfo) -> List[Finding]:
    findings: List[Finding] = []
    hot = hot_set(mi)
    jit_bindings = _collect_jit_bindings(mi)
    for qual, fi in mi.funcs.items():
        if isinstance(fi.node, ast.Lambda):
            continue
        _FuncWalker(mi, fi, qual in hot, jit_bindings, findings).run()
        # PHT005 applies everywhere, not just hot paths: registry growth
        # from an init-time loop is just as unbounded as from a tick
        _LabelCardinalityWalker(mi, fi, findings).run()

    targets = _jit_targets(mi)
    # PHT004 scope: jitted bodies plus same-module functions they reach
    nondet_scope: Set[str] = set()
    work = list(targets)
    while work:
        q = work.pop()
        if q in nondet_scope or q not in mi.funcs:
            continue
        nondet_scope.add(q)
        fi = mi.funcs[q]
        for ref in fi.calls:
            for tgt in resolve_same_module(mi, fi, ref):
                work.append(tgt)
    for qual, statics in targets.items():
        fi = mi.funcs.get(qual)
        if fi is None:
            continue
        _TracedBranchWalker(mi, fi, _traced_params(fi, statics),
                            findings).run()
    for qual in nondet_scope:
        _nondet_calls(mi, mi.funcs[qual], findings)
    return findings


# --------------------------------------------------------------------------
# PHT003: cross-module lock discipline
# --------------------------------------------------------------------------

# Method names so ubiquitous on stdlib objects (dict/list/set/queue/
# threading/futures) that receiver-unknown resolution through the
# project method-name index is noise, not signal: `self.cv.wait()`
# must not resolve to some project class's `wait`.  Distinctive project
# names (ingest, propose, tick, …) stay resolvable.  Conservative in
# the lint direction: a skipped name can only MISS a finding.
_COMMON_METHOD_NAMES = frozenset((
    "add", "append", "clear", "close", "copy", "count", "dec", "discard",
    "done", "extend", "flush", "get", "inc", "index", "insert", "items",
    "join", "keys", "next", "notify", "notify_all", "pop", "popleft",
    "put", "read", "recv", "release", "remove", "reset", "result", "run",
    "send", "set", "sort", "start", "submit", "update", "values", "wait",
    "write",
))


class _LockAnalysis:
    def __init__(self, modules: List[ModuleInfo]):
        self.modules = modules
        self.by_dotted = {m.dotted: m for m in modules}
        # method-name index over project classes (receiver-unknown calls)
        self.methods: Dict[str, List[Tuple[ModuleInfo, FuncInfo]]] = {}
        for m in modules:
            for qual, fi in m.funcs.items():
                if fi.class_name and qual.count(".") == 1:
                    self.methods.setdefault(
                        qual.split(".")[1], []).append((m, fi))
        self._acquires_memo: Dict[Tuple[str, str], Set[str]] = {}
        self._dispatch_memo: Dict[Tuple[str, str], bool] = {}

    # -- resolution --------------------------------------------------------
    def resolve(self, mi: ModuleInfo, fi: FuncInfo,
                ref: CallRef) -> List[Tuple[ModuleInfo, FuncInfo]]:
        out = [(mi, mi.funcs[q])
               for q in resolve_same_module(mi, fi, ref)]
        if out:
            return out
        if ref.kind == "dotted":
            # project module function: longest module prefix match
            parts = ref.name.split(".")
            for cut in range(len(parts) - 1, 0, -1):
                mod = ".".join(parts[:cut])
                m2 = self.by_dotted.get(mod)
                if m2 is not None:
                    qual = ".".join(parts[cut:])
                    fi2 = m2.funcs.get(qual)
                    if fi2 is not None:
                        return [(m2, fi2)]
                    return []
            return []
        if ref.kind in ("method", "self"):
            if ref.name in _COMMON_METHOD_NAMES:
                return []
            return self.methods.get(ref.name, [])
        return []

    # -- lock refs ---------------------------------------------------------
    def lock_of(self, mi: ModuleInfo, fi: FuncInfo,
                expr: ast.expr) -> Optional[str]:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and fi.class_name:
            key = f"{fi.class_name}.{expr.attr}"
            ld = mi.locks.get(key)
            return ld.lock_id if ld else None
        if isinstance(expr, ast.Name):
            ld = mi.locks.get(expr.id)
            return ld.lock_id if ld else None
        return None

    # -- transitive lock acquisition ---------------------------------------
    # No depth cap on either walk: memoization already bounds the work
    # to one computation per function (a cap would force truncated
    # results into the memo, and an unrelated deep call chain reaching a
    # function FIRST would permanently blind later shallow queries —
    # hiding real cycles depending on definition order).  The empty-set
    # placeholder is the recursion cycle guard; mutually recursive
    # functions under-approximate across the back edge, which can only
    # MISS, never false-positive.
    def acquires(self, mi: ModuleInfo, fi: FuncInfo) -> Set[str]:
        key = (mi.dotted, fi.qualname)
        if key in self._acquires_memo:
            return self._acquires_memo[key]
        self._acquires_memo[key] = set()   # cycle guard
        out: Set[str] = set()
        for node in ast.walk(fi.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    lk = self.lock_of(mi, fi, item.context_expr)
                    if lk:
                        out.add(lk)
        for ref in fi.calls:
            for m2, f2 in self.resolve(mi, fi, ref):
                out |= self.acquires(m2, f2)
        self._acquires_memo[key] = out
        return out

    # -- device dispatch reachability --------------------------------------
    def dispatches(self, mi: ModuleInfo, fi: FuncInfo) -> bool:
        key = (mi.dotted, fi.qualname)
        if key in self._dispatch_memo:
            return self._dispatch_memo[key]
        self._dispatch_memo[key] = False   # cycle guard
        out = False
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                d = _call_dotted(mi, node)
                if _is_device_call(d) or d in _SYNC_EXACT:
                    out = True
                    break
        if not out:
            for ref in fi.calls:
                for m2, f2 in self.resolve(mi, fi, ref):
                    if self.dispatches(m2, f2):
                        out = True
                        break
                if out:
                    break
        self._dispatch_memo[key] = out
        return out

    # -- the walk ----------------------------------------------------------
    def run(self) -> List[Finding]:
        findings: List[Finding] = []
        # edge -> first site (file, line, holder func)
        edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        dispatch_sites: List[Finding] = []

        for mi in self.modules:
            for fi in mi.funcs.values():
                self._walk_func(mi, fi, edges, dispatch_sites)

        findings.extend(dispatch_sites)

        # cycle detection on the order graph
        graph: Dict[str, Set[str]] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
        reported: Set[frozenset] = set()
        for a, b in sorted(edges):
            if a == b:
                cyc = frozenset((a,))
                if cyc not in reported:
                    reported.add(cyc)
                    f, ln, fn = edges[(a, b)]
                    findings.append(Finding(
                        rule="PHT003", file=f, line=ln, func=fn,
                        message=f"lock `{a}` acquired while an instance "
                                "of the same lock class is already held "
                                "— two threads nesting opposite "
                                "instances deadlock",
                        hint="impose a total order on instances, or "
                             "restructure so one is released first"))
                continue
            path = self._find_path(graph, b, a)
            if path is not None:
                cyc = frozenset(path)
                if cyc in reported:
                    continue
                reported.add(cyc)
                f, ln, fn = edges[(a, b)]
                chain = " -> ".join(path + [path[0]])
                findings.append(Finding(
                    rule="PHT003", file=f, line=ln, func=fn,
                    message=f"lock-order cycle: `{a}` -> `{b}` here, but "
                            f"the reverse path exists ({chain}) — "
                            "opposing acquisition orders deadlock under "
                            "contention",
                    hint="acquire in one global order everywhere, or "
                         "drop to snapshot-then-call (copy under one "
                         "lock, call outside it)"))
        return findings

    def _find_path(self, graph, src, dst) -> Optional[List[str]]:
        seen = set()
        stack = [(src, [src])]
        while stack:
            cur, path = stack.pop()
            if cur == dst:
                return path
            if cur in seen:
                continue
            seen.add(cur)
            for nxt in graph.get(cur, ()):
                stack.append((nxt, path + [nxt]))
        return None

    def _walk_func(self, mi, fi, edges, dispatch_sites):
        calls_by_id = {id(ref.node): ref for ref in fi.calls}
        flagged: Set[Tuple[str, str]] = set()

        def walk(node, held: List[str]):
            if isinstance(node, ast.With):
                lks = [self.lock_of(mi, fi, it.context_expr)
                       for it in node.items]
                lks = [lk for lk in lks if lk]
                # `with A, B:` acquires left-to-right: earlier items are
                # HELD when later ones are taken, so they order-edge
                # exactly like the enclosing held list
                for idx, lk in enumerate(lks):
                    for h in held + lks[:idx]:
                        edges.setdefault(
                            (h, lk), (mi.relpath, node.lineno,
                                      fi.qualname))
                inner = held + lks
                for s in node.body:
                    walk(s, inner)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            if isinstance(node, ast.Call) and held:
                self._check_call_under_lock(
                    mi, fi, node, held, calls_by_id, edges, flagged,
                    dispatch_sites)
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        # start from the body statements — walk()'s nested-def guard
        # must not short-circuit on the root FunctionDef itself
        for stmt in getattr(fi.node, "body", []):
            walk(stmt, [])

    def _check_call_under_lock(self, mi, fi, node, held, calls_by_id,
                               edges, flagged, dispatch_sites):
        d = _call_dotted(mi, node)
        direct_sync = (d in _SYNC_EXACT
                       or (isinstance(node.func, ast.Attribute)
                           and node.func.attr in ("item",
                                                  "block_until_ready")))
        direct_dispatch = _is_device_call(d)
        reason = None
        if direct_sync:
            reason = f"host sync `{d or node.func.attr}`"
        elif direct_dispatch:
            reason = f"device dispatch `{d}`"
        else:
            ref = calls_by_id.get(id(node))
            if ref is not None:
                for m2, f2 in self.resolve(mi, fi, ref):
                    for lk in self.acquires(m2, f2):
                        for h in held:
                            edges.setdefault(
                                (h, lk),
                                (mi.relpath, node.lineno, fi.qualname))
                    if self.dispatches(m2, f2):
                        reason = (f"call into {m2.dotted}."
                                  f"{f2.qualname} which dispatches "
                                  "device work")
        if reason:
            key = (held[-1], reason)
            if key not in flagged:
                flagged.add(key)
                dispatch_sites.append(Finding(
                    rule="PHT003", file=mi.relpath, line=node.lineno,
                    func=fi.qualname,
                    message=f"lock `{held[-1]}` held across {reason} — "
                            "every thread contending this lock stalls "
                            "behind the device",
                    hint="stage under the lock, dispatch outside it "
                         "(the ServingEngine.step stage/commit "
                         "pattern)"))


def lint_locks(modules: List[ModuleInfo]) -> List[Finding]:
    return _LockAnalysis(modules).run()
