"""pht-lint host-side data-race rules PHT009/PHT010 (catalog:
docs/STATIC_ANALYSIS.md; runtime half: observability/sanitizers.py
``race_sanitizer``).

PHT009  unguarded-shared-state — per class, each attribute's guarded-by
        discipline is INFERRED: an attribute written at least once under
        a recognized lock (``with self._lock:`` — the PHT003 lock model:
        ``threading.Lock/RLock/Condition`` ctors and the sanitizer's
        ``make_lock``/``make_rlock``) is lock-guarded.  Reads or writes
        of a guarded attribute with NO lock held, in a function
        reachable from a thread entry point via a call path that holds
        no lock, are data races the GIL does not excuse (check-then-act
        on dict/queue state, torn multi-attribute invariants).  Thread
        entry points: ``threading.Thread(target=...)`` targets,
        ``executor.submit(fn)`` callables, ``do_GET``-style HTTP handler
        methods, and ``run`` methods of ``threading.Thread`` subclasses.
        Allowlist: an access whose line (or the line above) carries
        ``# pht-lint: gil-atomic`` — the single-aligned-read /
        single-``+=``-bump contract for counters the lock-free metrics
        hot path relies on (the annotation is a CLAIM the reviewer can
        grep; the runtime sanitizer's ``atomic=`` mirrors it).

PHT010  check-then-act — a local assigned under a lock from an
        expression reading a guarded attribute (admission headroom, a
        free-slot test, a queue-empty probe) that is used as an
        ``if``/``while`` condition AFTER the lock was released, where
        the taken branch then ACTS (writes a guarded attribute, or
        calls a method that takes a lock).  Between release and act the
        state may change — the TOCTOU shape a least-loaded router
        dispatch is full of.  Pure snapshot-and-report (no act) stays
        clean: that is the designed /load pattern.

Same design rules as rules.py/flow.py: pure stdlib ``ast``,
conservative resolution — a shape we cannot prove is NOT flagged
(misses are acceptable, false positives are not).  Both rules are
per-module: cross-module thread entries (an engine method called from
the HTTP server's handler thread) need the entry module's own analysis.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CallRef, FuncInfo, ModuleInfo, dotted_of, \
    resolve_same_module
from .rules import Finding

GIL_ATOMIC_MARK = "pht-lint: gil-atomic"

# container-mutator method names: `self.x.append(v)` under a lock is a
# guard-establishing WRITE to x's state, same as `self.x = v`
_MUTATOR_METHODS = frozenset((
    "append", "appendleft", "add", "extend", "insert", "update",
    "setdefault", "pop", "popleft", "popitem", "clear", "remove",
    "discard", "put", "put_nowait",
))

_HTTP_HANDLER_METHODS = frozenset((
    "do_GET", "do_POST", "do_PUT", "do_DELETE", "do_HEAD", "do_PATCH",
))

# with-context names treated as lock acquisitions even when the lock
# object lives on another instance (`with self.inner.cv:`): the final
# path segment either matches a lock DEFINED in this module, or follows
# the naming convention.  Misclassifying a non-lock context manager as a
# lock can only MISS findings — the safe direction.
_LOCK_NAME_HINTS = ("lock", "cv", "cond", "mutex")


def _gil_atomic(mi: ModuleInfo, lineno: int) -> bool:
    return (GIL_ATOMIC_MARK in mi.source_line(lineno)
            or GIL_ATOMIC_MARK in mi.source_line(lineno - 1))


def _lock_attr_names(mi: ModuleInfo) -> Set[str]:
    """Final-segment names of every lock this module defines (the
    PHT003 model: threading ctors + make_lock/make_rlock), for
    recognizing ``with self.<name>:`` / ``with self.other.<name>:``."""
    out: Set[str] = set()
    for key in mi.locks:
        out.add(key.rsplit(".", 1)[-1])
    return out


def _is_lock_ctx(mi: ModuleInfo, expr: ast.expr,
                 lock_names: Set[str]) -> Optional[str]:
    """Lock display name when a with-item context expression is a lock
    acquisition, else None."""
    # `with self._lock:` / `with _module_lock:` / `with self.inner.cv:`
    d = dotted_of(expr)
    if d is None:
        # `with self._lock_for(i):`-style calls: not recognized (miss)
        return None
    tail = d.rsplit(".", 1)[-1]
    if tail in lock_names:
        return d
    low = tail.lower()
    if any(h in low for h in _LOCK_NAME_HINTS):
        return d
    return None


# --------------------------------------------------------------------------
# guarded-by inference
# --------------------------------------------------------------------------

def _store_attr_root(target: ast.expr) -> Optional[str]:
    """Attribute name X when ``target`` writes through ``self.X`` (the
    binding itself, a subscript of it, or a deeper path under it)."""
    e = target
    while isinstance(e, (ast.Subscript, ast.Attribute)):
        if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name) \
                and e.value.id == "self":
            return e.attr
        e = e.value
    return None


class _GuardInference(ast.NodeVisitor):
    """One pass over a function body: record self-attribute writes made
    while a recognized lock is held.  ``guarded[cls][attr] = lock``."""

    def __init__(self, mi: ModuleInfo, fi: FuncInfo, lock_names: Set[str],
                 guarded: Dict[str, Dict[str, str]]):
        self.mi = mi
        self.fi = fi
        self.lock_names = lock_names
        self.guarded = guarded
        self.held: List[str] = []

    def run(self):
        if self.fi.class_name is None:
            return
        for stmt in getattr(self.fi.node, "body", []):
            self.visit(stmt)

    def visit_FunctionDef(self, node):   # nested defs: their own FuncInfo
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    def visit_With(self, node: ast.With):
        lks = [_is_lock_ctx(self.mi, it.context_expr, self.lock_names)
               for it in node.items]
        lks = [lk for lk in lks if lk]
        self.held.extend(lks)
        for s in node.body:
            self.visit(s)
        if lks:
            del self.held[-len(lks):]

    visit_AsyncWith = visit_With

    def _mark(self, attr: str):
        if self.held and attr:
            cls = self.fi.class_name
            self.guarded.setdefault(cls, {}).setdefault(
                attr, self.held[-1])

    def visit_Assign(self, node: ast.Assign):
        if self.held:
            for t in node.targets:
                for e in ast.walk(t) if isinstance(
                        t, (ast.Tuple, ast.List, ast.Starred)) else [t]:
                    root = _store_attr_root(e)
                    if root:
                        self._mark(root)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        if self.held:
            root = _store_attr_root(node.target)
            if root:
                self._mark(root)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        # `self.x.append(v)` under the lock: a write to x's contents
        if self.held and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATOR_METHODS:
            root = _store_attr_root(node.func.value)
            if root:
                self._mark(root)
        self.generic_visit(node)


def infer_guarded(mi: ModuleInfo,
                  lock_names: Set[str]) -> Dict[str, Dict[str, str]]:
    guarded: Dict[str, Dict[str, str]] = {}
    for fi in mi.funcs.values():
        if not isinstance(fi.node, ast.Lambda):
            _GuardInference(mi, fi, lock_names, guarded).run()
    # lock attributes themselves are never "guarded data" (reading
    # self._lock to acquire it is the discipline, not a race)
    for cls, attrs in guarded.items():
        for key in [a for a in attrs
                    if f"{cls}.{a}" in mi.locks or a in lock_names]:
            del attrs[key]
    return guarded


# --------------------------------------------------------------------------
# thread entry points
# --------------------------------------------------------------------------

def _resolve_callable(mi: ModuleInfo, fi: FuncInfo,
                      expr: ast.expr) -> Set[str]:
    """Qualnames a callable-valued expression may name (same module)."""
    if isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name) and expr.value.id == "self":
        return resolve_same_module(
            mi, fi, CallRef("self", expr.attr, None))
    if isinstance(expr, ast.Name):
        return resolve_same_module(
            mi, fi, CallRef("bare", expr.id, None))
    return set()


def thread_entries(mi: ModuleInfo) -> Dict[str, str]:
    """qualname -> how it becomes a thread entry."""
    out: Dict[str, str] = {}

    def _add(quals: Set[str], why: str):
        for q in quals:
            out.setdefault(q, why)

    for fi in mi.funcs.values():
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            d = mi.resolve_dotted(node.func) or ""
            if d == "threading.Thread" or d.endswith(".Thread") \
                    and d.startswith("threading"):
                target = None
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = kw.value
                if target is None and len(node.args) >= 2:
                    target = node.args[1]   # Thread(group, target)
                if target is not None:
                    _add(_resolve_callable(mi, fi, target),
                         "threading.Thread(target=...)")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "submit" and node.args:
                # pool.submit(fn, ...): only flag when the first arg
                # provably names a same-module function (an engine's
                # submit(prompt) request API never resolves)
                _add(_resolve_callable(mi, fi, node.args[0]),
                     "executor.submit(...)")

    for qual, fi in mi.funcs.items():
        if qual.rsplit(".", 1)[-1] in _HTTP_HANDLER_METHODS \
                and fi.class_name is not None:
            out.setdefault(qual, "HTTP handler thread")

    # threading.Thread subclasses: run() is the entry
    for node in ast.walk(mi.tree):
        if isinstance(node, ast.ClassDef):
            for base in node.bases:
                if mi.resolve_dotted(base) == "threading.Thread":
                    q = f"{node.name}.run"
                    if q in mi.funcs:
                        out.setdefault(q, "threading.Thread subclass run()")
    return out


# --------------------------------------------------------------------------
# lock-free reachability from entries
# --------------------------------------------------------------------------

class _LockFreeCallCollector(ast.NodeVisitor):
    """Call nodes (and nested def names) that execute with NO recognized
    lock held, in one function body."""

    def __init__(self, mi: ModuleInfo, lock_names: Set[str]):
        self.mi = mi
        self.lock_names = lock_names
        self.held = 0
        self.calls: List[ast.Call] = []
        self.nested_defs: List[str] = []

    def visit_With(self, node: ast.With):
        lks = [lk for it in node.items
               if (lk := _is_lock_ctx(self.mi, it.context_expr,
                                      self.lock_names))]
        for it in node.items:
            self.visit(it.context_expr)
        self.held += len(lks)
        for s in node.body:
            self.visit(s)
        self.held -= len(lks)

    visit_AsyncWith = visit_With

    def visit_FunctionDef(self, node):
        if self.held == 0:
            self.nested_defs.append(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    def visit_Call(self, node: ast.Call):
        if self.held == 0:
            self.calls.append(node)
        self.generic_visit(node)


def lockfree_reachable(mi: ModuleInfo, entries: Dict[str, str],
                       lock_names: Set[str]) -> Dict[str, str]:
    """Functions reachable from a thread entry via call paths holding no
    lock: qualname -> the entry's description (first to reach)."""
    reached: Dict[str, str] = {}
    work = [(q, why) for q, why in entries.items() if q in mi.funcs]
    while work:
        q, why = work.pop()
        if q in reached:
            continue
        reached[q] = why
        fi = mi.funcs[q]
        col = _LockFreeCallCollector(mi, lock_names)
        for stmt in getattr(fi.node, "body", []):
            col.visit(stmt)
        by_id = {id(ref.node): ref for ref in fi.calls}
        for call in col.calls:
            ref = by_id.get(id(call))
            if ref is None:
                continue
            for tgt in resolve_same_module(mi, fi, ref):
                if tgt not in reached:
                    work.append((tgt, why))
        # nested defs declared outside any lock run in the entry's
        # dynamic extent (worker-body closures)
        for name in col.nested_defs:
            cand = f"{q}.{name}"
            if cand in mi.funcs and cand not in reached:
                work.append((cand, why))
    return reached


# --------------------------------------------------------------------------
# PHT009 flag pass
# --------------------------------------------------------------------------

class _UnguardedAccessWalker(ast.NodeVisitor):
    def __init__(self, mi: ModuleInfo, fi: FuncInfo, entry_why: str,
                 guarded: Dict[str, str], lock_names: Set[str],
                 findings: List[Finding]):
        self.mi = mi
        self.fi = fi
        self.entry_why = entry_why
        self.guarded = guarded
        self.lock_names = lock_names
        self.findings = findings
        self.held = 0
        self._seen: Set[str] = set()

    def run(self):
        for stmt in getattr(self.fi.node, "body", []):
            self.visit(stmt)

    def visit_With(self, node: ast.With):
        lks = [lk for it in node.items
               if (lk := _is_lock_ctx(self.mi, it.context_expr,
                                      self.lock_names))]
        for it in node.items:
            self.visit(it.context_expr)
        self.held += len(lks)
        for s in node.body:
            self.visit(s)
        self.held -= len(lks)

    visit_AsyncWith = visit_With

    def visit_FunctionDef(self, node):   # own FuncInfo, walked if reached
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    def visit_Attribute(self, node: ast.Attribute):
        if self.held == 0 and isinstance(node.value, ast.Name) \
                and node.value.id == "self" \
                and node.attr in self.guarded \
                and node.attr not in self._seen \
                and not _gil_atomic(self.mi, node.lineno):
            self._seen.add(node.attr)
            kind = "written" if isinstance(
                node.ctx, (ast.Store, ast.Del)) else "read"
            lock = self.guarded[node.attr]
            self.findings.append(Finding(
                rule="PHT009", file=self.mi.relpath, line=node.lineno,
                func=self.fi.qualname,
                message=f"`self.{node.attr}` is written under "
                        f"`{lock}` elsewhere in this class "
                        f"(guarded-by inference) but {kind} here with "
                        f"NO lock held — and this function is reachable "
                        f"from a thread entry ({self.entry_why}) on a "
                        "lock-free path: a concurrent locked writer "
                        "makes this a data race (torn invariants, "
                        "check-then-act on stale state)",
                hint=f"take {lock} around the access, or — for a "
                     "single GIL-atomic read / `+=` counter bump — "
                     "annotate the line `# pht-lint: gil-atomic` and "
                     "mirror it in the runtime sanitizer's `atomic=` "
                     "list (docs/STATIC_ANALYSIS.md, PHT009)"))
        self.generic_visit(node)


# --------------------------------------------------------------------------
# PHT010 check-then-act
# --------------------------------------------------------------------------

def _attr_reads_of_self(expr: ast.expr, guarded: Dict[str, str]) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(expr):
        if isinstance(n, ast.Attribute) and isinstance(
                n.value, ast.Name) and n.value.id == "self" \
                and n.attr in guarded:
            out.add(n.attr)
    return out


def _guard_touchers(mi: ModuleInfo,
                    guarded: Dict[str, Dict[str, str]]) -> Set[str]:
    """Qualnames of methods that read or write any of their own class's
    guarded attributes.  The PHT010 'act' criterion intersects this
    with the locking methods: a helper that merely takes an UNRELATED
    lock (a metrics bump under the registry lock) is not an act on the
    checked state — flagging it false-positived the documented-clean
    snapshot-and-report shape."""
    out: Set[str] = set()
    for qual, fi in mi.funcs.items():
        cls = fi.class_name
        attrs = guarded.get(cls or "", {})
        if not attrs:
            continue
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Attribute) \
                    and isinstance(n.value, ast.Name) \
                    and n.value.id == "self" and n.attr in attrs:
                out.add(qual)
                break
    return out


def _locking_methods(mi: ModuleInfo, lock_names: Set[str]) -> Set[str]:
    """Qualnames whose bodies acquire a recognized lock, closed over
    same-module calls (2 hops is plenty for the repo's idioms)."""
    direct: Set[str] = set()
    for qual, fi in mi.funcs.items():
        for n in ast.walk(fi.node):
            if isinstance(n, (ast.With, ast.AsyncWith)):
                if any(_is_lock_ctx(mi, it.context_expr, lock_names)
                       for it in n.items):
                    direct.add(qual)
                    break
    out = set(direct)
    for _ in range(2):
        grew = False
        for qual, fi in mi.funcs.items():
            if qual in out:
                continue
            for ref in fi.calls:
                if resolve_same_module(mi, fi, ref) & out:
                    out.add(qual)
                    grew = True
                    break
        if not grew:
            break
    return out


class _CheckThenActWalker:
    """One function: find `with lock: v = <reads guarded attr>` followed
    (after the with closes) by `if v:` / `while v:` whose branch acts."""

    def __init__(self, mi: ModuleInfo, fi: FuncInfo,
                 guarded: Dict[str, str], lock_names: Set[str],
                 acting: Set[str], findings: List[Finding]):
        self.mi = mi
        self.fi = fi
        self.guarded = guarded
        self.lock_names = lock_names
        # methods that BOTH take a lock and touch guarded state — the
        # only calls that count as acting on the checked decision
        self.acting = acting
        self.findings = findings

    def run(self):
        self._walk_list(getattr(self.fi.node, "body", []), {})

    @staticmethod
    def _kill_bound(stmt, decisions) -> None:
        """Drop decisions whose name this statement REBINDS — plain and
        tuple-unpack assigns, aug-assigns, for-loop targets, `with ...
        as x` — so a recycled name never flags as a stale decision (the
        no-false-positives contract)."""
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, ast.AugAssign):
            targets = [stmt.target]
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            targets = [stmt.target]
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            targets = [it.optional_vars for it in stmt.items
                       if it.optional_vars is not None]
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    decisions.pop(n.id, None)

    # decisions: name -> (attr, with_lineno, lock_name)
    def _walk_list(self, stmts, decisions: Dict[str, Tuple[str, int, str]]):
        for stmt in stmts:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._kill_bound(stmt, decisions)
                lks = [lk for it in stmt.items
                       if (lk := _is_lock_ctx(self.mi, it.context_expr,
                                              self.lock_names))]
                if lks:
                    self._collect_decisions(stmt, decisions, lks[-1])
                else:
                    self._walk_list(stmt.body, decisions)
                continue
            self._kill_bound(stmt, decisions)
            if isinstance(stmt, (ast.If, ast.While)):
                self._check_test(stmt, decisions)
                # branches see (and may add/kill) the same decisions
                self._walk_list(stmt.body, decisions)
                self._walk_list(stmt.orelse, decisions)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._walk_list(stmt.body, decisions)
                self._walk_list(stmt.orelse, decisions)
                continue
            if isinstance(stmt, ast.Try):
                self._walk_list(stmt.body, decisions)
                for h in stmt.handlers:
                    self._walk_list(h.body, decisions)
                self._walk_list(stmt.orelse, decisions)
                self._walk_list(stmt.finalbody, decisions)
                continue

    def _collect_decisions(self, with_node, decisions, lock_name):
        """Walk a locked region: every rebind kills (via _kill_bound —
        tuple unpacks, for-targets, with-as included, so a later-lock
        rebind of the name never leaves a stale decision), and a
        single-Name assign reading a guarded attribute records a
        decision."""
        def walk_body(stmts):
            for n in stmts:
                self._kill_bound(n, decisions)
                if isinstance(n, (ast.With, ast.AsyncWith)):
                    walk_body(n.body)
                elif isinstance(n, ast.Assign) and len(n.targets) == 1 \
                        and isinstance(n.targets[0], ast.Name):
                    attrs = _attr_reads_of_self(n.value, self.guarded)
                    if attrs:
                        decisions[n.targets[0].id] = (
                            sorted(attrs)[0], with_node.lineno, lock_name)
                elif isinstance(n, (ast.If, ast.While, ast.For,
                                    ast.AsyncFor)):
                    walk_body(n.body)
                    walk_body(n.orelse)
                elif isinstance(n, ast.Try):
                    walk_body(n.body)
                    for h in n.handlers:
                        walk_body(h.body)
                    walk_body(n.orelse)
                    walk_body(n.finalbody)
        walk_body(with_node.body)

    def _check_test(self, stmt, decisions):
        used = [n.id for n in ast.walk(stmt.test)
                if isinstance(n, ast.Name) and n.id in decisions]
        if not used:
            return
        act = self._find_act(stmt.body) or self._find_act(stmt.orelse)
        if act is None:
            return
        var = used[0]
        attr, with_line, lock_name = decisions[var]
        self.findings.append(Finding(
            rule="PHT010", file=self.mi.relpath, line=stmt.lineno,
            func=self.fi.qualname,
            message=f"check-then-act: `{var}` was derived from "
                    f"lock-guarded `self.{attr}` under `{lock_name}` "
                    f"(line {with_line}), but the lock was RELEASED "
                    f"before this test — the branch then {act}, acting "
                    "on state that may have changed in between (TOCTOU)",
            hint="re-validate under the lock at the point of action "
                 "(read the attribute again inside the locked region "
                 "that acts), or move the action into the original "
                 "locked block"))

    def _find_act(self, stmts) -> Optional[str]:
        for s in stmts:
            for n in ast.walk(s):
                if isinstance(n, (ast.Assign, ast.AugAssign)):
                    targets = n.targets if isinstance(n, ast.Assign) \
                        else [n.target]
                    for t in targets:
                        root = _store_attr_root(t)
                        if root in self.guarded:
                            return f"writes guarded `self.{root}`"
                elif isinstance(n, ast.Call):
                    f = n.func
                    if isinstance(f, ast.Attribute):
                        if isinstance(f.value, ast.Name) \
                                and f.value.id == "self":
                            for q in resolve_same_module(
                                    self.mi, self.fi,
                                    CallRef("self", f.attr, n)):
                                if q in self.acting:
                                    return (f"calls `self.{f.attr}()` "
                                            "which takes a lock and "
                                            "touches the guarded state")
                        if f.attr in _MUTATOR_METHODS:
                            root = _store_attr_root(f.value)
                            if root in self.guarded:
                                return ("mutates guarded "
                                        f"`self.{root}.{f.attr}(...)`")
        return None


# --------------------------------------------------------------------------
# driver
# --------------------------------------------------------------------------

def lint_module_races(mi: ModuleInfo) -> List[Finding]:
    findings: List[Finding] = []
    lock_names = _lock_attr_names(mi)
    guarded = infer_guarded(mi, lock_names)
    if not guarded:
        return findings

    entries = thread_entries(mi)
    if entries:
        reached = lockfree_reachable(mi, entries, lock_names)
        for qual, why in reached.items():
            fi = mi.funcs[qual]
            if isinstance(fi.node, ast.Lambda):
                continue
            if qual.rsplit(".", 1)[-1] == "__init__":
                continue   # pre-publication writes precede thread start
            cls_guarded = guarded.get(fi.class_name or "", {})
            if cls_guarded:
                _UnguardedAccessWalker(mi, fi, why, cls_guarded,
                                       lock_names, findings).run()

    acting = _locking_methods(mi, lock_names) & _guard_touchers(mi, guarded)
    for qual, fi in mi.funcs.items():
        if isinstance(fi.node, ast.Lambda):
            continue
        cls_guarded = guarded.get(fi.class_name or "", {})
        if cls_guarded:
            _CheckThenActWalker(mi, fi, cls_guarded, lock_names,
                                acting, findings).run()
    return findings
