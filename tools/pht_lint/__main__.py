"""CLI for pht-lint — see the package docstring and
docs/STATIC_ANALYSIS.md.  Exit codes: 0 clean, 1 findings, 2 usage/
config error (the perf_gate convention, so CI scripts can tell "lint
regression" from "lint broken")."""

from __future__ import annotations

import argparse
import json
import sys

from . import (DEFAULT_BASELINE, PASS_RULES, BaselineError, changed_paths,
               run_lint)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.pht_lint",
        description="JAX hot-path static analysis (PHT001-PHT010)")
    ap.add_argument("paths", nargs="*",
                    help="files to lint (default: package + tools + "
                         "bench.py)")
    ap.add_argument("--changed", action="store_true",
                    help="lint the .py files your change touches "
                         "(worktree + index + untracked + commits since "
                         "the merge-base with main); PHT003's lock graph "
                         "still spans the whole scope — the pre-PR check")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="suppression file (default: "
                         "tools/pht_lint/baseline.toml)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (show everything)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--stats", action="store_true",
                    help="report per-rule finding counts and per-pass "
                         "wall time (rules sharing one AST walk share "
                         "one honest time bucket) — the linter itself "
                         "is tier-1 budgeted, so rule growth must stay "
                         "measurable")
    args = ap.parse_args(argv)

    paths = args.paths or None
    if args.changed:
        if args.paths:
            print("pht-lint: --changed and explicit paths are exclusive",
                  file=sys.stderr)
            return 2
        paths = changed_paths()
        if not paths:
            print("pht-lint: no changed files in scope; nothing to lint")
            return 0

    stats = {} if args.stats else None
    try:
        findings, suppressed, unused = run_lint(
            paths=paths,
            baseline_path=None if args.no_baseline else args.baseline,
            strict=bool(args.paths),
            # a cycle's two halves may straddle the diff and an
            # unchanged module: the pre-PR check runs PHT003 repo-wide
            full_lock_graph=args.changed,
            stats=stats)
    except BaselineError as e:
        print(f"pht-lint: baseline error: {e}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"pht-lint: {e}", file=sys.stderr)
        return 2

    # an entry can only be proven stale by the FULL default scope — a
    # partial run (explicit paths, --changed) simply didn't look where
    # the entry points, and "fixed? delete it" advice would be wrong
    full_scope = paths is None
    if args.format == "json":
        doc = {
            "findings": [vars(f) for f in findings],
            "suppressed": [vars(f) for f in suppressed],
            "unused_baseline": unused if full_scope else [],
        }
        if stats is not None:
            doc["stats"] = stats
        print(json.dumps(doc, indent=2))
    else:
        for f in findings:
            print(f.render())
        if full_scope:
            for e in unused:
                print(f"pht-lint: warning: unused baseline entry "
                      f"{e['rule']} {e['file']} {e['func']} "
                      f"(fixed? delete it)", file=sys.stderr)
        if stats is not None:
            print(f"pht-lint stats: {stats['files']} file(s), "
                  f"{stats['total_s']:.2f}s wall "
                  f"({stats['cpu_s']:.2f}s cpu net of "
                  f"{stats['gc_cpu_s']:.2f}s gc)")
            for name, rules in PASS_RULES.items():
                print(f"  pass {name:<5} ({' '.join(rules)}): "
                      f"{stats['passes'][name]:.2f}s")
            counts = " ".join(f"{r}={n}" for r, n in
                              stats["rule_counts"].items())
            print(f"  findings (incl. suppressed): {counts}")
        print(f"pht-lint: {len(findings)} finding(s), "
              f"{len(suppressed)} suppressed by baseline")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
