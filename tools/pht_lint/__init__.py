"""pht-lint: project-specific static analysis for JAX hot paths.

Usage (scripted; perf_gate-style exit codes):

    python -m tools.pht_lint                 # default scope, baseline on
    python -m tools.pht_lint --changed       # only files in the git diff
    python -m tools.pht_lint path/to/file.py --format json

Exit codes: 0 = clean, 1 = unsuppressed findings, 2 = usage/config
error (bad baseline entry, unreadable path).

Rule catalog, the baseline workflow, and how to declare a new hot root:
docs/STATIC_ANALYSIS.md.
"""

from __future__ import annotations

import gc
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

from .callgraph import HOT_ROOT_MARK, ModuleInfo, index_module
from .flow import lint_module_flow
from .races import lint_module_races
from .rules import Finding, lint_locks, lint_module

__all__ = ["Finding", "run_lint", "load_baseline", "default_paths",
           "changed_paths", "BaselineError", "REPO_ROOT",
           "DEFAULT_BASELINE", "HOT_ROOT_MARK", "PASS_RULES"]

# pass name -> the rules it produces (stats attribution: rules sharing
# one AST walk share one honest wall-time bucket instead of a made-up
# per-rule split)
PASS_RULES = {
    "rules": ("PHT001", "PHT002", "PHT004", "PHT005"),
    "flow": ("PHT006", "PHT007", "PHT008"),
    "races": ("PHT009", "PHT010"),
    "locks": ("PHT003",),
}

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.toml")

# Default lint scope: the package, the tools, and the bench driver.
# Tests are excluded — they float()/block on purpose, and none of them
# is a hot path.
_DEFAULT_SCOPE = ("paddle_hackathon_tpu", "tools", "bench.py")
_SKIP_DIRS = {"__pycache__", ".git", "fixtures"}


class BaselineError(Exception):
    """Malformed baseline (missing reason, unknown key, bad syntax)."""


# ---------------------------------------------------------------------------
# baseline: a restricted TOML subset (this container is py3.10 — no
# tomllib), parsed strictly: only ``[[suppress]]`` tables with
# ``key = "string"`` pairs
# ---------------------------------------------------------------------------

def load_baseline(path: Optional[str]) -> List[Dict[str, str]]:
    if path is None or not os.path.exists(path):
        return []
    entries: List[Dict[str, str]] = []
    cur: Optional[Dict[str, str]] = None
    with open(path, encoding="utf-8") as f:
        for i, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if line == "[[suppress]]":
                cur = {}
                entries.append(cur)
                continue
            if "=" in line and cur is not None:
                key, _, val = line.partition("=")
                key = key.strip()
                val = val.strip()
                if len(val) >= 2 and val[0] == val[-1] == '"':
                    val = val[1:-1]
                else:
                    raise BaselineError(
                        f"{path}:{i}: values must be double-quoted "
                        f"strings (got {val!r})")
                if key not in ("rule", "file", "func", "reason"):
                    raise BaselineError(
                        f"{path}:{i}: unknown key {key!r} (allowed: "
                        "rule, file, func, reason)")
                cur[key] = val
                continue
            raise BaselineError(f"{path}:{i}: cannot parse {line!r}")
    for n, e in enumerate(entries, 1):
        for req in ("rule", "file", "func"):
            if not e.get(req):
                raise BaselineError(
                    f"{path}: suppress entry #{n} is missing {req!r}")
        if not e.get("reason", "").strip():
            raise BaselineError(
                f"{path}: suppress entry #{n} ({e['rule']} {e['file']} "
                f"{e['func']}) has no reason — every suppression must "
                "say WHY the finding is justified")
    return entries


def _matches(entry: Dict[str, str], f: Finding) -> bool:
    return (entry["rule"] == f.rule and entry["file"] == f.file
            and entry["func"] == f.func)


# ---------------------------------------------------------------------------
# file discovery
# ---------------------------------------------------------------------------

def default_paths(repo_root: str = REPO_ROOT) -> List[str]:
    out = []
    for rel in _DEFAULT_SCOPE:
        p = os.path.join(repo_root, rel)
        if os.path.isfile(p):
            out.append(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                # sorted: walk order anchors PHT003 cycle reports (first-
                # recorded edge wins) — inode order would make the
                # anchoring (file, func), and thus baseline matching,
                # machine-dependent
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in _SKIP_DIRS)
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
    return out


def _git(repo_root: str, *args: str) -> Optional[str]:
    try:
        out = subprocess.run(["git", *args], cwd=repo_root,
                             capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    return out.stdout if out.returncode == 0 else None


def changed_paths(repo_root: str = REPO_ROOT) -> List[str]:
    """Python files touched in the working tree + index + untracked,
    PLUS — on a feature branch — everything committed since the
    merge-base with main/master (the pre-PR check must not go vacuously
    green the moment the developer commits their diff)."""
    files = set()
    for args in (["diff", "--name-only", "HEAD"],
                 ["diff", "--name-only", "--cached"],
                 ["ls-files", "--others", "--exclude-standard"]):
        out = _git(repo_root, *args)
        if out is not None:
            files.update(ln.strip() for ln in out.splitlines()
                         if ln.strip())
    branch = (_git(repo_root, "rev-parse", "--abbrev-ref", "HEAD")
              or "").strip()
    if branch and branch not in ("main", "master"):
        # remote-tracking fallbacks: a fresh CI checkout often has no
        # LOCAL main/master, and a silent no-op here re-opens the
        # committed-diff hole this augmentation exists to close
        for base in ("main", "master", "origin/main", "origin/master"):
            mb = _git(repo_root, "merge-base", "HEAD", base)
            if mb is None:
                continue
            out = _git(repo_root, "diff", "--name-only",
                       mb.strip(), "HEAD")
            if out is not None:
                files.update(ln.strip() for ln in out.splitlines()
                             if ln.strip())
            break
    scope_dirs = tuple(s for s in _DEFAULT_SCOPE if not s.endswith(".py"))
    keep = []
    for rel in sorted(files):
        if not rel.endswith(".py"):
            continue
        if rel in _DEFAULT_SCOPE or rel.startswith(
                tuple(d + "/" for d in scope_dirs)):
            p = os.path.join(repo_root, rel)
            if os.path.exists(p):
                keep.append(p)
    return keep


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def run_lint(paths: Optional[List[str]] = None,
             baseline_path: Optional[str] = DEFAULT_BASELINE,
             repo_root: str = REPO_ROOT,
             strict: bool = False,
             full_lock_graph: bool = False,
             stats: Optional[dict] = None,
             ) -> Tuple[List[Finding], List[Finding], List[Dict[str, str]]]:
    """Lint ``paths`` (default scope when None).

    ``full_lock_graph=True`` (the ``--changed`` mode) runs PHT003 over
    the WHOLE default scope even when ``paths`` is partial: a lock-order
    cycle's two halves may straddle a changed and an unchanged module,
    and a graph built from the diff alone cannot see it.

    ``stats``, when a dict is passed, is filled in place (the ``--stats``
    CLI flag): per-pass wall seconds (``passes``), per-rule finding
    counts including suppressed (``rule_counts``), file count and total
    wall (``files``/``total_s``) — the linter's own cost is tier-1
    budgeted, so rule growth must stay measurable.

    Returns ``(findings, suppressed, unused_baseline_entries)`` —
    findings sorted by (file, line, rule).  Raises BaselineError on a
    malformed baseline and, with ``strict=True`` (the CLI's explicit-
    paths mode), OSError for a path that is missing or unparseable —
    callers map both to exit code 2.  A silent skip would report a
    'clean' lint that never ran on the file the caller named."""
    t_start = time.perf_counter()
    c_start = time.process_time()
    # GC collections triggered by the walk's allocations scan the HOST
    # process's whole tracked heap — inside the tier-1 suite that heap
    # carries jax plus every compiled program, so the pause cost scales
    # with the caller's ambient heap, not with the linter's work.  Track
    # it so cpu_s can subtract it: same noise class as wall-vs-load.
    _gc_cpu = [0.0, None]

    def _gc_probe(phase, info, _g=_gc_cpu):
        if phase == "start":
            _g[1] = time.process_time()
        elif _g[1] is not None:
            # guard against a "stop" with no observed "start": the
            # append can land while another thread is mid-collection,
            # and charging since-process-birth CPU here would drive
            # cpu_s negative and silently defeat the budget gate
            _g[0] += time.process_time() - _g[1]
            _g[1] = None

    gc.callbacks.append(_gc_probe)
    try:
        if paths is None:
            paths = default_paths(repo_root)
        baseline = load_baseline(baseline_path)

        modules: List[ModuleInfo] = []
        for p in paths:
            mi = index_module(os.path.abspath(p), repo_root)
            if mi is not None:
                modules.append(mi)
            elif strict:
                raise OSError(f"cannot lint {p}: missing, unreadable, or "
                              "not parseable as Python")

        passes = {name: 0.0 for name in PASS_RULES}
        findings: List[Finding] = []
        for mi in modules:
            t0 = time.perf_counter()
            findings.extend(lint_module(mi))
            t1 = time.perf_counter()
            findings.extend(lint_module_flow(mi))
            t2 = time.perf_counter()
            findings.extend(lint_module_races(mi))
            t3 = time.perf_counter()
            passes["rules"] += t1 - t0
            passes["flow"] += t2 - t1
            passes["races"] += t3 - t2
        lock_modules = modules
        if full_lock_graph:
            by_path = {m.path for m in modules}
            lock_modules = list(modules)
            for p in default_paths(repo_root):
                ap = os.path.abspath(p)
                if ap not in by_path:
                    mi = index_module(ap, repo_root)
                    if mi is not None:
                        lock_modules.append(mi)
        # full mode reports ALL lock findings, even ones anchored in
        # unchanged modules: the cycle report lands at the first-recorded
        # edge, which may be the unchanged half — filtering to the diff
        # would drop exactly the finding the mode exists to surface
        t0 = time.perf_counter()
        findings.extend(lint_locks(lock_modules))
        passes["locks"] += time.perf_counter() - t0
        findings.sort(key=lambda f: (f.file, f.line, f.rule))
    finally:
        gc.callbacks.remove(_gc_probe)
    if stats is not None:
        counts = {r: 0 for rules in PASS_RULES.values() for r in rules}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        stats.update(
            passes={k: round(v, 4) for k, v in passes.items()},
            rule_counts=dict(sorted(counts.items())),
            files=len(modules),
            total_s=round(time.perf_counter() - t_start, 4),
            # process-CPU seconds NET of GC pauses: the walk is
            # single-threaded pure CPU, so this equals wall on an idle
            # box but stays stable under concurrent load (wall flaked
            # the moment the box ran anything else) AND under a fat
            # caller heap (collections scan everything the host process
            # holds — inside the tier-1 suite that's jax plus every
            # compiled program, which blew the budget while standalone
            # runs sailed under it)
            cpu_s=round(time.process_time() - c_start - _gc_cpu[0], 4),
            gc_cpu_s=round(_gc_cpu[0], 4))

    kept, suppressed = [], []
    used = [False] * len(baseline)
    for f in findings:
        hit = False
        for i, e in enumerate(baseline):
            if _matches(e, f):
                used[i] = True
                hit = True
                break
        (suppressed if hit else kept).append(f)
    unused = [e for i, e in enumerate(baseline) if not used[i]]
    return kept, suppressed, unused
