"""Pretty-print or diff metrics-registry snapshots.

A snapshot is the JSON written by ``MetricRegistry.snapshot()`` — e.g.
the file ``hapi.callbacks.MetricsCallback(snapshot_path=...)`` drops at
``on_train_end``, or one saved by hand::

    import json
    from paddle_hackathon_tpu.observability import get_registry
    json.dump(get_registry().snapshot(), open("snap.json", "w"))

Usage::

    python tools/metrics_dump.py snap.json            # pretty-print
    python tools/metrics_dump.py before.json after.json   # diff
    python tools/metrics_dump.py --group replica fleet.json   # federated

The diff subtracts counters and histogram counts/sums (what HAPPENED
between the snapshots) and shows gauges as old -> new; bench rows'
embedded ``"metrics"`` dicts are a separate compact format gated by
``tools/perf_gate.py``, not this tool's input.

Byte-valued series (``*_bytes`` — e.g. the program observatory's
``program_hbm_bytes{site,kind}`` gauges) render the raw value plus a
humanized form (``1.5KiB``).  Program-registry snapshots
(``/debug/programs``) are ``tools/program_report.py``'s input, not
this tool's — this tool reads METRIC registry snapshots, where the
observatory shows up as ``jit_compile_seconds``/``program_hbm_bytes``
series.

``--group LABEL`` partitions the output into one section per value of
that label — the federated-fleet read: a snapshot taken through
``FleetRouter.expose_text()`` carries a bounded ``replica=`` label on
every replica-sourced series, and grouping by it answers "what did
replica X do" without grep (docs/OBSERVABILITY.md, "Fleet telemetry").
Series without the label land in a trailing ``(no LABEL)`` section.
"""

import argparse
import json
import sys


def _labels(d):
    if not d:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in sorted(d.items())) + "}"


def _fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float) and v != int(v):
        return f"{v:.6g}"
    return f"{int(v):,}"


def _human_bytes(v):
    """1536 -> '1.5KiB'; byte-valued series (program_hbm_bytes,
    pool/page accounting) get this next to the raw number."""
    v = float(v)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024.0 or unit == "TiB":
            return f"{v:.0f}{unit}" if unit == "B" else f"{v:.1f}{unit}"
        v /= 1024.0


def _group_key(s, group):
    """Section a series belongs to under ``--group LABEL`` (None = flat)."""
    if group is None:
        return None
    return (s.get("labels") or {}).get(group)


def _emit_grouped(rows, group, out):
    """rows: (group_value, key, col2, col3).  Flat when group is None;
    otherwise one header per label value (sorted, ungrouped last)."""
    width = max((len(r[1]) for r in rows), default=0)
    if group is None:
        for _, key, a, b in rows:
            out.write(f"{key:<{width}}  {a:<9}  {b}\n".rstrip() + "\n")
        return
    rows.sort(key=lambda r: (r[0] is None, r[0] or "", r[1]))
    current = object()
    for gv, key, a, b in rows:
        if gv != current:
            current = gv
            head = f'{group}="{gv}"' if gv is not None else f"(no {group})"
            out.write(f"== {head} ==\n")
        out.write(f"  {key:<{width}}  {a:<9}  {b}\n".rstrip() + "\n")


def render(snap, out=None, group=None):
    """One aligned line per series: NAME{labels} TYPE VALUE [detail].
    ``group``: label name to section the output by (module docstring)."""
    out = out or sys.stdout   # resolved at call time: a captured/replaced
    rows = []                 # stdout must not be baked in at import
    for name, fam in sorted(snap.get("metrics", {}).items()):
        for s in fam["series"]:
            key = name + _labels(s.get("labels"))
            if fam["type"] == "histogram":
                detail = (f"count={_fmt(s.get('count'))} "
                          f"sum={_fmt(s.get('sum'))}")
                for q in ("p50", "p90", "p99"):
                    if s.get(q) is not None:
                        detail += f" {q}={s[q]:.6g}"
                if s.get("max") is not None:
                    detail += f" max={s['max']:.6g}"
                rows.append((_group_key(s, group), key, fam["type"], detail))
            else:
                val = _fmt(s.get("value"))
                if name.endswith("_bytes") and \
                        isinstance(s.get("value"), (int, float)):
                    val += f" ({_human_bytes(s['value'])})"
                rows.append((_group_key(s, group), key, fam["type"], val))
    _emit_grouped(rows, group, out)
    return len(rows)


def render_diff(prev, cur, out=None, group=None):
    """Changed series only, prev -> cur (via observability.snapshot_delta
    for the counter/histogram subtraction semantics).  Series present in
    only one snapshot — engine churn drops labelled series, new sites
    register fresh families mid-run — render as added/removed instead of
    raising or silently vanishing.  ``group``: section by a label value
    (module docstring) — per-replica "what changed" in a federated diff."""
    out = out or sys.stdout
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    from paddle_hackathon_tpu.observability import snapshot_delta
    delta = snapshot_delta(prev, cur)
    pm = prev.get("metrics", {})
    cm = cur.get("metrics", {})

    def prev_series(name, labels):
        for s in pm.get(name, {}).get("series", []):
            if s.get("labels", {}) == labels:
                return s
        return None

    rows = []
    for name, fam in sorted(delta["metrics"].items()):
        for s in fam["series"]:
            key = name + _labels(s.get("labels"))
            gv = _group_key(s, group)
            old = prev_series(name, s.get("labels", {}))
            tag = " (added)" if old is None else ""
            if fam["type"] == "histogram":
                if not s.get("count") and not tag:
                    continue
                rows.append((gv, key, f"+{_fmt(s.get('count'))} obs{tag}",
                             f"sum +{s.get('sum', 0.0):.6g}"))
            elif fam["type"] == "counter":
                if not s.get("value") and not tag:
                    continue
                rows.append((gv, key, f"+{_fmt(s.get('value'))}{tag}", ""))
            else:
                oldv = old.get("value") if old else None
                if old is not None and oldv == s.get("value"):
                    continue
                rows.append((gv, key,
                             f"{_fmt(oldv)} -> {_fmt(s.get('value'))}{tag}",
                             ""))

    def series_keys(m):
        return {(name, tuple(sorted(s.get("labels", {}).items())))
                for name, fam in m.items() for s in fam.get("series", [])}

    for name, lk in sorted(series_keys(pm) - series_keys(cm)):
        lbl = dict(lk)
        rows.append((lbl.get(group) if group else None,
                     name + _labels(lbl), "(removed)", ""))
    if group is not None:
        _emit_grouped(rows, group, out)
    else:
        width = max((len(r[1]) for r in rows), default=0)
        for _, key, change, extra in rows:
            out.write(f"{key:<{width}}  "
                      f"{change}{'  ' + extra if extra else ''}\n")
    if not rows:
        out.write("(no changes)\n")
    return len(rows)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="pretty-print one metrics snapshot, or diff two")
    ap.add_argument("snapshot", help="registry snapshot JSON")
    ap.add_argument("snapshot2", nargs="?",
                    help="later snapshot: show what changed in between")
    ap.add_argument("--group", default=None, metavar="LABEL",
                    help="section output by this label's value (e.g. "
                         "--group replica for a federated fleet snapshot)")
    args = ap.parse_args(argv)
    with open(args.snapshot) as f:
        snap = json.load(f)
    if args.snapshot2 is None:
        render(snap, group=args.group)
        return 0
    with open(args.snapshot2) as f:
        snap2 = json.load(f)
    render_diff(snap, snap2, group=args.group)
    return 0


if __name__ == "__main__":
    sys.exit(main())
