"""Render or diff program-observatory snapshots.

A snapshot is the JSON served at ``/debug/programs`` (or
``ProgramRegistry.snapshot()`` saved by hand)::

    import json
    from paddle_hackathon_tpu.observability import get_program_registry
    json.dump(get_program_registry().snapshot(), open("progs.json", "w"))

Usage::

    python tools/program_report.py progs.json              # top sites
    python tools/program_report.py --causes progs.json     # cause history
    python tools/program_report.py before.json after.json  # diff

The single-snapshot view ranks sites by total compile seconds — the
"where does my compile time go" read — with builds/evictions and the
latest HBM analysis row when ``PHT_PROGRAM_ANALYSIS`` harvested one.
``--causes`` appends each site's bounded retrace-cause history (the
forensic read: WHY did build N happen).  The diff shows only sites
whose builds/evictions/compile-seconds moved between the snapshots,
with the causes recorded in between — "what recompiled during this
run, and why".  Reading rules and the cause taxonomy:
``docs/OBSERVABILITY.md``, "Program observatory".
"""

import argparse
import json
import sys


def _human_bytes(v):
    v = float(v)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024.0 or unit == "TiB":
            return f"{v:.0f}{unit}" if unit == "B" else f"{v:.1f}{unit}"
        v /= 1024.0


def _analysis_str(a):
    if not a:
        return ""
    parts = [f"{kind}={_human_bytes(a[f'{kind}_bytes'])}"
             for kind in ("args", "outputs", "temp", "generated")
             if a.get(f"{kind}_bytes") is not None]
    if a.get("flops"):
        parts.append(f"flops={a['flops']:.3g}")
    return "  ".join(parts)


def _ranked(snap):
    return sorted(snap.get("sites", {}).items(),
                  key=lambda kv: (-kv[1].get("compile_seconds_total", 0.0),
                                  kv[0]))


def render(snap, out=None):
    """Top compile-time sites, one aligned line each (+ analysis row)."""
    out = out or sys.stdout
    sites = _ranked(snap)
    out.write(f"programs: {snap.get('builds_total', 0)} builds, "
              f"{snap.get('compile_seconds_total', 0.0):.3f}s compile "
              f"across {len(sites)} sites\n")
    width = max((len(name) for name, _ in sites), default=0)
    for name, s in sites:
        out.write(f"  {name:<{width}}  "
                  f"{s.get('compile_seconds_total', 0.0):>8.3f}s  "
                  f"builds={s.get('builds', 0)}  "
                  f"evictions={s.get('evictions', 0)}  "
                  f"kind={s.get('kind', '?')}\n")
        analysis = _analysis_str(s.get("analysis"))
        if analysis:
            out.write(f"  {'':<{width}}  hbm: {analysis}\n")
    return len(sites)


def render_causes(snap, out=None, site=None):
    """Per-site retrace-cause history (bounded window, build order)."""
    out = out or sys.stdout
    n = 0
    for name, s in _ranked(snap):
        if site is not None and name != site:
            continue
        causes = [h for h in s.get("history", ()) if h.get("cause")]
        out.write(f"{name}: {s.get('builds', 0)} builds, "
                  f"{len(causes)} with recorded causes\n")
        for h in causes:
            out.write(f"  build {h['build']} "
                      f"({h.get('compile_s', 0.0):.3f}s): {h['cause']}\n")
        n += len(causes)
    return n


def render_diff(prev, cur, out=None):
    """Sites whose builds/evictions/compile-seconds moved, with the
    causes recorded in between (history entries newer than the previous
    snapshot's build count)."""
    out = out or sys.stdout
    ps = prev.get("sites", {})
    rows = 0
    for name, s in _ranked(cur):
        old = ps.get(name, {})
        db = s.get("builds", 0) - old.get("builds", 0)
        de = s.get("evictions", 0) - old.get("evictions", 0)
        ds = s.get("compile_seconds_total", 0.0) \
            - old.get("compile_seconds_total", 0.0)
        if not db and not de:
            continue
        tag = " (new site)" if name not in ps else ""
        out.write(f"{name}: +{db} builds, +{de} evictions, "
                  f"+{ds:.3f}s compile{tag}\n")
        for h in s.get("history", ()):
            if h.get("build", 0) > old.get("builds", 0) and h.get("cause"):
                out.write(f"  build {h['build']}: {h['cause']}\n")
        rows += 1
    for name in sorted(set(ps) - set(cur.get("sites", {}))):
        out.write(f"{name}: (removed)\n")
        rows += 1
    if not rows:
        out.write("(no program builds between snapshots)\n")
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="render one /debug/programs snapshot, or diff two")
    ap.add_argument("snapshot", help="program-registry snapshot JSON")
    ap.add_argument("snapshot2", nargs="?",
                    help="later snapshot: show what recompiled in between")
    ap.add_argument("--causes", action="store_true",
                    help="append per-site retrace-cause history")
    ap.add_argument("--site", default=None,
                    help="restrict --causes to one site label")
    args = ap.parse_args(argv)
    with open(args.snapshot) as f:
        snap = json.load(f)
    if args.snapshot2 is not None:
        with open(args.snapshot2) as f:
            snap2 = json.load(f)
        render_diff(snap, snap2)
        return 0
    render(snap)
    if args.causes or args.site:
        render_causes(snap, site=args.site)
    return 0


if __name__ == "__main__":
    sys.exit(main())
