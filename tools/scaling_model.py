"""Allreduce scaling-efficiency artifact (driver BASELINE target #2).

The driver's second target row — "Allreduce scaling efficiency (Fleet-style
DP) measured 8->256 chips" — cannot be hardware-measured here (one real chip
behind the axon tunnel).  This tool produces the honest substitute, split
into what is MEASURED and what is MODELED:

MEASURED (exact, from the compiler):
    For each mesh size n, the DP train step built by
    ``parallel.make_sharded_train_step`` is AOT-lowered and compiled over n
    virtual devices, and every collective instruction in the *optimized*
    HLO is extracted with its exact payload bytes.  These are the bytes XLA
    will actually move on a pod — including anything GSPMD added beyond the
    gradient psum (global-norm scalars, ZeRO reduce-scatters, ...).

MODELED (parameterized, documented):
    Those bytes feed the standard bidirectional-ring cost
        T_allreduce(n, B) = 2 (n-1)/n * B / bw_ring
    with ``bw_ring`` the per-chip injection bandwidth available to the dp
    axis (default: one v5e ICI torus axis, both directions:
    2 x 4.5e10 B/s — the public "How to Scale Your Model" v5e numbers),
    overlapped against the measured single-chip step time from BASELINE.md.
    256 chips is modeled as 4 x v5e-64 slices: in-slice ring over ICI plus a
    cross-slice ring over DCN (see ``parallel/multislice.py`` for the mesh
    geometry; default per-chip DCN share 2.5e9 B/s).

    Efficiency bounds reported per n:
      overlap   — XLA async collectives fully hidden under the backward
                  pass: eff = T_comp / max(T_comp, T_comm)
      no_overlap— worst case, nothing hidden: eff = T_comp/(T_comp+T_comm)

Reference analog: the Fleet DP scaling CI (`tools/ci_model_benchmark.sh`)
measures this on a GPU pool; the byte accounting here plays the role of its
nvprof NCCL traffic capture.

Usage:
    python tools/scaling_model.py            # tiny model, fast (CI)
    python tools/scaling_model.py --gpt2     # gpt2-small bytes (slow compile)
"""

import argparse
import json
import os
import subprocess
import sys

from paddle_hackathon_tpu.core.jaxcompat import set_mesh as _set_mesh

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

def collective_bytes_from_hlo(hlo_text):
    """Single owner of the HLO collective scan lives in the package —
    ``parallel/planner.py`` (the planner's cost model uses the same
    accounting)."""
    from paddle_hackathon_tpu.parallel.planner import (
        collective_bytes_from_hlo as _impl)
    return _impl(hlo_text)


def measure_dp_step(n, hidden=64, layers=2, vocab=256, seq=32,
                    zero_stage=0, heads=4):
    """Compile the DP train step on an n-device mesh; return the collective
    byte report and the total gradient bytes it should contain."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu import parallel
    from paddle_hackathon_tpu.models import (GPTConfig, GPTForCausalLM,
                                             param_sharding_spec)

    paddle.seed(0)
    devices = jax.devices()[:n]
    assert len(devices) >= n, f"need {n} devices, have {len(devices)}"
    mesh = parallel.create_mesh({"dp": n}, devices=devices)
    try:
        cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden,
                        num_layers=layers, num_heads=heads,
                        max_position_embeddings=seq,
                        hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                        use_flash_attention=False)
        model = GPTForCausalLM(cfg)
        step, state = parallel.make_sharded_train_step(
            model, mesh, rule=param_sharding_spec, learning_rate=1e-3,
            zero_stage=zero_stage)
        ids = jnp.asarray(np.zeros((n, seq)), jnp.int32)
        with _set_mesh(mesh):
            compiled = step._jitted.lower(
                state["params"], state["opt_state"], state["step"],
                (ids, ids), jax.random.key(0), jnp.float32(1e-3)).compile()
        report = collective_bytes_from_hlo(compiled.as_text())
        grad_bytes = sum(
            v.size * v.dtype.itemsize for v in state["params"].values()
            if jnp.issubdtype(v.dtype, jnp.floating))
    finally:
        parallel.set_mesh(None)
    return report, grad_bytes


def _measure_in_subprocess(n, **kw):
    """Re-exec measure_dp_step under an n-device virtual CPU platform."""
    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = " ".join(f for f in env.get("XLA_FLAGS", "").split()
                     if "host_platform_device_count" not in f)
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}").strip()
    code = (
        "import json, sys; sys.path.insert(0, {here!r});\n"
        # sitecustomize may force jax_platforms='axon,cpu' — pin it (same
        # dance as __graft_entry__.dryrun_multichip)
        "import jax; jax.config.update('jax_platforms', 'cpu');\n"
        "from scaling_model import measure_dp_step;\n"
        "r, g = measure_dp_step({n}, **{kw!r});\n"
        "print('RESULT ' + json.dumps([r, g]))"
    ).format(here=here, n=n, kw=kw)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=3600)
    if proc.returncode != 0:
        raise RuntimeError(f"subprocess failed:\n{proc.stderr[-2000:]}")
    for line in proc.stdout.splitlines():
        if line.startswith("RESULT "):
            report, grad_bytes = json.loads(line[len("RESULT "):])
            return report, grad_bytes
    raise RuntimeError(f"no RESULT line in:\n{proc.stdout[-2000:]}")


# ---------------------------------------------------------------------------
# the analytic part


def ring_allreduce_s(n, payload_bytes, bw_ring):
    if n <= 1:
        return 0.0
    return 2.0 * (n - 1) / n * payload_bytes / bw_ring


def efficiency_table(payload_bytes, step_compute_s,
                     chips=(8, 16, 32, 64, 256),
                     ici_bw_ring=2 * 4.5e10, dcn_bw_chip=2.5e9,
                     slice_size=64):
    """Predicted DP weak-scaling efficiency per chip count.

    Up to ``slice_size`` chips the dp ring rides one ICI torus axis; above
    it the allreduce is hierarchical (parallel/multislice.py geometry):
    in-slice ring + cross-slice DCN ring + in-slice broadcast phase, with
    the DCN stage carrying the full payload at per-chip DCN share.
    """
    rows = []
    for n in chips:
        if n <= slice_size:
            t_comm = ring_allreduce_s(n, payload_bytes, ici_bw_ring)
        else:
            n_slices = (n + slice_size - 1) // slice_size
            t_ici = ring_allreduce_s(slice_size, payload_bytes, ici_bw_ring)
            t_dcn = ring_allreduce_s(
                n_slices, payload_bytes, dcn_bw_chip * slice_size)
            t_comm = t_ici + t_dcn
        rows.append({
            "chips": n,
            "t_comm_ms": t_comm * 1e3,
            "eff_overlap": step_compute_s / max(step_compute_s, t_comm),
            "eff_no_overlap": step_compute_s / (step_compute_s + t_comm),
        })
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--gpt2", action="store_true",
                    help="measure gpt2-small HLO bytes (slow CPU compile)")
    ap.add_argument("--ns", default="4,8",
                    help="virtual mesh sizes to compile at")
    args = ap.parse_args()

    kw = (dict(hidden=768, layers=12, vocab=50304, seq=1024, heads=12)
          if args.gpt2 else {})
    ns = [int(x) for x in args.ns.split(",")]

    reports = {}
    for n in ns:
        report, grad_bytes = _measure_in_subprocess(n, **kw)
        reports[n] = report
        total = sum(report.values())
        print(f"n={n:3d}  collective bytes: {report}  "
              f"(grad payload {grad_bytes:,}B)")
    ar = [r.get("all-reduce", 0) for r in reports.values()]
    if len(ar) > 1 and ar[0]:
        drift = max(ar) / max(1, min(ar)) - 1
        print(f"all-reduce bytes across mesh sizes drift {drift:.1%} "
              "(weak scaling: should be ~0)")

    # model rows: measured single-chip step times from BASELINE.md.  The
    # gpt2 row only makes sense with --gpt2 (its payload must be the
    # measured gpt2 HLO bytes, not the tiny CI model's).
    configs = {"ResNet-50 DP (bs256/chip)": (256 / 2136.0, 51.3e6)}
    if args.gpt2:
        configs["gpt2-small DP (bs32/chip)"] = (0.2368, ar[-1] or None)
    else:
        print("(tiny CI model run — byte-accounting check only; use "
              "--gpt2 for the BASELINE.md gpt2 efficiency row)")
    for name, (t_comp, b) in configs.items():
        if b is None:
            continue
        print(f"\n{name}:")
        print(f"  MEASURED — payload {b / 1e6:.1f} MB (optimized-HLO "
              "collective bytes, mesh-size-invariant), compute "
              f"{t_comp * 1e3:.1f} ms/step (single-chip wall clock, "
              "BASELINE.md)")
        print("  MODELED  — bidirectional-ring cost on public constants "
              "(v5e ICI 2x4.5e10 B/s/axis, DCN 2.5e9 B/s/chip, "
              "jax-ml.github.io/scaling-book); NOT a hardware measurement")
        for row in efficiency_table(b, t_comp):
            print(f"  {row['chips']:4d} chips  comm {row['t_comm_ms']:7.2f} ms"
                  f"  eff(overlap) {row['eff_overlap']:6.1%}"
                  f"  eff(no-overlap) {row['eff_no_overlap']:6.1%}")
        # DCN is the weakest modeled constant (no error bars on the public
        # number): report the 256-chip row at 0.5x / 2x DCN bandwidth
        for factor in (0.5, 2.0):
            row = efficiency_table(b, t_comp, chips=(256,),
                                   dcn_bw_chip=2.5e9 * factor)[0]
            print(f"   256 chips @ {factor:g}x DCN  "
                  f"comm {row['t_comm_ms']:7.2f} ms"
                  f"  eff(overlap) {row['eff_overlap']:6.1%}"
                  f"  eff(no-overlap) {row['eff_no_overlap']:6.1%}")


if __name__ == "__main__":
    main()
