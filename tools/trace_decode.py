"""Trace one jitted greedy-decode program (GPTForCausalLM._generate_static)
and aggregate per-op device durations — the decode counterpart of
trace_step.py (VERDICT r4 directive #3: name where the 1.98 ms/token-step
goes vs the ~0.3 ms param-read floor).

Usage: python tools/trace_decode.py [batch] [prompt] [new_tokens]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np


def main(batch=8, prompt=64, new_tokens=128, outdir="/tmp/trace_decode"):
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu.core.tensor import Tensor
    from paddle_hackathon_tpu.models import GPTForCausalLM, gpt_config

    paddle.seed(0)
    cfg = gpt_config("gpt2-small-en", hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    model = GPTForCausalLM(cfg)
    for _, p in model.named_parameters():
        if jnp.issubdtype(p._value.dtype, jnp.floating):
            p._set_value(p._value.astype(jnp.bfloat16))
    model.eval()
    rng = np.random.RandomState(0)
    ids = Tensor(jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, prompt)),
                             jnp.int32))

    import time
    out = model.generate(ids, max_new_tokens=new_tokens, temperature=0.0)
    jax.block_until_ready(out._value)          # compile + warm
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        out = model.generate(ids, max_new_tokens=new_tokens, temperature=0.0)
    jax.block_until_ready(out._value)
    wall = (time.perf_counter() - t0) / reps
    tok_s = batch * new_tokens / wall
    print(f"wall: {wall*1e3:.1f} ms/call  {tok_s:,.0f} tok/s  "
          f"{wall*1e3/new_tokens:.3f} ms/token-step")

    import shutil
    shutil.rmtree(outdir, ignore_errors=True)
    jax.profiler.start_trace(outdir)
    out = model.generate(ids, max_new_tokens=new_tokens, temperature=0.0)
    jax.block_until_ready(out._value)
    jax.profiler.stop_trace()

    from trace_util import bucket_by_mnemonic, xla_op_durations_ms
    ind = xla_op_durations_ms(outdir)
    agg = bucket_by_mnemonic(ind)
    total = sum(ind.values())
    print(f"total device op time: {total:.2f} ms/call "
          f"({total/new_tokens:.4f} ms/token-step op-time)")
    for name, dur in agg.most_common(20):
        print(f"  {name:40s} {dur:8.2f} ms")
    print("top individual ops:")
    for name, dur in ind.most_common(30):
        print(f"  {name[:78]:78s} {dur:8.3f} ms")


if __name__ == "__main__":
    args = [int(a) for a in sys.argv[1:4]]
    main(*args)
