"""Cross-round performance gate (ref ``tools/ci_op_benchmark.sh:117`` /
``ci_model_benchmark.sh`` — the reference's CI rejects changes that regress
op or model benchmarks; it compares against an external benchmark repo, here
the history lives in-tree).

Two checks:

1. **Model gate** — the headline `bench.py` metric against the best prior
   `BENCH_r*.json`: fail when the current run is more than ``--tolerance``
   (default 5%) below the best recorded round.
2. **Op gate** — `cost_model/static_op_benchmark.json` regenerated (or a
   fresh file passed via ``--ops``) against the committed snapshot: fail
   when any op regresses more than ``--op-tolerance`` (default 25%; op
   microbenchmarks are noisy through the axon tunnel).

The ``--suite`` run additionally checks the telemetry each bench row
embeds (``"metrics"``, from the observability registry): a serving row
whose jit-build count grew between the warm phase and the measured
steady-state phase recompiled mid-run and fails the gate
(``compare_metrics``).

Usage::

    python tools/perf_gate.py                 # model gate only (fast)
    python tools/perf_gate.py --ops new.json  # + op gate vs snapshot

Exit code 0 = pass, 1 = regression, 2 = cannot evaluate (no history).
"""

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def best_recorded():
    sys.path.insert(0, ROOT)
    from bench import load_bench_history  # single owner of the file format
    return load_bench_history(ROOT)


def run_bench():
    out = subprocess.run([sys.executable, os.path.join(ROOT, "bench.py")],
                         capture_output=True, text=True, timeout=900)
    if out.returncode != 0:
        raise RuntimeError(f"bench.py failed:\n{out.stderr[-2000:]}")
    line = out.stdout.strip().splitlines()[-1]
    return json.loads(line)


def model_gate(tolerance):
    history = best_recorded()
    if not history:
        print("perf_gate: no BENCH_r*.json history — nothing to gate "
              "against")
        return 2
    best_round, best_value, metric = max(history, key=lambda r: r[1])
    cur = run_bench()
    value = float(cur["value"])
    floor = best_value * (1.0 - tolerance)
    status = "PASS" if value >= floor else "FAIL"
    print(f"perf_gate[model] {status}: {cur['metric']} = {value:,.0f} "
          f"{cur.get('unit', '')} vs best {best_value:,.0f} "
          f"(round {best_round}); floor at -{tolerance:.0%} = {floor:,.0f}")
    return 0 if status == "PASS" else 1


OP_SNAPSHOT = os.path.join(ROOT, "paddle_hackathon_tpu", "cost_model",
                           "static_op_benchmark.json")
MODEL_SNAPSHOT = os.path.join(ROOT, "paddle_hackathon_tpu", "cost_model",
                              "model_bench_baseline.json")


def _op_times(d):
    out = {}
    for entry in (d if isinstance(d, list) else d.get("ops", [])):
        name = entry.get("op") or entry.get("name")
        t = entry.get("paddle_gpu_time") or entry.get("time_ms")
        if name is not None and t:
            out[name] = float(t)
    return out


def compare_ops(old_t, new_t, op_tolerance):
    """[(name, old, new)] for ops slower than old*(1+tolerance)."""
    return [(name, t_old, new_t[name]) for name, t_old in old_t.items()
            if name in new_t and new_t[name] > t_old * (1.0 + op_tolerance)]


def op_gate(new_path, op_tolerance):
    snap_path = OP_SNAPSHOT
    if not os.path.exists(snap_path):
        print("perf_gate[ops]: no committed op snapshot — skip")
        return 0
    with open(snap_path) as fh:
        snap = json.load(fh)
    with open(new_path) as fh:
        new = json.load(fh)

    old_t, new_t = _op_times(snap), _op_times(new)
    regressed = compare_ops(old_t, new_t, op_tolerance)
    if regressed:
        print(f"perf_gate[ops] FAIL: {len(regressed)} ops regressed "
              f">{op_tolerance:.0%}:")
        for name, t_old, t_new in sorted(regressed,
                                         key=lambda r: r[2] / r[1],
                                         reverse=True)[:20]:
            print(f"  {name}: {t_old:.4f} -> {t_new:.4f} ms "
                  f"({t_new / t_old:.2f}x)")
        return 1
    print(f"perf_gate[ops] PASS: {len(old_t)} ops within "
          f"{op_tolerance:.0%} of snapshot "
          f"({len(new_t)} measured)")
    return 0


def _valued(rows):
    """{metric: value} over rows that actually carry a numeric value —
    error rows ({"error": ...} from bench.py run_suite) have none and
    are gated by compare_error_rows instead of crashing the parser."""
    return {r["metric"]: float(r["value"]) for r in rows
            if r.get("metric") and r.get("value") is not None}


def compare_suite(baseline, rows, tolerance):
    """[(metric, base, cur)] rows below baseline*(1-tolerance); baseline
    metrics the run didn't produce are reported as missing (regression)."""
    cur = _valued(rows)
    bad = []
    for metric, base in baseline.items():
        v = cur.get(metric)
        if v is None or v < float(base) * (1.0 - tolerance):
            bad.append((metric, float(base), v))
    return bad


def compare_error_rows(rows):
    """[(name, error_tail)] for rows bench.py recorded as crashed
    (``{"error": ...}`` — run_suite keeps sweeping past a crashing row
    instead of aborting the whole record, cf. the r04 rc=1 dtype crash
    that cost a full round's bench history).  The gate fails LOUDLY on
    each one: a crashed row must be a named failure with its stderr
    tail, never a silently missing metric."""
    return [(r.get("suite_row") or r.get("metric") or "?",
             str(r["error"])[:300])
            for r in rows if r.get("error")]


# Floor for the MoE flagship's embedded same-run ratio: the row itself
# runs its dense reference at matched ACTIVE params (bench_gpt2_moe), so
# the gate works identically on device and host-timed (CPU smoke) runs.
MOE_ACTIVE_RATIO_FLOOR = 0.60


def compare_moe_active_ratio(rows):
    """[(metric, ratio)] for MoE rows whose embedded
    ``vs_dense_active_params`` same-run ratio fell below the floor —
    the MoE tax (capacity-padded expert einsums + dispatch/combine) must
    stay under 40% of the matched-active-params dense throughput."""
    return [(r["metric"], float(r["vs_dense_active_params"]))
            for r in rows
            if r.get("vs_dense_active_params") is not None
            and float(r["vs_dense_active_params"]) < MOE_ACTIVE_RATIO_FLOOR]


# Same-run ratio gates: (metric, reference_metric, min_ratio).  Unlike the
# baseline comparison these need no committed number, so a NEW metric is
# gated from its first suite run.  hapi_fit is the compiled Model.fit
# path; it must stay within 10% of the hand-rolled jitted step it wraps
# (the acceptance bar for the fit fast path).  serving_spec is the
# speculative draft-and-verify tick over the identical serving workload:
# exact greedy equivalence means speculation must never LOSE throughput,
# so the bar is >= 1.0x the same-run non-speculative row.
RATIO_GATES = [
    ("hapi_fit_tokens_per_sec",
     "gpt2_small_pretrain_tokens_per_sec_per_chip", 0.90),
    # ZeRO-1 sharded optimizer through the identical Model.fit recipe:
    # the reduce-scatter/shard-update/all-gather path must hold tokens/s
    # within 10% of the replicated-update hapi_fit row (the per-tensor
    # gathers are designed to overlap the update tail inside the scanned
    # program — a serialized gather shows up here); the row additionally
    # embeds opt_state_bytes_vs_replicated ~ 1/dp as the HBM evidence
    ("hapi_fit_zero1_tokens_per_sec", "hapi_fit_tokens_per_sec", 0.90),
    # ZeRO-offload vs resident ZeRO-1: the offloaded update streams
    # every moment shard h2d and back d2h each step, so tokens/s is a
    # STATED capacity trade, not parity.  Curve: the pipe double-buffers
    # (offload_depth tensors in flight), so a healthy run hides most of
    # the transfer under the per-tensor update compute and the grads
    # program — 0.3x is the floor where the pipe has collapsed
    # (serialized h2d/d2h, a per-step recompile, or the ring draining
    # synchronously), not the expected steady state.  The capacity side
    # of the trade is gated separately: compare_zero_offload requires
    # device opt-state bytes ~ 0 with the host bytes stated.
    ("hapi_fit_offload_tokens_per_sec",
     "hapi_fit_zero1_tokens_per_sec", 0.30),
    ("gpt2_serving_spec_8stream_device_tokens_per_sec_per_chip",
     "gpt2_serving_8stream_device_tokens_per_sec_per_chip", 1.00),
    # paged KV at 2x the admitted streams must not lose aggregate
    # throughput to the dense layout: attention reads each slot's actual
    # length through the page table where dense reads max_len rows, so
    # the indirection has to pay for itself on the same-run workload
    ("gpt2_serving_paged_16stream_device_tokens_per_sec_per_chip",
     "gpt2_serving_8stream_device_tokens_per_sec_per_chip", 1.00),
    # weight-only int8 serving: decode is weight-HBM-bandwidth-bound, so
    # halving the bytes each tick streams must buy >= 1.3x the same-run
    # bf16 row on device timing (host-timed fallback rows are caught by
    # compare_timing_fallbacks instead of wall-clock-gated here)
    ("gpt2_serving_int8_8stream_device_tokens_per_sec_per_chip",
     "gpt2_serving_8stream_device_tokens_per_sec_per_chip", 1.30),
    # NOTE deliberately NO cross-row gate for gpt2_moe vs the gpt2
    # headline: the rows run different batch sizes (16 vs 32 — HBM
    # headroom for the 3.4x-total-params MoE), so a cross-row ratio
    # would conflate the MoE tax with batch effects.  The row gates
    # itself: bench_gpt2_moe embeds vs_dense_active_params from a
    # dense reference run in the SAME process at the SAME batch/seq,
    # held >= 0.60 by compare_moe_active_ratio below.
    # MoE serving sanity floor vs the same-run dense row (identical
    # workload/streams on both rows, so cross-row is sound): at matched
    # active params the MoE decode streams ~2.6x the weight bytes of the
    # dense model (8 experts x 2h resident vs one 4h MLP), so on a
    # weight-bandwidth-bound tick ~0.38x is the theoretical ceiling —
    # the floor catches the routed tick falling off a cliff (recompiles,
    # host syncs), not parity with dense
    ("gpt2_moe_serving_8stream_device_tokens_per_sec_per_chip",
     "gpt2_serving_8stream_device_tokens_per_sec_per_chip", 0.25),
    # multi-turn conversational serving: session suffix-caching removes
    # the per-turn history re-prefill, which must pay for the paged
    # indirection — aggregate tokens/s holds >= 1.0x the same-run dense
    # serving row (the turn-N TTFT improvement itself is gated by
    # compare_chat_ttft below, which works on host-timed runs too: both
    # TTFTs come from the same clock in the same process)
    ("gpt2_serving_chat_8conv_device_tokens_per_sec_per_chip",
     "gpt2_serving_8stream_device_tokens_per_sec_per_chip", 1.00),
]


def compare_ratios(rows):
    """[(metric, ref, ratio, floor)] for ratio gates that fail; gates
    whose metrics the run didn't produce are skipped (the baseline
    comparison already flags missing rows)."""
    cur = _valued(rows)
    bad = []
    for metric, ref, floor in RATIO_GATES:
        if metric in cur and ref in cur and cur[ref] > 0:
            ratio = cur[metric] / cur[ref]
            if ratio < floor:
                bad.append((metric, ref, ratio, floor))
    return bad


def compare_metrics(rows):
    """[(metric, warm, total)] for rows whose embedded telemetry shows
    jit builds GROWING between the warm (prefill + compile) phase and the
    measured steady-state phase — a program recompiled mid-run.  The
    serving bench rows embed ``metrics.jit_builds_warm/total`` (bench.py)
    exactly for this tripwire; rows without the keys are skipped."""
    bad = []
    for r in rows:
        m = r.get("metrics") or {}
        warm, total = m.get("jit_builds_warm"), m.get("jit_builds_total")
        if warm is not None and total is not None and total > warm:
            bad.append((r["metric"], int(warm), int(total)))
    return bad


def retrace_causes(rows, metric):
    """Recorded retrace causes for a failing row's ``programs`` block
    (the program-observatory evidence bench rows embed): ``(site,
    cause)`` pairs, build order.  Empty when the row predates the
    observatory — the caller prints a pointer instead of guessing."""
    for r in rows:
        if r.get("metric") != metric:
            continue
        out = []
        for site, s in ((r.get("programs") or {}).get("sites") or {}).items():
            out.extend((site, c) for c in s.get("causes") or ())
        return out
    return []


def compare_zero_sharding(rows):
    """[(metric, reason)] for ZeRO bench rows whose sharding evidence is
    vacuous or absent: a row claiming ``zero_stage>=1`` must have run on
    >1 data-axis devices (``dp``) AND show
    ``opt_state_bytes_vs_replicated`` strictly below 1.0 (the ~1/dp
    shrink).  A single-device bench environment — or a mesh the trainer
    silently degraded on — would otherwise green-light the
    ``hapi_fit_zero1`` ratio gate while both rows ran the identical
    replicated program, measuring nothing."""
    bad = []
    for r in rows:
        if not r.get("zero_stage"):
            continue
        dp = int(r.get("dp") or 0)
        ratio = r.get("opt_state_bytes_vs_replicated")
        if dp <= 1:
            bad.append((r["metric"],
                        f"ran on dp={dp} — ZeRO measured nothing"))
        elif ratio is None or float(ratio) >= 1.0:
            bad.append((r["metric"],
                        f"opt_state_bytes_vs_replicated={ratio!r} on "
                        f"dp={dp} — the optimizer state did not shard"))
    return bad


def compare_zero_offload(rows):
    """[(metric, reason)] for ZeRO-OFFLOAD bench rows whose evidence is
    vacuous (mirror of compare_zero_sharding): a row claiming
    ``zero_offload`` must have run on >1 data-axis devices, must show
    ``opt_state_bytes_vs_replicated`` ~ 0 (the moments really left the
    devices — a resident-looking ratio means the offload silently
    degraded), and must state a positive ``opt_state_host_bytes`` (the
    host side of the trade; 0 would mean no state existed at all and
    the tokens/s gate measured an empty update)."""
    bad = []
    for r in rows:
        if not r.get("zero_offload"):
            continue
        dp = int(r.get("dp") or 0)
        ratio = r.get("opt_state_bytes_vs_replicated")
        host = r.get("opt_state_host_bytes")
        if dp <= 1:
            bad.append((r["metric"],
                        f"ran on dp={dp} — offload measured nothing"))
        elif ratio is None or float(ratio) > 0.05:
            bad.append((r["metric"],
                        f"opt_state_bytes_vs_replicated={ratio!r} on "
                        f"dp={dp} — optimizer state stayed device-"
                        f"resident"))
        elif not host:
            bad.append((r["metric"],
                        f"opt_state_host_bytes={host!r} — no host-side "
                        f"state backs the offload claim"))
    return bad


def compare_timing_fallbacks(rows):
    """[metric] for rows measuring a *device* metric that fell back to
    HOST wall-clock timing (bench.py tags ``"timing": "host"`` when the
    profiler trace has no XLA device events).  On a TPU run that means
    the profiler broke: host wall through the axon tunnel is RTT-bound
    and must never be gated against committed device baselines — fail
    with a named cause instead of an unexplained throughput shift."""
    return [r["metric"] for r in rows
            if r.get("timing") == "host" and "device" in r.get("metric", "")]


# A returning turn that resumes its retained session skips the whole
# conversation-history prefill, so its TTFT must sit well below turn
# 1's full-prefill TTFT.  The floor is deliberately loose (turn-1
# prefills ~4x the suffix a resumed turn does, so a healthy run lands
# far under it) — it catches the resume path silently degrading to
# re-prefill, not timing noise.
CHAT_TTFT_RATIO_CEILING = 0.80


def compare_chat_ttft(rows):
    """[(metric, turn1_ms, turnN_ms)] for conversational serving rows
    whose returning-turn TTFT is NOT measurably below the turn-1 TTFT
    (``metrics.ttft_turnN_ms`` must be <= CHAT_TTFT_RATIO_CEILING x
    ``metrics.ttft_turn1_ms``): session resume fell back to
    re-prefilling the conversation.  Both stamps come from the same
    process and clock, so this gate holds on host-timed (CPU) runs
    too; rows without the keys are skipped."""
    bad = []
    for r in rows:
        m = r.get("metrics") or {}
        t1, tn = m.get("ttft_turn1_ms"), m.get("ttft_turnN_ms")
        if t1 is None or tn is None:
            continue
        if float(tn) > float(t1) * CHAT_TTFT_RATIO_CEILING:
            bad.append((r["metric"], float(t1), float(tn)))
    return bad


# SLO-aware scheduling gates (PR 17), over the serving_slo row's
# embedded same-run FIFO-vs-priority pair (both runs in one process on
# one clock, so the gates hold on host-timed CPU runs too).  Batch
# goodput is batch tokens per wall second: preempted work re-queues
# rather than aborting, so completed COUNTS always match — what
# preemption can crater is the time those tokens take (replay cost),
# and the floor holds that to 20%.  The interactive ceiling is loose
# by design (a healthy run lands far under it): it catches the
# scheduler degrading to FIFO, not timing noise.
SLO_BATCH_GOODPUT_FLOOR = 0.80
SLO_INTERACTIVE_TTFT_CEILING = 0.75


def compare_slo_scheduling(rows):
    """[(metric, reason)] for mixed-priority serving rows whose embedded
    FIFO-vs-priority evidence fails: interactive ttft_p99 must land at
    <= SLO_INTERACTIVE_TTFT_CEILING x the FIFO run's, batch goodput
    must hold >= SLO_BATCH_GOODPUT_FLOOR x FIFO, and scheduling must
    be lossless: every request in both runs delivers its full token
    budget (preemption re-queues and replays, never truncates).  Rows
    without the keys are skipped."""
    bad = []
    for r in rows:
        m = r.get("metrics") or {}
        ti_p = m.get("interactive_ttft_p99_ms_priority")
        ti_f = m.get("interactive_ttft_p99_ms_fifo")
        gp_p = m.get("batch_goodput_tokens_per_s_priority")
        gp_f = m.get("batch_goodput_tokens_per_s_fifo")
        if ti_p is None or ti_f is None or gp_p is None or gp_f is None:
            continue
        if float(ti_p) > float(ti_f) * SLO_INTERACTIVE_TTFT_CEILING:
            bad.append((r["metric"],
                        f"interactive ttft_p99 {float(ti_p):.1f}ms is "
                        f"not materially below FIFO's {float(ti_f):.1f}ms "
                        f"(ceiling {SLO_INTERACTIVE_TTFT_CEILING:.2f}x) "
                        f"— the scheduler degraded to FIFO"))
        if float(gp_p) < float(gp_f) * SLO_BATCH_GOODPUT_FLOOR:
            bad.append((r["metric"],
                        f"batch goodput {float(gp_p):.1f} tok/s fell "
                        f"below {SLO_BATCH_GOODPUT_FLOOR:.2f}x FIFO's "
                        f"{float(gp_f):.1f} tok/s — preemption/replay "
                        f"is cratering batch throughput"))
        if m.get("scheduling_lossless") is False:
            bad.append((r["metric"],
                        "a request finished short of its token budget "
                        "or errored — preemption/priority scheduling "
                        "dropped work instead of re-queueing it"))
    return bad


def compare_fleet_telemetry(rows):
    """[(metric, reason)] for fleet serving rows (``metrics.
    fleet_replicas`` present) whose armed-telemetry evidence is
    vacuous: the row must carry real dispatch-latency percentiles (the
    router's own ``fleet_dispatch_seconds`` histogram observed every
    placement), a stated retry rate, and the jit_builds_warm/total
    pair — compare_metrics holds that pair to zero growth, which for
    THIS row is the claim that the armed observability plane (spans,
    trace-context plumbing, federation labels) compiled nothing.  A
    row missing the builds pair would silently exempt itself from that
    gate, so its absence fails here by name.  Non-fleet rows are
    skipped."""
    bad = []
    for r in rows:
        m = r.get("metrics") or {}
        if m.get("fleet_replicas") is None:
            continue
        if (m.get("fleet_dispatch_p50_ms") is None
                or m.get("fleet_dispatch_p99_ms") is None):
            bad.append((r["metric"],
                        "no dispatch-latency percentiles — the router's "
                        "fleet_dispatch_seconds histogram observed no "
                        "placement"))
        if m.get("fleet_retry_rate") is None:
            bad.append((r["metric"],
                        "fleet_retry_rate missing from the embedded "
                        "telemetry"))
        if (m.get("jit_builds_warm") is None
                or m.get("jit_builds_total") is None):
            bad.append((r["metric"],
                        "jit_builds_warm/total missing — cannot prove "
                        "the armed telemetry plane compiled nothing"))
    return bad


def compare_pool_leaks(rows):
    """[(metric, leaked)] for paged serving rows whose KV page pool did
    not return to 0 allocated after the drain + prefix-cache drop
    (bench.py embeds ``metrics.kv_pages_leaked``): a refcount bug leaks
    HBM a page at a time in production — fail the gate instead."""
    bad = []
    for r in rows:
        leaked = (r.get("metrics") or {}).get("kv_pages_leaked")
        if leaked is not None and int(leaked) > 0:
            bad.append((r["metric"], int(leaked)))
    return bad


def suite_gate(tolerance, rows=None):
    """Gate EVERY BASELINE.md model config (ERNIE/1.3B/long-context/
    ResNet + gpt2) against the committed best values — the round-2 gate
    only covered the gpt2 headline, so 4 of 5 driver configs could
    regress silently (VERDICT r2 weak #3)."""
    if not os.path.exists(MODEL_SNAPSHOT):
        print("perf_gate[suite]: no committed model baseline — skip")
        return 0
    with open(MODEL_SNAPSHOT) as fh:
        baseline = json.load(fh)
    if rows is None:
        out = subprocess.run(
            [sys.executable, os.path.join(ROOT, "bench.py"), "--suite"],
            capture_output=True, text=True,
            timeout=42000)  # 13 rows x 2 attempts x 1500s + slack
        if out.returncode != 0:
            raise RuntimeError(f"bench.py --suite failed:\n"
                               f"{out.stderr[-2000:]}")
        rows = [json.loads(line) for line in out.stdout.splitlines()
                if line.startswith("{")]
    bad = compare_suite(baseline, rows, tolerance)
    bad_ratio = compare_ratios(rows)
    bad_metrics = compare_metrics(rows)
    bad_leaks = compare_pool_leaks(rows)
    bad_timing = compare_timing_fallbacks(rows)
    bad_errors = compare_error_rows(rows)
    bad_moe = compare_moe_active_ratio(rows)
    bad_zero = compare_zero_sharding(rows)
    bad_offload = compare_zero_offload(rows)
    bad_chat = compare_chat_ttft(rows)
    bad_slo = compare_slo_scheduling(rows)
    bad_fleet = compare_fleet_telemetry(rows)
    if (bad or bad_ratio or bad_metrics or bad_leaks or bad_timing
            or bad_errors or bad_moe or bad_zero or bad_offload
            or bad_chat or bad_slo or bad_fleet):
        if bad:
            print(f"perf_gate[suite] FAIL: {len(bad)} configs regressed "
                  f">{tolerance:.0%}:")
            for metric, base, v in bad:
                print(f"  {metric}: {base:,.0f} -> "
                      f"{'missing' if v is None else format(v, ',.0f')}")
        for name, err in bad_errors:
            print(f"perf_gate[suite] FAIL: suite row {name} CRASHED "
                  f"(recorded error row): {err}")
        for metric, ref, ratio, floor in bad_ratio:
            print(f"perf_gate[suite] FAIL: {metric} at {ratio:.2f}x of "
                  f"{ref} (floor {floor:.2f}x)")
        for metric, ratio in bad_moe:
            print(f"perf_gate[suite] FAIL: {metric} at {ratio:.2f}x of "
                  f"its same-run dense reference at matched active "
                  f"params (floor {MOE_ACTIVE_RATIO_FLOOR:.2f}x)")
        for metric, warm, total in bad_metrics:
            print(f"perf_gate[suite] FAIL: {metric} recompiled in steady "
                  f"state ({warm} jit builds after warm-up, {total} after "
                  f"the measured run)")
            causes = retrace_causes(rows, metric)
            for site, cause in causes:
                print(f"    retrace cause: {site}: {cause}")
            if not causes:
                print("    (no recorded causes — row carries no programs "
                      "block; see /debug/programs on a live run)")
        for metric, reason in bad_zero:
            print(f"perf_gate[suite] FAIL: {metric} ZeRO evidence is "
                  f"vacuous ({reason})")
        for metric, reason in bad_offload:
            print(f"perf_gate[suite] FAIL: {metric} ZeRO-offload "
                  f"evidence is vacuous ({reason})")
        for metric, t1, tn in bad_chat:
            print(f"perf_gate[suite] FAIL: {metric} turn-N TTFT "
                  f"{tn:.1f}ms is not measurably below turn-1 "
                  f"{t1:.1f}ms (ceiling "
                  f"{CHAT_TTFT_RATIO_CEILING:.2f}x) — session resume "
                  f"degraded to re-prefilling the conversation")
        for metric, reason in bad_slo:
            print(f"perf_gate[suite] FAIL: {metric} {reason}")
        for metric, reason in bad_fleet:
            print(f"perf_gate[suite] FAIL: {metric} fleet telemetry "
                  f"evidence is vacuous ({reason})")
        for metric, leaked in bad_leaks:
            print(f"perf_gate[suite] FAIL: {metric} leaked {leaked} KV "
                  f"pool pages (pages_in_use != 0 after drain + "
                  f"prefix-cache drop — a refcount bug)")
        for metric in bad_timing:
            print(f"perf_gate[suite] FAIL: {metric} was host-timed "
                  f"(profiler trace had no device events) — a device "
                  f"metric cannot be gated from wall clock")
        return 1
    print(f"perf_gate[suite] PASS: {len(baseline)} configs within "
          f"{tolerance:.0%} of the committed baseline; "
          f"{len(RATIO_GATES)} ratio gates hold; no error rows; no "
          f"steady-state recompilation; no KV pool leaks")
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tolerance", type=float, default=0.05,
                    help="allowed model-bench drop vs best round (0.05 = 5%%)")
    ap.add_argument("--op-tolerance", type=float, default=0.25,
                    help="allowed per-op slowdown vs snapshot")
    ap.add_argument("--ops", help="fresh op-benchmark json to gate")
    ap.add_argument("--suite", action="store_true",
                    help="gate every BASELINE.md model config (slow)")
    ap.add_argument("--suite-tolerance", type=float, default=0.07)
    args = ap.parse_args()

    rc = model_gate(args.tolerance)
    if args.ops:
        rc = max(rc, op_gate(args.ops, args.op_tolerance))
    if args.suite:
        rc = max(rc, suite_gate(args.suite_tolerance))
    return rc


if __name__ == "__main__":
    sys.exit(main())
