"""Cross-round performance gate (ref ``tools/ci_op_benchmark.sh:117`` /
``ci_model_benchmark.sh`` — the reference's CI rejects changes that regress
op or model benchmarks; it compares against an external benchmark repo, here
the history lives in-tree).

Two checks:

1. **Model gate** — the headline `bench.py` metric against the best prior
   `BENCH_r*.json`: fail when the current run is more than ``--tolerance``
   (default 5%) below the best recorded round.
2. **Op gate** — `cost_model/static_op_benchmark.json` regenerated (or a
   fresh file passed via ``--ops``) against the committed snapshot: fail
   when any op regresses more than ``--op-tolerance`` (default 25%; op
   microbenchmarks are noisy through the axon tunnel).

Usage::

    python tools/perf_gate.py                 # model gate only (fast)
    python tools/perf_gate.py --ops new.json  # + op gate vs snapshot

Exit code 0 = pass, 1 = regression, 2 = cannot evaluate (no history).
"""

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def best_recorded():
    sys.path.insert(0, ROOT)
    from bench import load_bench_history  # single owner of the file format
    return load_bench_history(ROOT)


def run_bench():
    out = subprocess.run([sys.executable, os.path.join(ROOT, "bench.py")],
                         capture_output=True, text=True, timeout=900)
    if out.returncode != 0:
        raise RuntimeError(f"bench.py failed:\n{out.stderr[-2000:]}")
    line = out.stdout.strip().splitlines()[-1]
    return json.loads(line)


def model_gate(tolerance):
    history = best_recorded()
    if not history:
        print("perf_gate: no BENCH_r*.json history — nothing to gate "
              "against")
        return 2
    best_round, best_value, metric = max(history, key=lambda r: r[1])
    cur = run_bench()
    value = float(cur["value"])
    floor = best_value * (1.0 - tolerance)
    status = "PASS" if value >= floor else "FAIL"
    print(f"perf_gate[model] {status}: {cur['metric']} = {value:,.0f} "
          f"{cur.get('unit', '')} vs best {best_value:,.0f} "
          f"(round {best_round}); floor at -{tolerance:.0%} = {floor:,.0f}")
    return 0 if status == "PASS" else 1


def op_gate(new_path, op_tolerance):
    snap_path = os.path.join(ROOT, "cost_model", "static_op_benchmark.json")
    if not os.path.exists(snap_path):
        print("perf_gate[ops]: no committed op snapshot — skip")
        return 0
    with open(snap_path) as fh:
        snap = json.load(fh)
    with open(new_path) as fh:
        new = json.load(fh)

    def times(d):
        out = {}
        for entry in (d if isinstance(d, list) else d.get("ops", [])):
            name = entry.get("op") or entry.get("name")
            t = entry.get("paddle_gpu_time") or entry.get("time_ms")
            if name is not None and t:
                out[name] = float(t)
        return out

    old_t, new_t = times(snap), times(new)
    regressed = []
    for name, t_old in old_t.items():
        t_new = new_t.get(name)
        if t_new is None:
            continue
        if t_new > t_old * (1.0 + op_tolerance):
            regressed.append((name, t_old, t_new))
    if regressed:
        print(f"perf_gate[ops] FAIL: {len(regressed)} ops regressed "
              f">{op_tolerance:.0%}:")
        for name, t_old, t_new in sorted(regressed,
                                         key=lambda r: r[2] / r[1],
                                         reverse=True)[:20]:
            print(f"  {name}: {t_old:.4f} -> {t_new:.4f} ms "
                  f"({t_new / t_old:.2f}x)")
        return 1
    print(f"perf_gate[ops] PASS: {len(old_t)} ops within "
          f"{op_tolerance:.0%} of snapshot "
          f"({len(new_t)} measured)")
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tolerance", type=float, default=0.05,
                    help="allowed model-bench drop vs best round (0.05 = 5%%)")
    ap.add_argument("--op-tolerance", type=float, default=0.25,
                    help="allowed per-op slowdown vs snapshot")
    ap.add_argument("--ops", help="fresh op-benchmark json to gate")
    args = ap.parse_args()

    rc = model_gate(args.tolerance)
    if args.ops:
        rc = max(rc, op_gate(args.ops, args.op_tolerance))
    return rc


if __name__ == "__main__":
    sys.exit(main())
