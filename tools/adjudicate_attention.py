"""Adjudicate the attention kernel: ours vs jax's pallas kernels, by
trace-measured op time inside the REAL train step (VERDICT r4 #4 — the
round-3 note "jax's flash_attention is within ~25% per call" left
unresolved whether the headline has attention fat; wall-clock microbenches
through the axon tunnel rank-invert and must not be used).

Candidates, each spliced into GPTAttention's fast path for a full traced
train step:
  packed    — this repo's packed-heads family (consumes the qkv projection
              output directly; in-kernel transposes; the round-2+ default)
  jax_flash — jax.experimental.pallas.ops.tpu.flash_attention (needs
              (b, h, s, d): head split/merge transposes around the call)
  splash    — jax.experimental.pallas.ops.tpu.splash_attention (same
              layout; its vjp recomputes per its own schedule)

Usage: python tools/adjudicate_attention.py [--batch 32] [--seq 1024]
"""
import argparse
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np


def _jax_flash_from_packed(qkv_t, num_heads, causal):
    """(b, s, 3hd) -> jax flash kernel -> (b, s, hd)."""
    from jax.experimental.pallas.ops.tpu import flash_attention as jfa

    from paddle_hackathon_tpu.core.autograd import apply_op

    def fn(qkv):
        b, s, hd3 = qkv.shape
        d = hd3 // 3 // num_heads
        x = qkv.reshape(b, s, 3, num_heads, d)
        q, k, v = [jnp.transpose(x[:, :, i], (0, 2, 1, 3))
                   for i in range(3)]          # (b, h, s, d)
        # bf16 operands at DEFAULT precision (the framework's global
        # 'highest' would make the jax kernel request an fp32 contract
        # Mosaic rejects — same choice our kernels' _prec() makes)
        blocks = None
        if os.environ.get("ADJ_TUNED_BLOCKS"):
            bq = min(1024, s)
            blocks = jfa.BlockSizes(
                block_q=bq, block_k_major=bq, block_k=bq, block_b=1,
                block_q_major_dkv=bq, block_k_major_dkv=bq,
                block_k_dkv=bq, block_q_dkv=bq,
                block_k_major_dq=bq, block_k_dq=bq, block_q_dq=bq)
        with jax.default_matmul_precision("default"):
            o = jfa.flash_attention(q, k, v, causal=causal,
                                    sm_scale=1.0 / d ** 0.5,
                                    block_sizes=blocks)
        return jnp.transpose(o, (0, 2, 1, 3)).reshape(b, s, -1)

    return apply_op("jax_flash_attention", fn, [qkv_t])


def _splash_from_packed(qkv_t, num_heads, causal):
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk)
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_mask as sm)

    from paddle_hackathon_tpu.core.autograd import apply_op

    def fn(qkv):
        b, s, hd3 = qkv.shape
        d = hd3 // 3 // num_heads
        x = qkv.reshape(b, s, 3, num_heads, d)
        q, k, v = [jnp.transpose(x[:, :, i], (0, 2, 1, 3))
                   for i in range(3)]
        mask = (sm.CausalMask((s, s)) if causal
                else sm.FullMask((s, s)))
        kernel = sk.make_splash_mha(
            mask=sm.MultiHeadMask([mask] * num_heads),
            head_shards=1, q_seq_shards=1)
        with jax.default_matmul_precision("default"):
            o = jax.vmap(kernel)(q * (1.0 / d ** 0.5), k, v)
        return jnp.transpose(o, (0, 2, 1, 3)).reshape(b, s, -1)

    return apply_op("splash_attention", fn, [qkv_t])


def run_one(impl, batch, seqlen, outdir):
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu import parallel
    from paddle_hackathon_tpu.models import (GPTForCausalLM, gpt_config,
                                             param_sharding_spec)

    if impl != "packed":
        # the framework's global 'highest' default would make the jax
        # kernels' BACKWARD (traced during grad, outside any local
        # context) request fp32 contracts on bf16 that Mosaic rejects;
        # our kernels pin per-dot precision instead (_prec()).  The model
        # matmuls run bf16 either way, so the step compare stays fair.
        jax.config.update("jax_default_matmul_precision", "default")
        import paddle_hackathon_tpu.incubate.nn.functional as IF
        fn = (_jax_flash_from_packed if impl == "jax_flash"
              else _splash_from_packed)
        orig = IF.flash_attention_qkv_packed

        def patched(qkv, num_heads, causal=True, sm_scale=None,
                    dropout_p=0.0, seed=None):
            assert dropout_p == 0.0
            return fn(qkv, num_heads, causal)
        # GPTAttention imports the symbol at call time from the package
        IF.flash_attention_qkv_packed = patched

    paddle.seed(0)
    cfg = gpt_config("gpt2-small-en", hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0, use_flash_attention=True)
    model = GPTForCausalLM(cfg)
    mesh = parallel.create_mesh({"dp": 1}, devices=jax.devices()[:1])
    step, state = parallel.make_sharded_train_step(
        model, mesh, rule=param_sharding_spec, learning_rate=1e-4,
        zero_stage=0, param_dtype=jnp.bfloat16)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seqlen)),
                      jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seqlen)),
                         jnp.int32)
    key = jax.random.key(0)
    for _ in range(3):
        state, loss = step(state, ids, labels, key)
    float(loss)
    shutil.rmtree(outdir, ignore_errors=True)
    jax.profiler.start_trace(outdir)
    for _ in range(3):
        state, loss = step(state, ids, labels, key)
    float(loss)
    jax.profiler.stop_trace()

    from trace_util import toplevel_device_ms
    total = toplevel_device_ms(outdir) / 3
    # per-impl kernel names differ (ours: jvp__.N pallas calls; jax's:
    # their own fusion names) — the step total is the decisive number
    tok_s = batch * seqlen / (total / 1e3)
    print(f"{impl:10s} step {total:7.2f} ms  {tok_s:,.0f} tok/s-equivalent")
    return {"impl": impl, "step_ms": total, "tok_s": tok_s}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--impls", default="packed,jax_flash,splash")
    args = ap.parse_args()
    results = []
    for impl in args.impls.split(","):
        # fresh subprocess per impl: the monkeypatch and compile caches
        # must not leak across candidates
        import json
        import subprocess
        code = (f"import sys; sys.path.insert(0, {os.path.dirname(os.path.abspath(__file__))!r}); "
                f"from adjudicate_attention import run_one; "
                f"run_one({impl!r}, {args.batch}, {args.seq}, "
                f"'/tmp/adjudicate_{impl}')")
        proc = subprocess.run([sys.executable, "-c", code], timeout=1200)
        if proc.returncode != 0:
            print(f"{impl}: FAILED (rc {proc.returncode})")
    print("(per-impl rows printed above by subprocesses)")


if __name__ == "__main__":
    main()
