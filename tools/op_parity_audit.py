"""Op-parity audit: reference phi kernel headers + api.yaml vs this surface.

Re-runnable evidence for the COMPONENTS.md audit table: resolves every
forward kernel header name and yaml api entry against the framework's
public namespaces and prints anything unresolved.  (Ref: the reference
gates op coverage in CI by diffing generated api lists —
``tools/check_api_approvals`` family; here the surface itself is the
contract.)
"""

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REF = "/root/reference"

# kernel-header name -> public API that carries the capability (one witness
# is enough; the name difference is the phi-internal vs public-API split)
KERNEL_TO_API = {
    "accuracy": "paddle.metric.accuracy",
    "activation": "F.relu",
    "adadelta": "paddle.optimizer.Adadelta",
    "adagrad": "paddle.optimizer.Adagrad",
    "adam": "paddle.optimizer.Adam",
    "adamax": "paddle.optimizer.Adamax",
    "adamw": "paddle.optimizer.AdamW",
    "arg_min_max": "paddle.argmax",
    "auc": "paddle.metric.Auc",
    "batch_norm": "paddle.nn.BatchNorm2D",
    "bce_loss": "F.binary_cross_entropy",
    "bilinear_tensor_product": "F.bilinear",
    "bitwise": "paddle.bitwise_and",
    "box_coder": "paddle.vision.ops.box_coder",
    "channel_shuffle": "F.channel_shuffle",
    "clip_by_norm": "paddle.nn.ClipGradByNorm",
    "compare": "paddle.equal",
    "conv": "F.conv2d",
    "conv_transpose": "F.conv2d_transpose",
    "cross_entropy": "F.cross_entropy",
    "cum": "paddle.cumsum",
    "deformable_conv": "paddle.vision.ops.deform_conv2d",
    "depthwise_conv": "F.conv2d",
    "determinant": "paddle.linalg.det",
    "diag_embed": "F.diag_embed",
    "dropout": "F.dropout",
    "elementwise": "paddle.add",
    "elementwise_add": "paddle.add",
    "elementwise_divide": "paddle.divide",
    "elementwise_multiply": "paddle.multiply",
    "elementwise_subtract": "paddle.subtract",
    "embedding": "F.embedding",
    "exponential": "ops.exponential_",
    "frobenius_norm": "paddle.linalg.norm",
    "gather_tree": "F.gather_tree",
    "gaussian_random": "paddle.randn",
    "gelu": "F.gelu",
    "graph_reindex": "paddle.incubate.graph_reindex",
    "graph_sample_neighbors": "paddle.incubate.graph_sample_neighbors",
    "graph_send_recv": "paddle.incubate.graph_send_recv",
    "grid_sample": "F.grid_sample",
    "group_norm": "paddle.nn.GroupNorm",
    "gumbel_softmax": "F.gumbel_softmax",
    "hierarchical_sigmoid": "F.hsigmoid_loss",
    "huber_loss": "F.smooth_l1_loss",
    "identity_loss": "paddle.incubate.identity_loss",
    "instance_norm": "paddle.nn.InstanceNorm2D",
    "interpolate": "F.interpolate",
    "kldiv_loss": "F.kl_div",
    "label_smooth": "F.label_smooth",
    "layer_norm": "paddle.nn.LayerNorm",
    "log_loss": "F.log_loss",
    "log_softmax": "F.log_softmax",
    "logical": "paddle.logical_and",
    "matrix_rank_tol": "paddle.linalg.matrix_rank",
    "maxout": "F.maxout",
    "mean_all": "paddle.mean",
    "merged_momentum": "paddle.optimizer.Momentum",
    "momentum": "paddle.optimizer.Momentum",
    "nll_loss": "F.nll_loss",
    "one_hot": "F.one_hot",
    "p_norm": "paddle.linalg.norm",
    "pad3d": "F.pad",
    "pixel_shuffle": "F.pixel_shuffle",
    "pixel_unshuffle": "F.pixel_unshuffle",
    "pool": "F.max_pool2d",
    "prelu": "F.prelu",
    "psroi_pool": "paddle.vision.ops.psroi_pool",
    "reduce_all": "paddle.all",
    "reduce_any": "paddle.any",
    "reduce_max": "paddle.max",
    "reduce_mean": "paddle.mean",
    "reduce_min": "paddle.min",
    "reduce_prod": "paddle.prod",
    "reduce_sum": "paddle.sum",
    "rmsprop": "paddle.optimizer.RMSProp",
    "rnn": "paddle.nn.LSTM",
    "roi_align": "paddle.vision.ops.roi_align",
    "roi_pool": "paddle.vision.ops.roi_pool",
    "rrelu": "F.rrelu",
    "segment_pool": "paddle.incubate.segment_sum",
    "selu": "F.selu",
    "set_value": "Tensor.__setitem__",
    "sgd": "paddle.optimizer.SGD",
    "sigmoid_cross_entropy_with_logits": "F.binary_cross_entropy_with_logits",
    "size": "paddle.numel",
    "slogdeterminant": "paddle.linalg.slogdet",
    "softmax": "F.softmax",
    "sparse_weight_embedding": "F.embedding",
    "squared_l2_norm": "paddle.linalg.norm",
    "sync_batch_norm": "paddle.nn.SyncBatchNorm",
    "temporal_shift": "F.temporal_shift",
    "top_k": "paddle.topk",
    "transfer_layout": "paddle.transpose",
    "tril_triu": "paddle.tril",
    "truncated_gaussian_random": "paddle.nn.initializer.TruncatedNormal",
    "unfold": "F.unfold",
    "uniform_random": "paddle.uniform",
    "viterbi_decode": "paddle.text.viterbi_decode",
    "warpctc": "F.ctc_loss",
    "where_index": "paddle.nonzero",
    "yolo_box": "paddle.vision.ops.yolo_box",
    "yolov3_loss": "paddle.vision.ops.yolo_loss",
}

# yaml entries that are deliberate n/a (see COMPONENTS.md audit table)
YAML_NA = {
    "brelu": "F.hardtanh carries the formula (fluid-1.x name)",
    "copy_to": "PJRT single device space; to_tensor/set_device",
    "cross_entropy_with_softmax": "F.cross_entropy (fused)",
    "depthwise_conv2d": "F.conv2d(groups=cin)",
    "depthwise_conv2d_transpose": "F.conv2d_transpose(groups=cin)",
    "full_batch_size_like": "fluid-1.x static helper",
    "hard_shrink": "F.hardshrink", "hard_sigmoid": "F.hardsigmoid",
    "hard_swish": "F.hardswish", "logsigmoid": "F.log_sigmoid",
    "soft_shrink": "F.softshrink", "tanh_shrink": "F.tanhshrink",
    "max_pool2d_with_index": "F.max_pool2d(return_mask=True)",
    "max_pool3d_with_index": "F.max_pool3d(return_mask=True)",
    "modulo": "paddle.mod", "elementwise_pow": "paddle.pow",
    "pool2d": "F.max_pool2d/avg_pool2d", "pool3d": "F.max_pool3d",
    "pool2d_gpudnn_unused": "cuDNN artifact",
    "reverse_array": "TensorArray reversal = python list.reverse()",
    "transfer_layout": "XLA layout assignment",
    "sigmoid_cross_entropy_with_logits": "F.binary_cross_entropy_with_logits",
    "truncated_gaussian_random": "initializer.TruncatedNormal",
    "uniform_random": "paddle.uniform", "gaussian_random": "paddle.randn",
    "top_k": "paddle.topk", "tril_triu": "paddle.tril",
    "warpctc": "F.ctc_loss", "where_index": "paddle.nonzero",
    "viterbi_decode": "paddle.text.viterbi_decode",
    "squared_l2_norm": "paddle.linalg.norm", "p_norm": "paddle.linalg.norm",
    "frobenius_norm": "paddle.linalg.norm", "mean_all": "paddle.mean",
    "reduce_prod": "paddle.prod", "huber_loss": "F.smooth_l1_loss",
    "kldiv_loss": "F.kl_div", "bce_loss": "F.binary_cross_entropy",
    "momentum": "paddle.optimizer.Momentum",
    "adadelta": "paddle.optimizer.Adadelta",
    "adamax": "paddle.optimizer.Adamax", "adamw": "paddle.optimizer.AdamW",
    "accuracy": "paddle.metric.accuracy", "auc": "paddle.metric.Auc",
    "bilinear_tensor_product": "F.bilinear",
    "box_coder": "paddle.vision.ops.box_coder",
    "clip_by_norm": "paddle.nn.ClipGradByNorm",
    "deformable_conv": "paddle.vision.ops.deform_conv2d",
    "matrix_rank_tol": "paddle.linalg.matrix_rank",
    "pad3d": "F.pad", "segment_pool": "paddle.incubate.segment_sum",
    "sync_batch_norm": "paddle.nn.SyncBatchNorm",
}


def _resolve(path):
    import paddle_hackathon_tpu as paddle
    import paddle_hackathon_tpu.nn.functional as F
    import paddle_hackathon_tpu.ops as ops
    from paddle_hackathon_tpu.core.tensor import Tensor
    roots = {"paddle": paddle, "F": F, "ops": ops, "Tensor": Tensor}
    parts = path.split(".")
    obj = roots[parts[0]]
    for part in parts[1:]:
        obj = getattr(obj, part, None)
        if obj is None:
            return None
    return obj


def main():
    from paddle_hackathon_tpu.ops import OP_TABLE
    ours = set(OP_TABLE)

    kdir = os.path.join(REF, "paddle/phi/kernels")
    fwd = {f[:-len("_kernel.h")] for f in os.listdir(kdir)
           if f.endswith("_kernel.h")}
    fwd = {k for k in fwd if not k.endswith("_grad")
           and not k.endswith("_grad_grad")}

    unresolved = []
    for k in sorted(fwd):
        if k in ours:
            continue
        api = KERNEL_TO_API.get(k)
        if api is None or _resolve(api) is None:
            unresolved.append((k, api))
    print(f"kernel headers: {len(fwd)} fwd; unresolved: {len(unresolved)}")
    for k, api in unresolved:
        print("  UNRESOLVED", k, "->", api)

    yaml_names = set()
    for yml in ("paddle/phi/api/yaml/api.yaml",
                "paddle/phi/api/yaml/legacy_api.yaml"):
        with open(os.path.join(REF, yml)) as fh:
            for line in fh:
                m = re.match(r"- api\s*:\s*(\w+)", line)
                if m:
                    yaml_names.add(m.group(1))
    import paddle_hackathon_tpu as paddle
    import paddle_hackathon_tpu.nn.functional as F
    from paddle_hackathon_tpu.core.tensor import Tensor
    missing = []
    for n in sorted(yaml_names):
        if n.endswith("_") or n.startswith("c_") or n.endswith("_grad"):
            continue
        if any(getattr(m, n, None) is not None for m in (
                paddle, F, Tensor, paddle.linalg, paddle.vision.ops,
                paddle.incubate)):
            continue
        if n in YAML_NA or n in KERNEL_TO_API:
            continue
        missing.append(n)
    print(f"yaml apis: {len(yaml_names)}; unexplained missing: "
          f"{len(missing)}")
    for n in missing:
        print("  MISSING", n)
    return 0 if not unresolved and not missing else 1


if __name__ == "__main__":
    sys.exit(main())
