"""precommit: the docs/STATIC_ANALYSIS.md pre-PR checklist as ONE command.

    python tools/precommit.py [--durations /tmp/durations.log] [--stats]

Chains, in order:

1. **pht-lint --changed** — lints the .py files your change touches
   (worktree + index + untracked + commits since the merge-base with
   main); PHT003's lock graph still spans the whole scope.
2. **test-budget drift** — ``tools/test_budget.py`` diffs a
   ``pytest --durations=0`` log against ``tests/conftest.py _FILE_COST``
   so budget drift fails HERE instead of as an RC=137 archaeology
   session.  Runs when ``--durations`` is given or the default log
   exists; otherwise SKIPPED with the command to produce one (a lint-only
   change doesn't need a suite run, so a missing log is not a failure).
3. **jaxcompat canary** — imports the bridge symbols in a subprocess
   (``core/jaxcompat.py`` has been wiped by a re-seed before; a broken
   bridge must fail the pre-PR check loudly, not as a downstream XLA
   abort).
4. **fault drills** — deterministic ``PHT_FAULTS`` drills against
   host-only stubs (no tick program compiles).  The fleet
   dispatch-failover drill — an injected ``fleet.dispatch`` fault
   plus a submit-time replica death must re-dispatch cleanly (retry
   books, survivor completes); the fleet-telemetry drill — a forced
   mid-request failover must land router + both replicas' spans on ONE
   rid-stitched swimlane in the merged chrome trace, with the
   federated exposition labeled per replica and zero leaked pages.
   The started-stream loud-failure path and mid-flight kills live in
   ``tests/test_fleet.py``'s acceptance drills, not here.  Add new
   drills to ``_DRILLS``.

Exit codes (perf_gate convention): 0 = every step that ran passed,
1 = at least one step failed, 2 = usage error.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_DURATIONS = "/tmp/durations.log"

_CANARY = (
    "from paddle_hackathon_tpu.core import jaxcompat\n"
    "import jax\n"
    "assert callable(jaxcompat.shard_map), 'jaxcompat.shard_map gone'\n"
    "assert callable(jaxcompat.set_mesh), 'jaxcompat.set_mesh gone'\n"
    "assert hasattr(jax, 'export'), 'jax.export bridge gone'\n"
    "print('jaxcompat bridge symbols present')\n"
)


# ``PHT_FAULTS`` fault drills run as step 4: (name, env-spec, script).
# Each script runs in a fresh interpreter with the spec armed through
# the environment (the same delivery the crash drills use), against
# host-only stubs — no tick program compiles, so the step stays cheap.
_FLEET_DRILL = """
import numpy as np, threading, itertools
from paddle_hackathon_tpu.inference.fleet import (
    FleetRouter, StreamInterruptedError)

_ids = itertools.count()
class Req:
    def __init__(self, prompt, n, on_token=None):
        self.rid = next(_ids); self.prompt = np.asarray(prompt, np.int32)
        self.tokens = []; self.done = False; self.error = None
        self._event = threading.Event(); self.on_token = on_token; self.n = n
    def finish(self):
        self.tokens = list(range(self.n)); self.done = True
        self._event.set()
    def result(self):
        if self.error is not None:
            raise RuntimeError('failed') from self.error
        return np.concatenate([self.prompt, np.asarray(self.tokens, np.int32)])

class Stub:
    def __init__(self, name, headroom):
        self.engine_id = name; self.headroom = headroom; self.submitted = []
    def load_report(self):
        return {'version': 1, 'engine': self.engine_id, 'draining': False,
                'slots': {'max': 8, 'active': 0, 'free': 8},
                'queue': {'depth': 0, 'oldest_wait_s': 0.0},
                'admission': {'headroom_tokens': self.headroom}}
    def submit(self, prompt, max_new_tokens, deadline_s=None,
               on_token=None, **kw):
        r = Req(prompt, max_new_tokens, on_token)
        self.submitted.append(r); r.finish(); return r
    def drain(self, timeout=None): pass
    def shutdown(self, timeout=None): pass

a, b = Stub('drill-a', 9000), Stub('drill-b', 100)
router = FleetRouter([a, b], backoff_s=0.001, breaker_failures=1)
# PHT_FAULTS fleet.dispatch=fail@1 kills the FIRST placement attempt:
# the retry must land the request anyway and book exactly one retry
fr = router.submit([1, 2, 3], 4)
assert fr.wait(10) and fr.error is None, fr.error
assert list(fr.result()) == [1, 2, 3, 0, 1, 2, 3]
assert fr.retries == 0  # placement retry, not a failover
from paddle_hackathon_tpu.observability import get_registry
assert get_registry().total('fleet_retries_total',
                            fleet=router.fleet_id) == 1
# replica death before any token: failover to the survivor
dead = Stub('drill-c', 9000); live = Stub('drill-d', 10)
dead.submit = lambda *a, **k: (_ for _ in ()).throw(
    RuntimeError('replica down'))
r2 = FleetRouter([dead, live], backoff_s=0.001, breaker_failures=1)
fr2 = r2.submit([7], 2)
assert fr2.wait(10) and fr2.replica == 'drill-d'
print('fleet drill: dispatch-fault retry + failover OK')
"""

# Session eviction under drain, both layers.  Engine side: a draining
# replica must DONATE every retained session chain to its prefix cache
# (returning conversations replay from cached pages, and nothing leaks
# — construction-only, no tick compiles: the session record is
# fabricated white-box and drain() on an idle sync engine is pure
# host work).  Fleet side: the armed fleet.dispatch fault kills the
# session turn's first placement; the retry must still land AND pin,
# the pin must stick, and draining the pinned replica must clear it so
# the next turn migrates to the survivor carrying the session kwarg.
_SESSION_DRILL = """
import numpy as np, threading, itertools
from paddle_hackathon_tpu.models.gpt import GPTConfig, GPTForCausalLM
from paddle_hackathon_tpu.inference.serving import ServingEngine, _Session

cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                num_heads=4, max_position_embeddings=128,
                hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                use_flash_attention=False)
m = GPTForCausalLM(cfg); m.eval()
eng = ServingEngine(m, max_slots=2, max_len=64, chunk=4, auto_run=False,
                    cache_mode="paged", page_size=8, num_pages=12)
# fabricate a retained 20-token session (3 pages, 2 of them full)
pages = eng._pool.alloc(3)
sess = _Session("drill")
sess.tokens = np.arange(20, dtype=np.int32)
sess.kv_len = 20
sess.pages = list(pages)
eng._sessions["drill"] = sess
assert eng.kv_pages_in_use == 3
eng.drain(timeout=10)
# drain donated the chain: session record gone, the 2 FULL pages now
# live in the prefix cache, the partial tail page was freed
assert not eng._sessions
assert int(eng._c["sessions_evicted"].value) == 1
assert eng.kv_pages_in_use == 2
eng.drop_prefix_cache()
assert eng.kv_pages_in_use == 0     # zero leak
eng.shutdown(timeout=5)

from paddle_hackathon_tpu.inference.fleet import FleetRouter
_ids = itertools.count()
class Req:
    def __init__(self, prompt, n, on_token=None):
        self.rid = next(_ids); self.prompt = np.asarray(prompt, np.int32)
        self.tokens = []; self.done = False; self.error = None
        self._event = threading.Event(); self.on_token = on_token; self.n = n
    def finish(self):
        self.tokens = list(range(self.n)); self.done = True
        self._event.set()
    def result(self):
        return np.concatenate([self.prompt, np.asarray(self.tokens,
                                                       np.int32)])

class Stub:
    def __init__(self, name, headroom):
        self.engine_id = name; self.headroom = headroom
        self.sessions_seen = []
    def load_report(self):
        return {'version': 1, 'engine': self.engine_id, 'draining': False,
                'slots': {'max': 8, 'active': 0, 'free': 8},
                'queue': {'depth': 0, 'oldest_wait_s': 0.0},
                'admission': {'headroom_tokens': self.headroom}}
    def submit(self, prompt, max_new_tokens, deadline_s=None,
               on_token=None, **kw):
        self.sessions_seen.append(kw.get('session'))
        r = Req(prompt, max_new_tokens, on_token)
        r.finish(); return r
    def drain(self, timeout=None): pass
    def shutdown(self, timeout=None): pass

a, b = Stub('sess-a', 9000), Stub('sess-b', 100)
router = FleetRouter([a, b], backoff_s=0.001, breaker_failures=3)
# the armed fleet.dispatch=fail@1 kills THIS turn's first placement:
# the retry must land it anyway and still record the pin
fr = router.submit([1, 2, 3], 4, session='chat')
assert fr.wait(10) and fr.error is None, fr.error
pinned = router._session_pins.get('chat')
assert pinned == fr.replica and pinned in ('sess-a', 'sess-b')
assert router.introspect_requests()['session_pins'] == 1
# second turn sticks to the pin regardless of headroom
fr2 = router.submit([1, 2, 3, 9], 4, session='chat')
assert fr2.wait(10) and fr2.replica == pinned
# draining the pinned replica clears the pin; the next turn migrates
# to the survivor and re-pins there, session kwarg intact
router.drain(pinned)
assert 'chat' not in router._session_pins
other = 'sess-b' if pinned == 'sess-a' else 'sess-a'
fr3 = router.submit([1, 2, 3, 9, 9], 4, session='chat')
assert fr3.wait(10) and fr3.replica == other
assert router._session_pins.get('chat') == other
survivor = a if other == 'sess-a' else b
assert survivor.sessions_seen[-1] == 'chat'
router.shutdown()
print('session drill: drain donation + pin migration under '
      'dispatch fault OK')
"""

# Fleet-telemetry drill (PR 19).  Two host-only stub replicas behind a
# FleetRouter, span sink armed; the PHT_FAULTS ``serving.tick[tele-a]``
# point (which the stub fires after accepting a request, the same point
# a real engine's tick loop owns) kills the first placement AFTER
# submit succeeded — a genuine failover, not a placement retry.  The
# drill then closes the whole observability loop: federated exposition
# carries both replicas under bounded ``replica=`` labels plus the
# fleet-only series, ``load_report()`` serializes, and the merged
# chrome trace (``--stitch-fleet`` pass) shows router dispatch +
# failover spans AND both replicas' lifecycle spans — including a
# rid-only tick span mapped via the rid bridge — on ONE
# ``fleet_rid`` swimlane.  Fake KV page accounting on the stubs must
# read zero after the failover (the dead attempt released its pages).
_TELEMETRY_DRILL = """
import itertools, json, os, tempfile, threading, time
import numpy as np
from paddle_hackathon_tpu.observability import faults as _faults
from paddle_hackathon_tpu.observability import tracing as tr
from paddle_hackathon_tpu.inference.fleet import FleetRouter
from paddle_hackathon_tpu.profiler.cross_stack import merge_traces

_ids = itertools.count(100)
class Req:
    def __init__(self, prompt, n):
        self.rid = next(_ids); self.prompt = np.asarray(prompt, np.int32)
        self.tokens = []; self.done = False; self.error = None
        self._event = threading.Event()

class Stub:
    # host-only replica with fake KV page accounting, a per-replica
    # exposition, and the same lifecycle spans ServingEngine emits:
    # serving.request carries rid + fleet_rid, the per-tick span
    # carries rid ONLY (the stitch pass must bridge it via the rid map)
    def __init__(self, name, headroom):
        self.engine_id = name; self.headroom = headroom
        self.pages_in_use = 0
    def load_report(self):
        return {'version': 1, 'engine': self.engine_id, 'draining': False,
                'slots': {'max': 8, 'active': 0, 'free': 8},
                'queue': {'depth': 0, 'oldest_wait_s': 0.0},
                'admission': {'headroom_tokens': self.headroom}}
    def metrics_text(self):
        return ('# HELP pht_stub_pages fake page gauge\\n'
                '# TYPE pht_stub_pages gauge\\n'
                'pht_stub_pages{engine="%s"} %d\\n'
                % (self.engine_id, self.pages_in_use))
    def submit(self, prompt, max_new_tokens, deadline_s=None,
               on_token=None, trace_ctx=None, **kw):
        r = Req(prompt, max_new_tokens)
        self.pages_in_use += 2
        fa = ({'fleet_rid': trace_ctx['fleet_rid']} if trace_ctx else {})
        sp = tr.start_span('serving.request', _tid=r.rid, rid=r.rid,
                           engine=self.engine_id, **fa)
        t0 = time.perf_counter_ns()
        tr.add_span('serving.decode', t0, t0 + 1000, _tid=r.rid,
                    rid=r.rid, engine=self.engine_id, slot=0)
        try:
            _faults.point('serving.tick[%s]' % self.engine_id)
        except Exception as e:
            # armed tick fault kills the request AFTER placement with
            # zero tokens streamed: the router must fail it over
            r.error = e; self.pages_in_use -= 2
            sp.end(error=type(e).__name__); r._event.set(); return r
        r.tokens = list(range(max_new_tokens)); r.done = True
        self.pages_in_use -= 2
        sp.end(tokens=len(r.tokens)); r._event.set(); return r
    def drain(self, timeout=None): pass
    def shutdown(self, timeout=None): pass

spans = []
tr.set_span_sink(lambda name, t0, t1, tid, attrs: spans.append(
    {'name': name, 'ph': 'X', 'pid': 0, 'tid': tid, 'ts': t0 / 1e3,
     'dur': max((t1 - t0) / 1e3, 0.001), 'args': dict(attrs or {})}))
tr.enable_tracing()
# headroom skew makes tele-a the deterministic first pick: the armed
# serving.tick[tele-a] fault then forces the failover onto tele-b
a, b = Stub('tele-a', 9000), Stub('tele-b', 100)
router = FleetRouter([a, b], backoff_s=0.001)
fr = router.submit([1, 2, 3], 4)
assert fr.wait(10) and fr.error is None, fr.error
assert fr.replica == 'tele-b' and fr.retries == 1, (fr.replica, fr.retries)
tr.disable_tracing(); tr.set_span_sink(None)

# federation: both replicas under bounded replica= labels + fleet series
text = router.expose_text()
assert 'replica="tele-a"' in text and 'replica="tele-b"' in text, text
assert 'fleet_dispatch_seconds' in text and 'fleet_retries_total' in text
json.dumps(router.load_report())      # aggregated report serializes

d = tempfile.mkdtemp()
p = os.path.join(d, 'trace.json')
with open(p, 'w') as f:
    json.dump({'traceEvents': spans}, f)
merged = merge_traces([p], stitch_fleet=True)
ev = merged['traceEvents']
meta = [e for e in ev if e.get('ph') == 'M'
        and e.get('name') == 'process_name'
        and 'rid-stitched' in (e.get('args') or {}).get('name', '')]
assert meta, 'stitched fleet process missing'
fpid = meta[0]['pid']
lane = [e for e in ev if e.get('ph') != 'M' and e['pid'] == fpid
        and e['tid'] == fr.fleet_rid]
names = set(e['name'] for e in lane)
assert {'fleet.route', 'fleet.dispatch', 'fleet.failover',
        'serving.request', 'serving.decode'} <= names, names
engines = set((e.get('args') or {}).get('engine') for e in lane
              if e['name'] == 'serving.request')
assert engines == {'tele-a', 'tele-b'}, engines
assert a.pages_in_use == 0 and b.pages_in_use == 0, 'page leak'
router.shutdown()
print('telemetry drill: failover stitched onto one fleet lane, '
      'federation labeled per replica, zero page leak OK')
"""

# Priority-inversion drain drill (PR 17).  A real (tiny, CPU) engine
# behind a FleetRouter: a batch stream fills the page pool, an
# interactive arrival preempts it mid-decode (pages released, request
# re-queued), and the replica is drained WHILE the preempted stream
# sits in the queue.  Drain must complete — a scheduler that refused to
# re-admit the demoted request while draining would wedge the drain on
# a priority inversion — the preempted stream must still produce its
# full token count (re-queued work is never lost), and the pool must
# read zero after the cache drop (preemption releases/donates pages,
# never leaks them).  The one drill that compiles tick programs
# (~tens of seconds): preempt-while-draining needs real ticks.
_PRIORITY_DRILL = """
import time
import numpy as np
from paddle_hackathon_tpu.models.gpt import GPTConfig, GPTForCausalLM
from paddle_hackathon_tpu.inference.serving import ServingEngine
from paddle_hackathon_tpu.inference.fleet import FleetRouter

cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                num_heads=4, max_position_embeddings=128,
                hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                use_flash_attention=False)
m = GPTForCausalLM(cfg); m.eval()
# pool sized so the batch request's footprint (8 pages) fills the
# usable pool: the interactive arrival (3 pages) can only admit by
# preempting it
eng = ServingEngine(m, max_slots=2, max_len=64, chunk=4,
                    cache_mode="paged", page_size=8, num_pages=9)
router = FleetRouter([eng])
name = eng._engine_id
rb = router.submit(np.arange(16, dtype=np.int32), 40, priority="batch")
end = time.monotonic() + 120
while not rb.tokens and time.monotonic() < end:
    time.sleep(0.01)
assert rb.tokens, "batch stream never started decoding"
ri = router.submit(np.arange(8, dtype=np.int32) + 3, 8,
                   priority="interactive")
while int(eng._c["preemptions"].value) < 1 and time.monotonic() < end:
    time.sleep(0.01)
assert int(eng._c["preemptions"].value) >= 1, "no preemption fired"
# drain WHILE the preempted batch stream sits re-queued: the drain
# must re-admit and finish it, not wedge on the inversion
router.drain(name, timeout=120)
assert rb.done and rb.error is None, rb.error
assert ri.done and ri.error is None, ri.error
assert len(rb.tokens) == 40, (len(rb.tokens), "preempted work lost")
assert len(ri.tokens) == 8
eng.drop_prefix_cache()
assert eng.kv_pages_in_use == 0, eng.kv_pages_in_use
print('priority drill: preempt mid-decode + drain-under-inversion '
      'completed, zero page leak OK')
"""

# ZeRO x pp composition smoke (PR 18).  zero_stage>=1 must compose
# with the pipeline trainer: moments dp-sharded WITHIN each stage (or
# host numpy under zero_offload), and the composed flat namespace must
# dp-reshard through restore_like.  On jax>=0.6 (partial-manual
# shard_map available) the drill also runs one composed superstep
# under the donation sanitizer; on this container's jax<0.6 the
# superstep path is structurally gated (same gate as the pp test
# files), so the drill exercises construction, placement, and the
# dp2->dp4 reshard-resume instead — the pieces that run everywhere.
_ZERO_PP_SMOKE = """
import os
import tempfile
# the pp2 x dp2 mesh needs the virtual 8-device CPU topology the test
# conftest arranges; this subprocess must arrange it before jax imports
_flags = os.environ.get('XLA_FLAGS', '')
if 'xla_force_host_platform_device_count' not in _flags:
    os.environ['XLA_FLAGS'] = (
        _flags + ' --xla_force_host_platform_device_count=8').strip()
import jax
import numpy as np
import paddle_hackathon_tpu as paddle
from paddle_hackathon_tpu import parallel
from paddle_hackathon_tpu.models import (GPTConfig, GPTForCausalLM,
                                         param_sharding_spec)
from paddle_hackathon_tpu.observability import sanitizers
from paddle_hackathon_tpu.parallel.checkpointing import (
    CheckpointManager, flatten_train_state, restore_like)

def build(mesh_dims, **kw):
    n = int(np.prod(list(mesh_dims.values())))
    mesh = parallel.create_mesh(mesh_dims, devices=jax.devices()[:n])
    paddle.seed(123)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=64, hidden_size=16, num_layers=4, num_heads=2,
        intermediate_size=32, max_position_embeddings=32,
        hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
        use_flash_attention=False))
    step, state = parallel.make_sharded_train_step(
        model, mesh, rule=param_sharding_spec, learning_rate=1e-3,
        zero_stage=1, grad_clip_norm=None, **kw)
    return step, state

k = 'gpt.blocks.$stacked.attn.qkv_proj.weight'
with sanitizers.donation_sanitizer():
    step, state = build({'pp': 2, 'dp': 2})
    mom = state['opt_state'][k]['m']
    spec = tuple(mom.sharding.spec)
    axes = [a for s in spec if s is not None
            for a in (s if isinstance(s, tuple) else (s,))]
    assert spec[0] == 'pp' and 'dp' in axes, spec
    if hasattr(jax, 'set_mesh'):
        r = np.random.RandomState(0)
        ids = np.asarray(r.randint(0, 64, (8, 16)))
        labels = np.asarray(r.randint(0, 64, (8, 16)))
        state, loss = step(state, ids, labels, jax.random.key(0))
        assert np.isfinite(float(loss)), loss
        mode = 'superstep loss %.4f' % float(loss)
    else:
        _, st_off = build({'pp': 2, 'dp': 2}, zero_offload=True)
        assert isinstance(st_off['opt_state'][k]['m'], np.ndarray)
        key_order = list(state['params'])
        flat = flatten_train_state(
            state['params'],
            [state['opt_state'][q] for q in key_order], state['step'])
        with tempfile.TemporaryDirectory() as d:
            mgr = CheckpointManager(d, async_save=False)
            mgr.save(flat, step=0, block=True)
            mgr.close()
            _, state2 = build({'pp': 2, 'dp': 4})
            flat2 = flatten_train_state(
                state2['params'],
                [state2['opt_state'][q] for q in key_order],
                state2['step'])
            placed, _ = restore_like(d, flat2)
        i = key_order.index(k)
        np.testing.assert_array_equal(
            np.asarray(placed['opt::%d::m' % i]),
            np.asarray(flat['opt::%d::m' % i]))
        mode = 'placement + dp2->dp4 reshard (superstep gated)'
print('zero-pp smoke: composed state sharded pp x dp, ' + mode
      + ', donation-sanitizer clean OK')
"""

# Program-observatory retrace drill: drive one instrumented site with a
# changed shape (numpy callable — construction only, no jax compile) and
# assert the forensics landed end-to-end: the registry's cause record
# names the changed argument, the flight event carries the same cause,
# the jit_builds_total/jit_compile_seconds series exist, and both CLI
# renderers (metrics_dump over the metric snapshot, program_report over
# the registry snapshot) show the new rows.
_PROGRAM_DRILL = """
import io
import numpy as np
from paddle_hackathon_tpu import observability as obs
from paddle_hackathon_tpu.observability import metrics, programs
from tools import metrics_dump, program_report

prog = programs.get_program_registry()

def tick(ids, mask):
    return ids.sum() + mask.sum()

w = obs.instrument_jit(tick, site='drill.tick')
a = np.zeros((8, 16), np.float32)
m = np.ones((8,), np.float32)
w(a, m); w(a, m)                       # build 1, then steady-state
w(np.zeros((8, 24), np.float32), m)    # forced retrace: seqlen change

site = prog.snapshot()['sites']['drill.tick']
assert site['builds'] == 2, site
cause = site['history'][-1]['cause']
for frag in ('arg[0]', '`ids`', '8,16', '8,24'):
    assert frag in cause, (frag, cause)
ev = [e for e in obs.get_flight_recorder().events()
      if e.get('kind') == 'program_build' and e.get('site') == 'drill.tick']
assert len(ev) == 2 and ev[-1]['cause'] == cause, ev
reg = metrics.get_registry()
assert reg.total('jit_builds_total', site='drill.tick') == 2.0
out = io.StringIO()
metrics_dump.render(reg.snapshot(), out=out)
assert 'jit_compile_seconds{site=drill.tick}' in out.getvalue()
out = io.StringIO()
program_report.render(prog.snapshot(), out=out)
program_report.render_causes(prog.snapshot(), out=out, site='drill.tick')
assert 'drill.tick' in out.getvalue() and cause in out.getvalue()
print('program drill: retrace cause %r recorded, flight + metrics + '
      'reports agree OK' % cause)
"""

_DRILLS = [
    ("fleet-drill", "fleet.dispatch=fail@1", _FLEET_DRILL),
    ("session-drill", "fleet.dispatch=fail@1", _SESSION_DRILL),
    ("telemetry-drill", "serving.tick[tele-a]=fail@1", _TELEMETRY_DRILL),
    ("priority-drill", "", _PRIORITY_DRILL),
    ("zero-pp-smoke", "", _ZERO_PP_SMOKE),
    ("program-drill", "", _PROGRAM_DRILL),
]


def _run_step(name: str, argv, results, display=None, env=None) -> None:
    print(f"== {name}: {display or ' '.join(argv)}")
    run_env = None
    if env:
        run_env = dict(os.environ)
        run_env.update(env)
    proc = subprocess.run(argv, cwd=REPO_ROOT, env=run_env)
    ok = proc.returncode == 0
    results.append((name, "PASS" if ok else f"FAIL (rc={proc.returncode})"))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python tools/precommit.py",
        formatter_class=argparse.RawDescriptionHelpFormatter,
        description=__doc__)
    ap.add_argument("--durations", default=None,
                    help="pytest --durations=0 log for the budget drift "
                         f"check (default: {DEFAULT_DURATIONS} when it "
                         "exists; otherwise the step is skipped)")
    ap.add_argument("--stats", action="store_true",
                    help="pass --stats through to pht-lint (per-rule "
                         "counts + per-pass wall time)")
    ap.add_argument("--skip-canary", action="store_true",
                    help="skip the jaxcompat import canary (it imports "
                         "jax: ~10s)")
    args = ap.parse_args(argv)

    results = []

    lint_cmd = [sys.executable, "-m", "tools.pht_lint", "--changed"]
    if args.stats:
        lint_cmd.append("--stats")
    _run_step("pht-lint", lint_cmd, results)

    durations = args.durations
    if durations is None and os.path.exists(DEFAULT_DURATIONS):
        durations = DEFAULT_DURATIONS
    if durations is not None:
        if not os.path.exists(durations):
            print(f"precommit: durations log {durations!r} not found",
                  file=sys.stderr)
            return 2
        _run_step("test-budget",
                  [sys.executable, "tools/test_budget.py", durations],
                  results)
    else:
        results.append(("test-budget", "SKIP (no durations log)"))
        print("== test-budget: SKIPPED — to include it:\n"
              "   python -m pytest tests/ -q -m 'not slow' --durations=0 "
              "-p no:cacheprovider | tee /tmp/durations.log")

    if args.skip_canary:
        results.append(("jaxcompat-canary", "SKIP (--skip-canary)"))
    else:
        _run_step("jaxcompat-canary",
                  [sys.executable, "-c", _CANARY], results,
                  display="python -c '<import the jaxcompat bridge "
                          "symbols>'")

    for name, spec, script in _DRILLS:
        _run_step(name, [sys.executable, "-c", script], results,
                  display=f"PHT_FAULTS='{spec}' python -c "
                          f"'<host-only {name}>'",
                  env={"PHT_FAULTS": spec})

    print("\nprecommit summary:")
    width = max(len(n) for n, _ in results)
    for name, status in results:
        print(f"  {name:<{width}}  {status}")
    return 1 if any(s.startswith("FAIL") for _, s in results) else 0


if __name__ == "__main__":
    sys.exit(main())
