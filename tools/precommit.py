"""precommit: the docs/STATIC_ANALYSIS.md pre-PR checklist as ONE command.

    python tools/precommit.py [--durations /tmp/durations.log] [--stats]

Chains, in order:

1. **pht-lint --changed** — lints the .py files your change touches
   (worktree + index + untracked + commits since the merge-base with
   main); PHT003's lock graph still spans the whole scope.
2. **test-budget drift** — ``tools/test_budget.py`` diffs a
   ``pytest --durations=0`` log against ``tests/conftest.py _FILE_COST``
   so budget drift fails HERE instead of as an RC=137 archaeology
   session.  Runs when ``--durations`` is given or the default log
   exists; otherwise SKIPPED with the command to produce one (a lint-only
   change doesn't need a suite run, so a missing log is not a failure).
3. **jaxcompat canary** — imports the bridge symbols in a subprocess
   (``core/jaxcompat.py`` has been wiped by a re-seed before; a broken
   bridge must fail the pre-PR check loudly, not as a downstream XLA
   abort).

Exit codes (perf_gate convention): 0 = every step that ran passed,
1 = at least one step failed, 2 = usage error.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_DURATIONS = "/tmp/durations.log"

_CANARY = (
    "from paddle_hackathon_tpu.core import jaxcompat\n"
    "import jax\n"
    "assert callable(jaxcompat.shard_map), 'jaxcompat.shard_map gone'\n"
    "assert callable(jaxcompat.set_mesh), 'jaxcompat.set_mesh gone'\n"
    "assert hasattr(jax, 'export'), 'jax.export bridge gone'\n"
    "print('jaxcompat bridge symbols present')\n"
)


def _run_step(name: str, argv, results, display=None) -> None:
    print(f"== {name}: {display or ' '.join(argv)}")
    proc = subprocess.run(argv, cwd=REPO_ROOT)
    ok = proc.returncode == 0
    results.append((name, "PASS" if ok else f"FAIL (rc={proc.returncode})"))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python tools/precommit.py",
        formatter_class=argparse.RawDescriptionHelpFormatter,
        description=__doc__)
    ap.add_argument("--durations", default=None,
                    help="pytest --durations=0 log for the budget drift "
                         f"check (default: {DEFAULT_DURATIONS} when it "
                         "exists; otherwise the step is skipped)")
    ap.add_argument("--stats", action="store_true",
                    help="pass --stats through to pht-lint (per-rule "
                         "counts + per-pass wall time)")
    ap.add_argument("--skip-canary", action="store_true",
                    help="skip the jaxcompat import canary (it imports "
                         "jax: ~10s)")
    args = ap.parse_args(argv)

    results = []

    lint_cmd = [sys.executable, "-m", "tools.pht_lint", "--changed"]
    if args.stats:
        lint_cmd.append("--stats")
    _run_step("pht-lint", lint_cmd, results)

    durations = args.durations
    if durations is None and os.path.exists(DEFAULT_DURATIONS):
        durations = DEFAULT_DURATIONS
    if durations is not None:
        if not os.path.exists(durations):
            print(f"precommit: durations log {durations!r} not found",
                  file=sys.stderr)
            return 2
        _run_step("test-budget",
                  [sys.executable, "tools/test_budget.py", durations],
                  results)
    else:
        results.append(("test-budget", "SKIP (no durations log)"))
        print("== test-budget: SKIPPED — to include it:\n"
              "   python -m pytest tests/ -q -m 'not slow' --durations=0 "
              "-p no:cacheprovider | tee /tmp/durations.log")

    if args.skip_canary:
        results.append(("jaxcompat-canary", "SKIP (--skip-canary)"))
    else:
        _run_step("jaxcompat-canary",
                  [sys.executable, "-c", _CANARY], results,
                  display="python -c '<import the jaxcompat bridge "
                          "symbols>'")

    print("\nprecommit summary:")
    width = max(len(n) for n, _ in results)
    for name, status in results:
        print(f"  {name:<{width}}  {status}")
    return 1 if any(s.startswith("FAIL") for _, s in results) else 0


if __name__ == "__main__":
    sys.exit(main())
