"""Shared jax-profiler trace parsing: per-op device durations on the
"XLA Ops" threads.  The trace-file format (thread_name metadata, X events)
is owned here so the tools that depend on it (trace_step, trace_model,
gen_op_benchmark) cannot drift apart when the schema changes.
"""
import collections
import glob
import gzip
import json
import os


def bucket_by_mnemonic(durs):
    """Aggregate per-op durations into mnemonic buckets (fusion, copy,
    dot, ...) — shared by trace_step and trace_model."""
    agg = collections.Counter()
    for name, dur in durs.items():
        base = name.split(".")[0].rstrip("0123456789_")
        if "fusion" in name:
            base = "fusion"
        agg[base] += dur
    return agg


def xla_op_durations_ms(outdir):
    """Counter of {op name: total device ms} summed over every event on an
    "XLA Ops" thread in the newest trace under ``outdir``."""
    paths = glob.glob(os.path.join(outdir, "**", "*.trace.json.gz"),
                      recursive=True)
    if not paths:
        return collections.Counter()
    with gzip.open(max(paths, key=os.path.getmtime), "rt") as fh:
        trace = json.load(fh)
    events = trace["traceEvents"]
    tids = {(e["pid"], e["tid"]): e["args"]["name"] for e in events
            if e.get("ph") == "M" and e.get("name") == "thread_name"}
    op_tids = {k for k, v in tids.items() if "XLA Ops" in v}
    durs = collections.Counter()
    for e in events:
        if e.get("ph") == "X" and (e.get("pid"), e.get("tid")) in op_tids:
            durs[e["name"]] += e.get("dur", 0) / 1e3
    return durs
