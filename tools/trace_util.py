"""Shared jax-profiler trace parsing: per-op device durations on the
"XLA Ops" threads.  The trace-file format (thread_name metadata, X events)
is owned here so the tools that depend on it (trace_step, trace_model,
gen_op_benchmark) cannot drift apart when the schema changes.
"""
import collections
import glob
import gzip
import json
import os


def bucket_by_mnemonic(durs):
    """Aggregate per-op durations into mnemonic buckets (fusion, copy,
    dot, ...) — shared by trace_step and trace_model."""
    agg = collections.Counter()
    for name, dur in durs.items():
        base = name.split(".")[0].rstrip("0123456789_")
        if "fusion" in name:
            base = "fusion"
        agg[base] += dur
    return agg


def _xla_ops_events(outdir):
    """X events on "XLA Ops" threads of the newest trace under ``outdir``,
    as [(thread_key, name, ts, dur_us)] — the single owner of the
    trace-file schema (thread_name metadata + X events)."""
    paths = glob.glob(os.path.join(outdir, "**", "*.trace.json.gz"),
                      recursive=True)
    if not paths:
        return []
    with gzip.open(max(paths, key=os.path.getmtime), "rt") as fh:
        trace = json.load(fh)
    events = trace["traceEvents"]
    tids = {(e["pid"], e["tid"]): e["args"]["name"] for e in events
            if e.get("ph") == "M" and e.get("name") == "thread_name"}
    op_tids = {k for k, v in tids.items() if "XLA Ops" in v}
    return [((e["pid"], e["tid"]), e["name"], e["ts"], e.get("dur", 0))
            for e in events
            if e.get("ph") == "X" and (e.get("pid"), e.get("tid")) in op_tids]


def xla_op_durations_ms(outdir):
    """Counter of {op name: total device ms} summed over every event on an
    "XLA Ops" thread in the newest trace under ``outdir``."""
    durs = collections.Counter()
    for _, name, _, dur in _xla_ops_events(outdir):
        durs[name] += dur / 1e3
    return durs


def toplevel_device_ms(outdir):
    """Total device ms counting nested ops ONCE: a ``while`` op's X event
    spans its whole loop execution and the body ops appear as separate
    events inside that span — summing all durations double-counts. Sums
    only events not contained in an earlier event's span on the same
    XLA-Ops thread."""
    per_thread = collections.defaultdict(list)
    for key, _, ts, dur in _xla_ops_events(outdir):
        per_thread[key].append((ts, dur))
    total = 0.0
    for evs in per_thread.values():
        evs.sort()
        cover_end = -1.0
        for ts, dur in evs:
            if ts >= cover_end:
                total += dur
                cover_end = ts + dur
            elif ts + dur > cover_end:   # partial overlap: count the tail
                total += ts + dur - cover_end
                cover_end = ts + dur
    return total / 1e3
