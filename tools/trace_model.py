"""Trace the conv-model train/infer steps (ResNet-50 / PP-YOLOE) and
aggregate per-op device durations from the profiler trace — the same
methodology that found the ERNIE MLM-head relayout win (BASELINE.md
round-3 notes; wall-clock microbenches through the axon tunnel lie).

Usage: python tools/trace_model.py [resnet|resnet-infer] [batch]
"""
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

from trace_util import bucket_by_mnemonic, xla_op_durations_ms

REPS = 3


def _aggregate(outdir, reps, norm_label):
    ind = xla_op_durations_ms(outdir)
    agg = bucket_by_mnemonic(ind)
    total = sum(ind.values())
    print(f"total device op time: {total / reps:.2f} ms/step ({norm_label})")
    for name, dur in agg.most_common(25):
        print(f"  {name:40s} {dur / reps:8.2f} ms")
    print("top individual ops:")
    for name, dur in ind.most_common(30):
        print(f"  {name:70s} {dur / reps:8.2f} ms")


def build_resnet_train(batch):
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu import parallel
    from paddle_hackathon_tpu.core import random as core_random
    from paddle_hackathon_tpu.core.tensor import Tensor
    from paddle_hackathon_tpu.nn.functional.loss import fused_softmax_ce_rows
    from paddle_hackathon_tpu.nn.layer import functional_call
    from paddle_hackathon_tpu.vision.models import resnet50

    paddle.seed(0)
    model = resnet50()

    def loss_fn(model, params, buffers, batch_, rng):
        images, labels = batch_
        with core_random.rng_scope(rng):
            logits = functional_call(model, params, (Tensor(images),),
                                     buffers=dict(buffers))
        lg = logits._value if isinstance(logits, Tensor) else logits
        return jnp.mean(fused_softmax_ce_rows(lg, labels))

    mesh = parallel.create_mesh({"dp": 1}, devices=jax.devices()[:1])
    step, state = parallel.make_sharded_train_step(
        model, mesh, rule=None, learning_rate=1e-4, zero_stage=0,
        param_dtype=jnp.bfloat16, loss_fn=loss_fn)
    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.randn(batch, 3, 224, 224), jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, 1000, (batch,)), jnp.int32)
    key = jax.random.key(0)

    def run():
        nonlocal state
        for _ in range(REPS):
            state, loss = step(state, images, labels, key)
        float(loss)

    return run


def build_resnet_infer(batch):
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu.core.tensor import Tensor
    from paddle_hackathon_tpu.nn.layer import functional_call
    from paddle_hackathon_tpu.vision.models import resnet50

    paddle.seed(0)
    model = resnet50()
    model.eval()
    params, buffers = model.functional_state()

    def _bf16(d):
        return {k: v.astype(jnp.bfloat16) if jnp.issubdtype(
            v.dtype, jnp.floating) else v for k, v in d.items()}

    params, buffers = _bf16(params), _bf16(buffers)

    @jax.jit
    def fwd(params, x):
        return functional_call(model, params, (Tensor(x),), buffers=buffers,
                               training=False)

    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.randn(batch, 3, 224, 224), jnp.bfloat16)

    def run():
        out = None
        for _ in range(REPS):
            out = fwd(params, images)
        jax.block_until_ready(out)

    return run


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "resnet"
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else (
        512 if which == "resnet-infer" else 256)
    outdir = "/tmp/trace_model"
    run = {"resnet": build_resnet_train,
           "resnet-infer": build_resnet_infer}[which](batch)
    run()  # warm/compile
    run()
    shutil.rmtree(outdir, ignore_errors=True)
    jax.profiler.start_trace(outdir)
    run()
    jax.profiler.stop_trace()
    _aggregate(outdir, REPS, f"{which} bs={batch}")


if __name__ == "__main__":
    main()
