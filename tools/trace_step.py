"""Trace the gpt2 train step and aggregate per-op durations from the
profiler's trace (the only trustworthy per-op numbers through the axon
tunnel — see BASELINE notes; wall-clock microbenches lie)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np


def main(batch=32, seqlen=1024, outdir="/tmp/trace_step"):
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu import parallel
    from paddle_hackathon_tpu.models import (GPTForCausalLM, gpt_config,
                                             param_sharding_spec)
    paddle.seed(0)
    cfg = gpt_config("gpt2-small-en", hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    model = GPTForCausalLM(cfg)
    mesh = parallel.create_mesh({"dp": 1}, devices=jax.devices()[:1])
    step, state = parallel.make_sharded_train_step(
        model, mesh, rule=param_sharding_spec, learning_rate=1e-4,
        zero_stage=0, param_dtype=jnp.bfloat16)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seqlen)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seqlen)), jnp.int32)
    key = jax.random.key(0)
    for _ in range(3):
        state, loss = step(state, ids, labels, key)
    float(loss)
    import shutil
    shutil.rmtree(outdir, ignore_errors=True)
    jax.profiler.start_trace(outdir)
    for _ in range(3):
        state, loss = step(state, ids, labels, key)
    float(loss)
    jax.profiler.stop_trace()

    from trace_util import bucket_by_mnemonic, xla_op_durations_ms
    ind = xla_op_durations_ms(outdir)
    agg = bucket_by_mnemonic(ind)
    total = sum(ind.values())
    print(f"total device op time: {total/3:.2f} ms/step  "
          f"({batch*seqlen*3/ (total/1e3):,.0f} tok/s-equivalent)")
    for name, dur in agg.most_common(30):
        print(f"  {name:40s} {dur/3:8.2f} ms")
    print("top individual ops:")
    for name, dur in ind.most_common(25):
        print(f"  {name:60s} {dur/3:8.2f} ms")


if __name__ == "__main__":
    main()
