"""Decompose the GPT train-step time on the real chip.

Times (a) forward loss only, (b) forward+backward, (c) the full train step
(fwd+bwd+clip+Adam), plus a pure-matmul MXU calibration at the model's
dominant shapes, so the MFU gap can be attributed to a phase instead of
guessed at.  Not a test — a tuning tool (ref tools/ci_op_benchmark.sh
gathers per-op numbers the same way).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, steps=10, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / steps


def main():
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu import parallel
    from paddle_hackathon_tpu.models import (GPTForCausalLM, gpt_config,
                                             param_sharding_spec)
    from paddle_hackathon_tpu.nn.layer import functional_call
    from paddle_hackathon_tpu.core.tensor import Tensor

    paddle.seed(0)
    cfg = gpt_config("gpt2-small-en", hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    batch, seqlen = 24, 1024
    cfg.max_position_embeddings = max(cfg.max_position_embeddings, seqlen)

    model = GPTForCausalLM(cfg)
    mesh = parallel.create_mesh({"dp": 1}, devices=jax.devices()[:1])
    step, state = parallel.make_sharded_train_step(
        model, mesh, rule=param_sharding_spec, learning_rate=1e-4,
        zero_stage=0, param_dtype=jnp.bfloat16)

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seqlen)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seqlen)),
                         jnp.int32)
    key = jax.random.key(0)

    params = state["params"]
    _, buffers = model.functional_state()

    from paddle_hackathon_tpu.nn.functional.loss import fused_softmax_ce_rows
    from paddle_hackathon_tpu.core import random as core_random

    def loss_fn(p):
        with core_random.rng_scope(key):
            logits = functional_call(model, p, (Tensor(ids),),
                                     buffers=dict(buffers))
        lg = logits._value if isinstance(logits, Tensor) else logits
        return jnp.mean(fused_softmax_ce_rows(lg, labels))

    fwd = jax.jit(loss_fn)
    fwdbwd = jax.jit(lambda p: jax.value_and_grad(loss_fn)(p)[0])

    t_fwd = timeit(fwd, params)
    t_fwdbwd = timeit(fwdbwd, params)

    # step() mutates python-side state dict; time it directly
    for _ in range(3):
        state, loss = step(state, ids, labels, key)
    float(loss)
    t0 = time.perf_counter()
    n = 10
    for _ in range(n):
        state, loss = step(state, ids, labels, key)
    float(loss)
    t_step = (time.perf_counter() - t0) / n

    # MXU calibration: model-shaped matmul chain in bf16
    h, ffn, v = cfg.hidden_size, 4 * cfg.hidden_size, cfg.vocab_size
    tok = batch * seqlen
    a = jnp.zeros((tok, h), jnp.bfloat16)
    w1 = jnp.zeros((h, ffn), jnp.bfloat16)
    w2 = jnp.zeros((ffn, h), jnp.bfloat16)
    wv = jnp.zeros((h, v), jnp.bfloat16)

    @jax.jit
    def mm(a):
        x = a @ w1
        y = x @ w2
        z = y @ wv
        return jnp.sum(z.astype(jnp.float32))

    t_mm = timeit(mm, a)
    fl_mm = 2 * tok * (h * ffn + ffn * h + h * v)

    # model flops (fwd): 6*N per token approx via params; use 2*N_matmul
    n_params = sum(int(np.prod(v.shape)) for v in jax.tree.leaves(params))
    fl_fwd = 2 * n_params * tok + 2 * 2 * batch * cfg.num_layers * \
        cfg.num_heads * seqlen * seqlen * (cfg.hidden_size // cfg.num_heads)
    fl_step = 3 * fl_fwd  # fwd + 2x bwd

    peak = 394e12  # v5e bf16
    tok_s = tok / t_step
    print(f"fwd      {t_fwd*1e3:8.2f} ms  ({fl_fwd/t_fwd/1e12:6.1f} TF/s, "
          f"{fl_fwd/t_fwd/peak*100:5.1f}% MFU)")
    print(f"fwd+bwd  {t_fwdbwd*1e3:8.2f} ms  ({fl_step/t_fwdbwd/1e12:6.1f} TF/s, "
          f"{fl_step/t_fwdbwd/peak*100:5.1f}% MFU)")
    print(f"step     {t_step*1e3:8.2f} ms  ({fl_step/t_step/1e12:6.1f} TF/s, "
          f"{fl_step/t_step/peak*100:5.1f}% MFU)  {tok_s:,.0f} tok/s")
    print(f"opt+clip {(t_step-t_fwdbwd)*1e3:8.2f} ms  (step - fwdbwd)")
    print(f"bwd      {(t_fwdbwd-t_fwd)*1e3:8.2f} ms  (fwdbwd - fwd)")
    print(f"mxu cal  {t_mm*1e3:8.2f} ms  ({fl_mm/t_mm/1e12:6.1f} TF/s, "
          f"{fl_mm/t_mm/peak*100:5.1f}% of peak) at model shapes")


if __name__ == "__main__":
    main()
