"""Generate the committed op-time snapshot
(``paddle_hackathon_tpu/cost_model/static_op_benchmark.json``) by timing
~55 hot ops on the local accelerator.

Schema mirrors the reference's ``static_op_benchmark.json`` (the CI op gate
input, ``tools/ci_op_benchmark.sh:117``) with ``paddle_gpu_time`` holding
this framework's measured device ms.

Timing method (default): the N-queued-reps + one float() sync wall
pattern — honest for the multi-ms shapes used here, where dispatch
pipelines fully under the op (BASELINE.md axon-tunnel notes).
``GEN_OPS_TRACE=1`` switches to exact per-op profiler traces (sums on the
"XLA Ops" thread), which cost seconds per op through the tunnel.
"""

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np


def _trace_device_ms(run, outdir):
    from trace_util import xla_op_durations_ms
    shutil.rmtree(outdir, ignore_errors=True)
    jax.profiler.start_trace(outdir)
    run()
    jax.profiler.stop_trace()
    durs = xla_op_durations_ms(outdir)
    return sum(durs.values()) if durs else None


def device_time(fn, *args, reps=20):
    """Device ms per execution.

    Default: the N-queued-reps + one float() sync wall pattern — honest
    for the multi-ms shapes used here (dispatch pipelines under the op;
    BASELINE.md axon-tunnel notes).  ``GEN_OPS_TRACE=1`` switches to
    per-op profiler traces (exact device ms, but each trace costs seconds
    through the tunnel — too slow for the full 60-op sweep there)."""
    jfn = fn if hasattr(fn, "lower") else jax.jit(fn)  # accept pre-jitted
    out = jfn(*args)
    float(jnp.sum(jnp.ravel(jax.tree.leaves(out)[0])[:1]).astype(jnp.float32))

    def run():
        o = out
        for _ in range(reps):
            o = jfn(*args)
        float(jnp.sum(jnp.ravel(jax.tree.leaves(o)[0])[:1])
              .astype(jnp.float32))

    if os.environ.get("GEN_OPS_TRACE") == "1":
        with tempfile.TemporaryDirectory() as d:
            ms = _trace_device_ms(run, d)
        if ms is not None:
            return ms / reps
    t0 = time.perf_counter()
    run()
    return (time.perf_counter() - t0) / reps * 1e3


# Module level with static shape/dtype args: a stable jit identity, so
# repeated shapes hit the cache instead of retracing a fresh lambda per
# operand (pht-lint PHT002).
def _rnd_impl(k, shape, dtype):
    return jax.random.normal(k, shape, jnp.float32).astype(dtype)


def _rint_impl(k, shape, hi):
    return jax.random.randint(k, shape, 0, hi, jnp.int32)


_rnd_impl = jax.jit(_rnd_impl, static_argnums=(1, 2))
_rint_impl = jax.jit(_rint_impl, static_argnums=(1, 2))


def build_ops():
    # ALL inputs are generated ON DEVICE (jax.random): materializing these
    # ~3 GB of operands host-side and pushing them through the axon tunnel
    # stalls for many minutes before the first op even compiles
    _key_iter = iter(jax.random.split(jax.random.key(0), 40))

    def _rnd(shape, dtype=jnp.float32):
        return _rnd_impl(next(_key_iter), tuple(shape), dtype)

    def _rint(shape, hi):
        return _rint_impl(next(_key_iter), tuple(shape), int(hi))
    # elementwise workhorse shape: big enough that per-call dispatch noise
    # vanishes under the op (~6 ms/pass f32)
    x4 = _rnd((16, 128, 257, 257), jnp.float32)
    x4b = _rnd((16, 128, 257, 257), jnp.bfloat16)
    m1 = _rnd((1024, 1024), jnp.float32)
    m2 = _rnd((1024, 1024), jnp.float32)
    # model-shaped matmuls (gpt2 ffn / vocab head, bf16 MXU path)
    a_tok = _rnd((8192, 768), jnp.bfloat16)
    w_ffn = _rnd((768, 3072), jnp.bfloat16)
    w_voc = _rnd((768, 50304), jnp.bfloat16)
    img = _rnd((32, 64, 56, 56), jnp.float32)
    ker = _rnd((64, 64, 3, 3), jnp.float32)
    ker1 = _rnd((256, 64, 1, 1), jnp.float32)
    imgb = _rnd((64, 256, 56, 56), jnp.bfloat16)
    kerb = _rnd((64, 256, 1, 1), jnp.bfloat16)
    seq = _rnd((32, 1024, 768), jnp.float32)
    logits = _rnd((8192, 50304), jnp.float32)
    lab = _rint((8192,), 50304)
    emb = _rnd((50304, 768), jnp.float32)
    ids = _rint((32, 1024), 50304)
    key = jax.random.key(0)

    def conv(x, k, stride=1):
        return jax.lax.conv_general_dilated(x, k, (stride, stride), "SAME")

    def adam(p, g, m, v):
        m2_ = 0.9 * m + 0.1 * g
        v2_ = 0.95 * v + 0.05 * g * g
        return p - 1e-3 * m2_ / (jnp.sqrt(v2_) + 1e-8), m2_, v2_

    big = "x (Variable) - dtype: float32, shape: [16, 128, 257, 257]\n"
    bigb = "x (Variable) - dtype: bfloat16, shape: [16, 128, 257, 257]\n"
    tokc = "x bf16 [8192, 768]"
    seqc = "x f32 [32, 1024, 768]"

    ew = {  # elementwise family on the workhorse shape (fwd + bwd)
        "abs": jnp.abs, "relu": jax.nn.relu, "exp": jnp.exp,
        "tanh": jnp.tanh, "sigmoid": jax.nn.sigmoid,
        "gelu": lambda x: jax.nn.gelu(x, approximate=True),
        "silu": jax.nn.silu, "erf": jax.lax.erf,
        "log": lambda x: jnp.log(jnp.abs(x) + 1e-6),
        "sqrt": lambda x: jnp.sqrt(jnp.abs(x)),
        "rsqrt": lambda x: jax.lax.rsqrt(jnp.abs(x) + 1e-6),
        "square": jnp.square, "floor": jnp.floor, "sign": jnp.sign,
        "clip": lambda x: jnp.clip(x, -1.0, 1.0),
    }
    binw = {
        "elementwise_add": jnp.add, "elementwise_mul": jnp.multiply,
        "elementwise_sub": jnp.subtract,
        "elementwise_div": lambda a, b: a / (jnp.abs(b) + 1.0),
        "elementwise_max": jnp.maximum, "elementwise_min": jnp.minimum,
        "elementwise_pow": lambda a, b: jnp.power(jnp.abs(a) + 1e-3, 2.0),
        "where": lambda a, b: jnp.where(a > 0, a, b),
    }
    ops = {}
    for name, fn in ew.items():
        ops[name] = (fn, (x4,), big, True)
    for name, fn in binw.items():
        ops[name] = (fn, (x4, x4), big, True)
    ops.update({
        "softmax": (lambda x: jax.nn.softmax(x, axis=-1), (x4,), big, True),
        "log_softmax": (lambda x: jax.nn.log_softmax(x, axis=-1), (x4,),
                        big, True),
        "mean": (jnp.mean, (x4,), big, True),
        "sum": (jnp.sum, (x4,), big, True),
        "reduce_max": (jnp.max, (x4,), big, True),
        "cumsum": (lambda x: jnp.cumsum(x, axis=-1), (x4,), big, True),
        "cast_bf16": (lambda x: x.astype(jnp.bfloat16), (x4,), big, False),
        "transpose": (lambda x: jnp.swapaxes(x, -1, -2), (x4,), big, False),
        "concat": (lambda a, b: jnp.concatenate([a, b], -1), (x4b, x4b),
                   bigb, False),
        "split": (lambda x: jnp.split(x, 2, axis=1)[0], (x4,), big, False),
        "pad": (lambda x: jnp.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1))),
                (x4b,), bigb, False),
        "slice": (lambda x: x[:, :, 1:-1, 1:-1], (x4,), big, False),
        "matmul": (jnp.matmul, (m1, m2), "x f32 [1024,1024] @ [1024,1024]",
                   True),
        "matmul_ffn_bf16": (jnp.matmul, (a_tok, w_ffn),
                            tokc + " @ [768, 3072]", True),
        "matmul_vocab_bf16": (jnp.matmul, (a_tok, w_voc),
                              tokc + " @ [768, 50304]", True),
        "conv2d": (conv, (img, ker), "x f32 [32,64,56,56]; w [64,64,3,3]",
                   True),
        "conv2d_1x1": (lambda x, k: conv(x, k), (img, ker1),
                       "x f32 [32,64,56,56]; w [256,64,1,1]", True),
        "conv2d_1x1_bf16": (lambda x, k: conv(x, k), (imgb, kerb),
                            "x bf16 [64,256,56,56]; w [64,256,1,1]", True),
        "layer_norm": (lambda x: jax.nn.standardize(x, axis=-1), (seq,),
                       seqc, True),
        "batch_norm_infer": (
            lambda x: (x - jnp.mean(x, (0, 2, 3), keepdims=True))
            * jax.lax.rsqrt(jnp.var(x, (0, 2, 3), keepdims=True) + 1e-5),
            (img,), "x f32 [32,64,56,56]", True),
        "max_pool2d": (
            lambda x: jax.lax.reduce_window(
                x, -jnp.inf, jax.lax.max, (1, 1, 2, 2), (1, 1, 2, 2),
                "VALID"), (img,), "x f32 [32,64,56,56] k2s2", True),
        "avg_pool2d": (
            lambda x: jax.lax.reduce_window(
                x, 0.0, jax.lax.add, (1, 1, 2, 2), (1, 1, 2, 2),
                "VALID") / 4.0, (img,), "x f32 [32,64,56,56] k2s2", True),
        "embedding_lookup": (lambda w, i: jnp.take(w, i, axis=0),
                             (emb, ids), "w f32 [50304,768]; ids [32,1024]",
                             True),
        "one_hot": (lambda i: jax.nn.one_hot(i, 50304, dtype=jnp.bfloat16),
                    (lab,), "ids [8192] -> [8192, 50304]", False),
        "gather_rows": (
            lambda lg, i: jnp.take_along_axis(lg, i[:, None], axis=1),
            (logits, lab), "logits f32 [8192, 50304]", True),
        "argmax": (lambda x: jnp.argmax(x, axis=-1), (logits,),
                   "logits f32 [8192, 50304]", False),
        "top_k": (lambda x: jax.lax.top_k(x, 8)[0], (logits,),
                  "logits f32 [8192, 50304] k=8", False),
        "softmax_ce_fused": (
            lambda lg, i: jnp.mean(
                jax.nn.logsumexp(lg, axis=-1)
                - jnp.take_along_axis(lg, i[:, None], axis=1)[:, 0]),
            (logits, lab), "fused lse-gather CE rows [8192, 50304]", True),
        "dropout": (
            lambda x: x * (jax.random.bernoulli(key, 0.9, x.shape)
                           / 0.9).astype(x.dtype),
            (seq,), seqc, True),
        "adam_update": (adam, (m1, m2, m1 * 0.1, jnp.abs(m2) * 0.1),
                        "p/g/m/v f32 [1024, 1024] fused update", False),
        "global_norm": (
            lambda a, b: jnp.sqrt(jnp.sum(jnp.square(a))
                                  + jnp.sum(jnp.square(b))),
            (m1, m2), "grad-norm over two [1024,1024] leaves", False),
        "flip": (lambda x: jnp.flip(x, axis=-1), (x4b,), bigb, False),
        "tril_mask": (
            lambda x: jnp.where(
                jnp.arange(x.shape[-1])[None, :]
                <= jnp.arange(x.shape[-2])[:, None], x, -1e30),
            (_rnd((1024, 1024), jnp.float32),),
            "causal mask [1024, 1024]", False),
    })

    # the perf-critical Pallas kernel itself
    from paddle_hackathon_tpu.incubate.nn.kernels import (
        flash_attention_packed as fap)
    qkv = _rnd((8, 1024, 3 * 768), jnp.bfloat16) * 0.1
    ops["flash_attention_packed"] = (
        lambda x: fap.flash_attention_packed(x, 12, True, 0.125), (qkv,),
        "packed qkv bf16 [8, 1024, 2304] causal", True)
    return ops


def main():
    ops = build_ops()
    rows = []
    stamp = time.strftime("%Y.%m%d.%H%M%S") + ".tpu-v5e"

    # compiles happen serially on first call inside device_time — threaded
    # pre-compilation deadlocks the remote compile helper
    for name, (fn, args, cfg, diff) in ops.items():
        fwd = device_time(fn, *args)
        bwd = 0.0
        if diff:
            def loss(*a, _fn=fn):
                out = _fn(*a)
                return jnp.sum(jax.tree.leaves(out)[0].astype(jnp.float32))
            darg = tuple(i for i, a in enumerate(args)
                         if jnp.issubdtype(a.dtype, jnp.floating))
            if darg:
                bwd = device_time(jax.grad(loss, argnums=darg), *args)
        rows.append({
            "name": f"{name}_0",
            "op": name,
            "op_count": 0,
            "config": cfg,
            "timestamp": stamp,
            "paddle_gpu_time": round(fwd, 4),
            "paddle_gpu_time_backward": round(bwd, 4),
            "device": ("tpu-v5e (trace-measured device ms)"
                       if os.environ.get("GEN_OPS_TRACE") == "1" else
                       "tpu-v5e (queued-reps wall ms; see module doc)"),
        })
        print(f"{name:24s} fwd {fwd:8.3f}  bwd {bwd:8.3f} ms")
    out = os.path.join(os.path.dirname(__file__), "..",
                       "paddle_hackathon_tpu", "cost_model",
                       "static_op_benchmark.json")
    with open(out, "w") as f:
        json.dump(rows, f, indent=1)
    print(f"wrote {len(rows)} ops to", out)


if __name__ == "__main__":
    main()
