"""Generate cost_model/static_op_benchmark.json by timing ops on the local
accelerator (run on the TPU chip; schema mirrors the reference's
``static_op_benchmark.json`` with paddle_gpu_time holding device ms)."""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, reps=20):
    jfn = jax.jit(fn)  # jit once; re-jitting per rep would time retracing
    out = jfn(*args)
    # hard sync through the axon tunnel
    float(jnp.sum(jax.tree.leaves(out)[0]).astype(jnp.float32))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = jfn(*args)
    float(jnp.sum(jax.tree.leaves(out)[0]).astype(jnp.float32))
    return (time.perf_counter() - t0) / reps * 1e3


def main():
    r = np.random.RandomState(0)
    x4 = jnp.asarray(r.randn(16, 128, 257, 257), jnp.float32)
    m1 = jnp.asarray(r.randn(1024, 1024), jnp.float32)
    m2 = jnp.asarray(r.randn(1024, 1024), jnp.float32)
    img = jnp.asarray(r.randn(32, 64, 56, 56), jnp.float32)
    ker = jnp.asarray(r.randn(64, 64, 3, 3), jnp.float32)

    def conv(x, k):
        return jax.lax.conv_general_dilated(x, k, (1, 1), "SAME")

    ops = {
        "abs": (jnp.abs, (x4,), "x (Variable) - dtype: float32, shape: [16, 128, 257, 257]\n"),
        "relu": (jax.nn.relu, (x4,), "x (Variable) - dtype: float32, shape: [16, 128, 257, 257]\n"),
        "exp": (jnp.exp, (x4,), "x (Variable) - dtype: float32, shape: [16, 128, 257, 257]\n"),
        "tanh": (jnp.tanh, (x4,), "x (Variable) - dtype: float32, shape: [16, 128, 257, 257]\n"),
        "sigmoid": (jax.nn.sigmoid, (x4,), "x (Variable) - dtype: float32, shape: [16, 128, 257, 257]\n"),
        "softmax": (lambda x: jax.nn.softmax(x, axis=-1), (x4,), "x (Variable) - dtype: float32, shape: [16, 128, 257, 257]\n"),
        "matmul": (jnp.matmul, (m1, m2), "x (Variable) - dtype: float32, shape: [1024, 1024]; y - float32 [1024, 1024]\n"),
        "conv2d": (conv, (img, ker), "x (Variable) - dtype: float32, shape: [32, 64, 56, 56]; w float32 [64, 64, 3, 3]\n"),
        "mean": (jnp.mean, (x4,), "x (Variable) - dtype: float32, shape: [16, 128, 257, 257]\n"),
        "sum": (jnp.sum, (x4,), "x (Variable) - dtype: float32, shape: [16, 128, 257, 257]\n"),
        "layer_norm": (lambda x: jax.nn.standardize(x, axis=-1), (x4,), "x (Variable) - dtype: float32, shape: [16, 128, 257, 257]\n"),
        "elementwise_add": (jnp.add, (x4, x4), "x, y (Variable) - dtype: float32, shape: [16, 128, 257, 257]\n"),
        "elementwise_mul": (jnp.multiply, (x4, x4), "x, y (Variable) - dtype: float32, shape: [16, 128, 257, 257]\n"),
        "log_softmax": (lambda x: jax.nn.log_softmax(x, axis=-1), (x4,), "x (Variable) - dtype: float32, shape: [16, 128, 257, 257]\n"),
        "sqrt": (jnp.sqrt, (jnp.abs(x4),), "x (Variable) - dtype: float32, shape: [16, 128, 257, 257]\n"),
    }
    rows = []
    stamp = time.strftime("%Y.%m%d.%H%M%S") + ".tpu-v5e"
    for i, (name, (fn, args, cfg)) in enumerate(ops.items()):
        fwd = timeit(fn, *args)

        def loss(*a):
            return jnp.sum(fn(*a))
        bwd = timeit(jax.grad(loss, argnums=tuple(range(len(args)))), *args)
        rows.append({
            "name": f"{name}_0",
            "op": name,
            "op_count": 0,
            "config": cfg,
            "timestamp": stamp,
            "paddle_gpu_time": round(fwd, 4),
            "paddle_gpu_time_backward": round(bwd, 4),
            "device": "tpu-v5e (this framework's measured device ms)",
        })
        print(name, round(fwd, 3), round(bwd, 3))
    out = os.path.join(os.path.dirname(__file__), "..",
                       "paddle_hackathon_tpu", "cost_model",
                       "static_op_benchmark.json")
    with open(out, "w") as f:
        json.dump(rows, f, indent=1)
    print("wrote", out)


if __name__ == "__main__":
    main()
