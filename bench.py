"""Benchmark: GPT pretraining tokens/sec/chip on the local accelerator.

North-star metric (BASELINE.md): ERNIE/GPT-class LM pretraining throughput.
Runs a full jitted train step (forward + backward + global-norm clip + Adam)
in bfloat16 on one chip and prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``vs_baseline`` compares against the previous recorded run (BENCH_r*.json) if
present, else 1.0 (the reference publishes no in-repo numbers — SURVEY §6).
"""

import glob
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def load_bench_history(root=None):
    """Parse the driver's BENCH_r*.json records (which wrap the metric
    under "parsed") into [(round, value, metric)], sorted by round.
    Shared by this script's vs_baseline and tools/perf_gate.py."""
    import re
    root = root or (os.path.dirname(os.path.abspath(__file__)) or ".")
    rounds = []
    for p in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        if not m:
            continue
        try:
            with open(p) as fh:
                data = json.load(fh)
            rec = data.get("parsed", data)
            rounds.append((int(m.group(1)), float(rec["value"]),
                           rec.get("metric", "?")))
        except (KeyError, TypeError, ValueError, OSError):
            continue
    return sorted(rounds)


def main():
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu import parallel
    from paddle_hackathon_tpu.models import GPTForCausalLM, gpt_config, param_sharding_spec

    paddle.seed(0)

    on_tpu = any(d.platform in ("tpu", "axon") for d in jax.devices())
    if on_tpu:
        cfg = gpt_config("gpt2-small-en", hidden_dropout_prob=0.0,
                         attention_dropout_prob=0.0)
        batch, seqlen = 32, 1024  # round-2 sweep with the packed-heads
        # kernels: 24/32/40/48 all ~137k tok/s, 32 edges ahead; bs=32
        # used to OOM before the packed layout freed the head-split copies
        steps, warmup = 10, 3
        param_dtype = jnp.bfloat16
    else:  # CPU smoke path so the script always works
        cfg = gpt_config("gpt2-small-en", num_layers=2, hidden_size=128,
                         num_heads=4, vocab_size=1024,
                         hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
        batch, seqlen = 2, 128
        steps, warmup = 3, 1
        param_dtype = jnp.float32
    cfg.max_position_embeddings = max(cfg.max_position_embeddings, seqlen)

    model = GPTForCausalLM(cfg)
    mesh = parallel.create_mesh({"dp": 1}, devices=jax.devices()[:1])
    step, state = parallel.make_sharded_train_step(
        model, mesh, rule=param_sharding_spec, learning_rate=1e-4,
        zero_stage=0, param_dtype=param_dtype)

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seqlen)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seqlen)), jnp.int32)
    key = jax.random.key(0)

    for i in range(warmup):
        state, loss = step(state, ids, labels, jax.random.fold_in(key, i))
    float(loss)  # hard sync (device->host) — block_until_ready alone is not
    # trustworthy through the axon tunnel

    t0 = time.perf_counter()
    for i in range(steps):
        state, loss = step(state, ids, labels, jax.random.fold_in(key, 100 + i))
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss)

    tokens_per_sec = batch * seqlen * steps / dt

    history = load_bench_history()
    prev = history[-1][1] if history else None
    vs_baseline = (tokens_per_sec / prev) if prev else 1.0

    print(json.dumps({
        "metric": "gpt2_small_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(vs_baseline, 4),
    }))


if __name__ == "__main__":
    sys.exit(main())
