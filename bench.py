"""Benchmark: GPT pretraining tokens/sec/chip on the local accelerator.

North-star metric (BASELINE.md): ERNIE/GPT-class LM pretraining throughput.
Runs a full jitted train step (forward + backward + global-norm clip + Adam)
in bfloat16 on one chip and prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``vs_baseline`` compares against the previous recorded run (BENCH_r*.json) if
present, else 1.0 (the reference publishes no in-repo numbers — SURVEY §6).

``--suite`` additionally measures the other BASELINE.md model rows (ERNIE
MLM, GPT-3 1.3B, long-context s=4096, ResNet-50 train) and prints one JSON
line per config — the input ``tools/perf_gate.py --suite`` gates against
``paddle_hackathon_tpu/cost_model/model_bench_baseline.json`` so those
configs can no longer regress silently (VERDICT r2 weak #3).
"""

import glob
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def load_bench_history(root=None):
    """Parse the driver's BENCH_r*.json records (which wrap the metric
    under "parsed") into [(round, value, metric)], sorted by round.
    Shared by this script's vs_baseline and tools/perf_gate.py."""
    import re
    root = root or (os.path.dirname(os.path.abspath(__file__)) or ".")
    rounds = []
    for p in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        if not m:
            continue
        try:
            with open(p) as fh:
                data = json.load(fh)
            rec = data.get("parsed", data)
            rounds.append((int(m.group(1)), float(rec["value"]),
                           rec.get("metric", "?")))
        except (KeyError, TypeError, ValueError, OSError):
            continue
    return sorted(rounds)


def _timed_steps(step, state, ids, labels, steps, warmup, attempts=2):
    """The trustworthy pattern through the axon tunnel: N dependent steps,
    one device->host float() sync (block_until_ready alone does not sync).
    Best of ``attempts`` timed blocks: tunnel jitter is strictly additive
    (it can slow a block, never speed it), so the minimum is the less
    biased estimate of chip throughput — single-block runs measured the
    same program 3.5% apart across tunnel weather."""
    key = jax.random.key(0)
    for i in range(warmup):
        state, loss = step(state, ids, labels, jax.random.fold_in(key, i))
    float(loss)
    best = None
    for a in range(attempts):
        t0 = time.perf_counter()
        for i in range(steps):
            state, loss = step(state, ids, labels,
                               jax.random.fold_in(key, 100 + a * steps + i))
        final_loss = float(loss)
        dt = time.perf_counter() - t0
        assert np.isfinite(final_loss)
        best = dt if best is None else min(best, dt)
    return best


def bench_gpt2(seqlen=1024, batch=32, preset="gpt2-small-en",
               metric="gpt2_small_pretrain_tokens_per_sec_per_chip",
               steps=100, warmup=5, moment_dtype=None):
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu import parallel
    from paddle_hackathon_tpu.models import (GPTForCausalLM, gpt_config,
                                             param_sharding_spec)
    paddle.seed(0)
    cfg = gpt_config(preset, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    cfg.max_position_embeddings = max(cfg.max_position_embeddings, seqlen)
    model = GPTForCausalLM(cfg)
    mesh = parallel.create_mesh({"dp": 1}, devices=jax.devices()[:1])
    step, state = parallel.make_sharded_train_step(
        model, mesh, rule=param_sharding_spec, learning_rate=1e-4,
        zero_stage=0, param_dtype=jnp.bfloat16, moment_dtype=moment_dtype)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seqlen)),
                      jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seqlen)),
                         jnp.int32)
    dt = _timed_steps(step, state, ids, labels, steps, warmup)
    return {"metric": metric, "value": round(batch * seqlen * steps / dt, 1),
            "unit": "tokens/s"}


def bench_ernie(batch=64, seqlen=512, steps=50, warmup=3):
    """ERNIE-3.0-base MLM pretraining (the north-star config family)."""
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu import parallel
    from paddle_hackathon_tpu.models import param_sharding_spec
    from paddle_hackathon_tpu.models.bert import (BertForPretraining,
                                                  bert_config)
    from paddle_hackathon_tpu.nn.layer import functional_call
    from paddle_hackathon_tpu.core.tensor import Tensor
    from paddle_hackathon_tpu.nn.functional.loss import fused_softmax_ce_rows
    from paddle_hackathon_tpu.core import random as core_random

    paddle.seed(0)
    cfg = bert_config("ernie-3.0-base-zh", hidden_dropout_prob=0.0,
                      attention_dropout_prob=0.0)
    model = BertForPretraining(cfg)
    mesh = parallel.create_mesh({"dp": 1}, devices=jax.devices()[:1])

    # masked_positions path (round 4): the data pipeline supplies the
    # flat masked indices + their labels — the reference's pretraining
    # heads contract — so the 40k-vocab MLM decode runs on ~15% of rows
    # instead of all b*s (the full-logits trio was 33 ms of the 204 ms
    # round-3 step).  K is padded to a static size; pad rows carry
    # label -1 and drop out of the CE.
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seqlen)),
                      jnp.int32)
    lab = rng.randint(0, cfg.vocab_size, (batch, seqlen))
    m = rng.rand(batch, seqlen) < 0.15   # 15% MLM masking
    flat_idx = np.where(m.reshape(-1))[0]
    K = -(-int(batch * seqlen * 0.16) // 512) * 512
    pos = np.zeros(K, np.int32)
    pos[:len(flat_idx)] = flat_idx
    glab = np.full(K, -1, np.int64)
    glab[:len(flat_idx)] = lab.reshape(-1)[flat_idx]
    pos = jnp.asarray(pos)
    labels = jnp.asarray(glab, jnp.int32)   # (K,) gathered labels

    def loss_fn(model, params, buffers, batch_, rng_key):
        b_ids, b_labels = batch_
        with core_random.rng_scope(rng_key):
            out = functional_call(model, params, (Tensor(b_ids),),
                                  kwargs={"masked_positions": Tensor(pos)},
                                  buffers=dict(buffers))
        lg = out[0]
        lg = lg._value if isinstance(lg, Tensor) else lg
        mask = b_labels >= 0
        rows = fused_softmax_ce_rows(lg, jnp.maximum(b_labels, 0))
        rows = jnp.where(mask, rows, 0.0)
        return jnp.sum(rows) / jnp.maximum(jnp.sum(mask), 1)

    step, state = parallel.make_sharded_train_step(
        model, mesh, rule=param_sharding_spec, learning_rate=1e-4,
        zero_stage=0, param_dtype=jnp.bfloat16, loss_fn=loss_fn)
    dt = _timed_steps(step, state, ids, labels, steps, warmup)
    return {"metric": "ernie_base_mlm_tokens_per_sec_per_chip",
            "value": round(batch * seqlen * steps / dt, 1),
            "unit": "tokens/s"}


def bench_resnet(batch=256, steps=50, warmup=3):
    """ResNet-50 bf16 training step (conv-heavy driver config)."""
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu import parallel
    from paddle_hackathon_tpu.vision.models import resnet50
    from paddle_hackathon_tpu.nn.layer import functional_call
    from paddle_hackathon_tpu.core.tensor import Tensor
    from paddle_hackathon_tpu.nn.functional.loss import fused_softmax_ce_rows
    from paddle_hackathon_tpu.core import random as core_random

    paddle.seed(0)
    model = resnet50()

    def loss_fn(model, params, buffers, batch_, rng):
        images, labels = batch_
        with core_random.rng_scope(rng):
            logits = functional_call(model, params, (Tensor(images),),
                                     buffers=dict(buffers))
        lg = logits._value if isinstance(logits, Tensor) else logits
        return jnp.mean(fused_softmax_ce_rows(lg, labels))

    mesh = parallel.create_mesh({"dp": 1}, devices=jax.devices()[:1])
    step, state = parallel.make_sharded_train_step(
        model, mesh, rule=None, learning_rate=1e-4, zero_stage=0,
        param_dtype=jnp.bfloat16, loss_fn=loss_fn)
    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.randn(batch, 3, 224, 224), jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, 1000, (batch,)), jnp.int32)
    dt = _timed_steps(step, state, images, labels, steps, warmup)
    return {"metric": "resnet50_train_imgs_per_sec_per_chip",
            "value": round(batch * steps / dt, 1), "unit": "imgs/s"}


def bench_ppyoloe(batch=64, size=640, steps=100, warmup=5):
    # ~17 ms/step: anything under ~30 steps is dominated by the single
    # device->host sync latency through the axon tunnel (measured 2.4k
    # imgs/s at 10 steps vs 3.8k at 100 — same compiled program)
    """PP-YOLOE-s 640x640 bf16 jitted inference (driver config #5,
    conv-heavy compiled path)."""
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu.core.tensor import Tensor
    from paddle_hackathon_tpu.models.ppyoloe import ppyoloe_s
    from paddle_hackathon_tpu.nn.layer import functional_call

    paddle.seed(0)
    model = ppyoloe_s()
    model.eval()
    params, buffers = model.functional_state()

    def _bf16(d):
        return {k: v.astype(jnp.bfloat16)
                if jnp.issubdtype(v.dtype, jnp.floating) else v
                for k, v in d.items()}

    params, buffers = _bf16(params), _bf16(buffers)

    @jax.jit
    def fwd(params, x):
        cls_logits, reg_dists = functional_call(
            model, params, (Tensor(x),), buffers=buffers, training=False)
        # return BOTH heads — jit dead-code-eliminates unused outputs, and
        # dropping reg_dists would bench a truncated model
        unwrap = lambda t: t._value if isinstance(t, Tensor) else t
        return ([unwrap(c) for c in cls_logits],
                [unwrap(r) for r in reg_dists])

    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.rand(batch, 3, size, size), jnp.bfloat16)
    out = None
    for _ in range(warmup):
        out = fwd(params, images)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fwd(params, images)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    return {"metric": "ppyoloe_s_infer_imgs_per_sec_per_chip",
            "value": round(batch * steps / dt, 1), "unit": "imgs/s"}


def bench_decode(batch=8, prompt=64, new_tokens=128):
    """One-program greedy decoding DEVICE throughput: one traced
    generate() call, summed top-level XLA-op device time (nested while
    bodies counted once). Wall clock through the axon tunnel is
    round-trip-bound (~100-160 ms per RTT, varying day to day) and
    measures the tunnel, not the chip — the round-3 "4,032 tok/s" row was
    ~2/3 tunnel latency (BASELINE.md round-4 decode notes)."""
    import shutil
    import tempfile

    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu.core.tensor import Tensor
    from paddle_hackathon_tpu.models import GPTForCausalLM, gpt_config

    paddle.seed(0)
    cfg = gpt_config("gpt2-small-en", hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    for _, p in model.named_parameters():
        if jnp.issubdtype(p._value.dtype, jnp.floating):
            p._set_value(p._value.astype(jnp.bfloat16))
    rng = np.random.RandomState(0)
    ids = Tensor(jnp.asarray(rng.randint(0, cfg.vocab_size,
                                         (batch, prompt)), jnp.int32))
    np.asarray(model.generate(ids, max_new_tokens=new_tokens,
                              temperature=0.0).numpy())  # compile+sync
    outdir = tempfile.mkdtemp(prefix="bench_decode_trace")
    try:
        jax.profiler.start_trace(outdir)
        try:
            out = np.asarray(model.generate(
                ids, max_new_tokens=new_tokens, temperature=0.0).numpy())
        finally:
            # a raise mid-trace must not leave the profiler running for
            # every subsequent suite row
            jax.profiler.stop_trace()
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tools"))
        from trace_util import toplevel_device_ms
        dev_ms = toplevel_device_ms(outdir)
    finally:
        shutil.rmtree(outdir, ignore_errors=True)
    assert out.shape == (batch, prompt + new_tokens)
    assert dev_ms > 0, "empty profiler trace"
    return {"metric": "gpt2_greedy_decode_device_tokens_per_sec_per_chip",
            "value": round(batch * new_tokens / (dev_ms / 1e3), 1),
            "unit": "tokens/s"}


SUITE = {
    "gpt2": lambda: bench_gpt2(),
    "ernie": lambda: bench_ernie(),
    # bs6 + bf16 Adam moments: the round-3 winning 1.3B config (+26%
    # over bs4/f32 — BASELINE.md; convergence parity pinned by
    # tests/test_moment_dtype.py; default moment dtype stays f32)
    "gpt3_1p3b": lambda: bench_gpt2(
        preset="gpt3-1.3B-en", batch=6, moment_dtype="bfloat16",
        metric="gpt3_1p3b_pretrain_tokens_per_sec_per_chip"),
    "long_context": lambda: bench_gpt2(
        seqlen=4096, batch=4,
        metric="gpt2_long_context_s4096_tokens_per_sec_per_chip"),
    "resnet": lambda: bench_resnet(),
    "ppyoloe": lambda: bench_ppyoloe(),
    "decode": lambda: bench_decode(),
}


def run_suite():
    """Each config runs in a FRESH subprocess: HBM-hungry rows (1.3B bs6
    fills ~15 of 16 GB) are not squeezed by buffers the earlier benches
    leave behind, and a transient axon-tunnel error fails one row, not
    the sweep (one retry per row)."""
    import subprocess
    rows = []
    me = os.path.abspath(__file__)
    for name in SUITE:
        for attempt in (1, 2):
            try:
                proc = subprocess.run(
                    [sys.executable, me, "--one", name],
                    capture_output=True, text=True, timeout=1500)
            except subprocess.TimeoutExpired:
                sys.stderr.write(
                    f"suite row {name} attempt {attempt} timed out\n")
                continue
            line = next((ln for ln in proc.stdout.splitlines()[::-1]
                         if ln.startswith("{")), None)
            if proc.returncode == 0 and line:
                rows.append(json.loads(line))
                print(line)
                break
            sys.stderr.write(
                f"suite row {name} attempt {attempt} failed:\n"
                f"{proc.stderr[-1500:]}\n")
        else:
            raise RuntimeError(f"suite row {name} failed twice")
    return rows


def main():
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu import parallel
    from paddle_hackathon_tpu.models import GPTForCausalLM, gpt_config, param_sharding_spec

    paddle.seed(0)

    if "--suite" in sys.argv:
        run_suite()
        return
    if "--one" in sys.argv:
        name = sys.argv[sys.argv.index("--one") + 1]
        print(json.dumps(SUITE[name]()))
        return

    on_tpu = any(d.platform in ("tpu", "axon") for d in jax.devices())
    if on_tpu:
        cfg = gpt_config("gpt2-small-en", hidden_dropout_prob=0.0,
                         attention_dropout_prob=0.0)
        batch, seqlen = 32, 1024  # round-2 sweep with the packed-heads
        # kernels: 24/32/40/48 all ~137k tok/s, 32 edges ahead; bs=32
        # used to OOM before the packed layout freed the head-split copies
        steps, warmup = 10, 3
        param_dtype = jnp.bfloat16
    else:  # CPU smoke path so the script always works
        cfg = gpt_config("gpt2-small-en", num_layers=2, hidden_size=128,
                         num_heads=4, vocab_size=1024,
                         hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
        batch, seqlen = 2, 128
        steps, warmup = 3, 1
        param_dtype = jnp.float32
    cfg.max_position_embeddings = max(cfg.max_position_embeddings, seqlen)

    model = GPTForCausalLM(cfg)
    mesh = parallel.create_mesh({"dp": 1}, devices=jax.devices()[:1])
    step, state = parallel.make_sharded_train_step(
        model, mesh, rule=param_sharding_spec, learning_rate=1e-4,
        zero_stage=0, param_dtype=param_dtype)

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seqlen)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seqlen)), jnp.int32)
    key = jax.random.key(0)

    for i in range(warmup):
        state, loss = step(state, ids, labels, jax.random.fold_in(key, i))
    float(loss)  # hard sync (device->host) — block_until_ready alone is not
    # trustworthy through the axon tunnel

    t0 = time.perf_counter()
    for i in range(steps):
        state, loss = step(state, ids, labels, jax.random.fold_in(key, 100 + i))
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss)

    tokens_per_sec = batch * seqlen * steps / dt

    history = load_bench_history()
    prev = history[-1][1] if history else None
    vs_baseline = (tokens_per_sec / prev) if prev else 1.0

    print(json.dumps({
        "metric": "gpt2_small_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(vs_baseline, 4),
    }))


if __name__ == "__main__":
    sys.exit(main())
