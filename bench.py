"""Benchmark: GPT pretraining tokens/sec/chip on the local accelerator.

North-star metric (BASELINE.md): ERNIE/GPT-class LM pretraining throughput.
Runs a full jitted train step (forward + backward + global-norm clip + Adam)
in bfloat16 on one chip and prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``vs_baseline`` compares against the previous recorded run (BENCH_r*.json) if
present, else 1.0 (the reference publishes no in-repo numbers — SURVEY §6).

``--suite`` additionally measures the other BASELINE.md model rows (ERNIE
MLM, GPT-3 1.3B, long-context s=4096, ResNet-50 train) and prints one JSON
line per config — the input ``tools/perf_gate.py --suite`` gates against
``paddle_hackathon_tpu/cost_model/model_bench_baseline.json`` so those
configs can no longer regress silently (VERDICT r2 weak #3).
"""

import glob
import json
import os
import sys
import time

import jax

# The axon environment's sitecustomize force-sets jax_platforms="axon,cpu",
# overriding the JAX_PLATFORMS env var — honor an explicit env setting so
# `JAX_PLATFORMS=cpu python bench.py` really runs the CPU smoke path
# (same pattern as tests/conftest.py).
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

# Program-observatory deep pass is always-on in bench (its per-build
# AOT memory/cost harvest is exactly the evidence a perf row should
# carry; builds happen during warm-up, so steady-state timing is
# unaffected).  setdefault: an explicit =0 still wins.  Inherited by
# the --one row subprocesses run_suite spawns.
os.environ.setdefault("PHT_PROGRAM_ANALYSIS", "1")

import jax.numpy as jnp
import numpy as np


def _programs_block():
    """The program-observatory evidence a bench row embeds:
    compile_seconds_total plus per-site builds/evictions and recent
    retrace causes — what ``perf_gate.suite_gate`` prints when the
    builds_warm/total tripwire fires, so a tripped gate names the site
    and the exact signature delta instead of just "a build happened"."""
    try:
        from paddle_hackathon_tpu.observability.programs import \
            get_program_registry
        return get_program_registry().bench_block()
    except Exception:
        return None


def load_bench_history(root=None):
    """Parse the driver's BENCH_r*.json records (which wrap the metric
    under "parsed") into [(round, value, metric)], sorted by round.
    Shared by this script's vs_baseline and tools/perf_gate.py."""
    import re
    root = root or (os.path.dirname(os.path.abspath(__file__)) or ".")
    rounds = []
    for p in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        if not m:
            continue
        try:
            with open(p) as fh:
                data = json.load(fh)
            rec = data.get("parsed", data)
            rounds.append((int(m.group(1)), float(rec["value"]),
                           rec.get("metric", "?")))
        except (KeyError, TypeError, ValueError, OSError):
            continue
    return sorted(rounds)


def _timed_steps(step, state, ids, labels, steps, warmup, attempts=2):
    """The trustworthy pattern through the axon tunnel: N dependent steps,
    one device->host float() sync (block_until_ready alone does not sync).
    Best of ``attempts`` timed blocks: tunnel jitter is strictly additive
    (it can slow a block, never speed it), so the minimum is the less
    biased estimate of chip throughput — single-block runs measured the
    same program 3.5% apart across tunnel weather."""
    key = jax.random.key(0)
    for i in range(warmup):
        state, loss = step(state, ids, labels, jax.random.fold_in(key, i))
    float(loss)
    best = None
    for a in range(attempts):
        t0 = time.perf_counter()
        for i in range(steps):
            state, loss = step(state, ids, labels,
                               jax.random.fold_in(key, 100 + a * steps + i))
        final_loss = float(loss)
        dt = time.perf_counter() - t0
        assert np.isfinite(final_loss)
        best = dt if best is None else min(best, dt)
    return best


def bench_gpt2(seqlen=1024, batch=32, preset="gpt2-small-en",
               metric="gpt2_small_pretrain_tokens_per_sec_per_chip",
               steps=100, warmup=5, moment_dtype=None,
               param_dtype=jnp.bfloat16, with_params=False, **cfg_kw):
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu import parallel
    from paddle_hackathon_tpu.models import (GPTForCausalLM, gpt_config,
                                             param_sharding_spec)
    paddle.seed(0)
    cfg = gpt_config(preset, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0, **cfg_kw)
    cfg.max_position_embeddings = max(cfg.max_position_embeddings, seqlen)
    model = GPTForCausalLM(cfg)
    active, total = parallel.moe_active_params(model)
    mesh = parallel.create_mesh({"dp": 1}, devices=jax.devices()[:1])
    step, state = parallel.make_sharded_train_step(
        model, mesh, rule=param_sharding_spec, learning_rate=1e-4,
        zero_stage=0, param_dtype=param_dtype, moment_dtype=moment_dtype)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seqlen)),
                      jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seqlen)),
                         jnp.int32)
    dt = _timed_steps(step, state, ids, labels, steps, warmup)
    row = {"metric": metric, "value": round(batch * seqlen * steps / dt, 1),
           "unit": "tokens/s"}
    if with_params:
        # active/total param counts (the gpt2_moe matched-active-params
        # evidence); opt-in — the headline row's key set is a pinned
        # contract the driver's BENCH_r*.json parser consumes
        row.update(params_active=active, params_total=total)
    return row


def bench_gpt2_moe():
    """MoE-GPT flagship pretraining row (ROADMAP item 5): the SAME-RUN
    throughput ratio of an expert-parallel GPT-2 variant against its
    dense reference at matched ACTIVE params — 8 experts of ffn 2h with
    top-2 routing activate exactly the dense 4h MLP per token, so
    tokens/s/chip is comparable per quality-FLOP while total params grow
    ~3.4x (the MoE scaling bet).  Both sides run in THIS process with
    identical batch/seq/steps; ``vs_dense_active_params`` embeds the
    ratio tools/perf_gate.py holds >= 0.6x (the MoE tax: capacity-padded
    expert einsums + dispatch/combine must not eat more than 40%).

    On CPU-only containers the pair scales down like the other smoke
    paths (ratio stays meaningful, absolute tokens/s are not chip
    numbers; ``"timing": "host"`` + a ``_cpu_smoke`` metric name keep it
    ungateable against device baselines)."""
    on_tpu = any(d.platform in ("tpu", "axon") for d in jax.devices())
    if on_tpu:
        common = dict(seqlen=1024, batch=16, steps=50, warmup=5)
        hidden = 768
        metric = "gpt2_moe_pretrain_tokens_per_sec_per_chip"
    else:
        common = dict(seqlen=128, batch=8, steps=12, warmup=3,
                      param_dtype=jnp.float32, num_layers=2,
                      hidden_size=128, num_heads=4, vocab_size=1024)
        hidden = 128
        metric = "gpt2_moe_pretrain_tokens_per_sec_cpu_smoke"
    moe_kw = dict(moe_num_experts=8, moe_topk=2, moe_gate="gshard",
                  moe_capacity_factor=1.25, intermediate_size=hidden * 2)
    if not on_tpu:
        # the auto group (512) is tuned for d=768+, where the (S, E, C)
        # dispatch einsums cost ~20% of the expert FFNs; at the smoke
        # config's d=128 that ratio scales by 6x and the dispatch
        # dominates — smaller groups restore the tax the gate prices
        moe_kw["moe_group_size"] = 128
    dense = bench_gpt2(metric="dense_ref", with_params=True, **common)
    moe = bench_gpt2(metric=metric, with_params=True, **moe_kw, **common)
    row = dict(moe)
    if not on_tpu:
        row["timing"] = "host"   # wall clock on CPU, like the smoke rows
    row.update({
        "dense_tokens_per_sec": dense["value"],
        "dense_params_total": dense["params_total"],
        "vs_dense_active_params": round(moe["value"] / dense["value"], 4),
        # active-param matching evidence: the MoE row's ACTIVE count vs
        # the dense model's total (embeddings identical, MLP matched)
        "active_vs_dense_params": round(
            moe["params_active"] / dense["params_total"], 4),
    })
    return row


def bench_ernie(batch=64, seqlen=512, steps=50, warmup=3):
    """ERNIE-3.0-base MLM pretraining (the north-star config family)."""
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu import parallel
    from paddle_hackathon_tpu.models import param_sharding_spec
    from paddle_hackathon_tpu.models.bert import (BertForPretraining,
                                                  bert_config)
    from paddle_hackathon_tpu.nn.layer import functional_call
    from paddle_hackathon_tpu.core.tensor import Tensor
    from paddle_hackathon_tpu.nn.functional.loss import fused_softmax_ce_rows
    from paddle_hackathon_tpu.core import random as core_random

    paddle.seed(0)
    cfg = bert_config("ernie-3.0-base-zh", hidden_dropout_prob=0.0,
                      attention_dropout_prob=0.0)
    model = BertForPretraining(cfg)
    mesh = parallel.create_mesh({"dp": 1}, devices=jax.devices()[:1])

    # masked_positions path (round 4): the data pipeline supplies the
    # flat masked indices + their labels — the reference's pretraining
    # heads contract — so the 40k-vocab MLM decode runs on ~15% of rows
    # instead of all b*s (the full-logits trio was 33 ms of the 204 ms
    # round-3 step).  K is padded to a static size; pad rows carry
    # label -1 and drop out of the CE.  pos + gathered labels travel as
    # per-step BATCH inputs (round 5 — they were jit closure constants,
    # which measured a step no data pipeline could feed).
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seqlen)),
                      jnp.int32)
    lab = rng.randint(0, cfg.vocab_size, (batch, seqlen))
    m = rng.rand(batch, seqlen) < 0.15   # 15% MLM masking
    flat_idx = np.where(m.reshape(-1))[0]
    K = -(-int(batch * seqlen * 0.16) // 512) * 512
    assert len(flat_idx) <= K, (len(flat_idx), K)
    pos = np.zeros(K, np.int32)
    pos[:len(flat_idx)] = flat_idx
    glab = np.full(K, -1, np.int64)
    glab[:len(flat_idx)] = lab.reshape(-1)[flat_idx]
    pos = jnp.asarray(pos)
    labels = jnp.asarray(glab, jnp.int32)   # (K,) gathered labels

    def loss_fn(model, params, buffers, batch_, rng_key):
        (b_ids, b_pos), b_labels = batch_
        with core_random.rng_scope(rng_key):
            out = functional_call(model, params, (Tensor(b_ids),),
                                  kwargs={"masked_positions": Tensor(b_pos)},
                                  buffers=dict(buffers))
        lg = out[0]
        lg = lg._value if isinstance(lg, Tensor) else lg
        mask = b_labels >= 0
        rows = fused_softmax_ce_rows(lg, jnp.maximum(b_labels, 0))
        rows = jnp.where(mask, rows, 0.0)
        return jnp.sum(rows) / jnp.maximum(jnp.sum(mask), 1)

    step, state = parallel.make_sharded_train_step(
        model, mesh, rule=param_sharding_spec, learning_rate=1e-4,
        zero_stage=0, param_dtype=jnp.bfloat16, loss_fn=loss_fn)
    dt = _timed_steps(step, state, (ids, pos), labels, steps, warmup)
    return {"metric": "ernie_base_mlm_tokens_per_sec_per_chip",
            "value": round(batch * seqlen * steps / dt, 1),
            "unit": "tokens/s"}


def bench_resnet(batch=256, steps=50, warmup=3):
    """ResNet-50 bf16 training step (conv-heavy driver config)."""
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu import parallel
    from paddle_hackathon_tpu.vision.models import resnet50
    from paddle_hackathon_tpu.nn.layer import functional_call
    from paddle_hackathon_tpu.core.tensor import Tensor
    from paddle_hackathon_tpu.nn.functional.loss import fused_softmax_ce_rows
    from paddle_hackathon_tpu.core import random as core_random

    paddle.seed(0)
    model = resnet50()

    def loss_fn(model, params, buffers, batch_, rng):
        images, labels = batch_
        with core_random.rng_scope(rng):
            logits = functional_call(model, params, (Tensor(images),),
                                     buffers=dict(buffers))
        lg = logits._value if isinstance(logits, Tensor) else logits
        return jnp.mean(fused_softmax_ce_rows(lg, labels))

    mesh = parallel.create_mesh({"dp": 1}, devices=jax.devices()[:1])
    step, state = parallel.make_sharded_train_step(
        model, mesh, rule=None, learning_rate=1e-4, zero_stage=0,
        param_dtype=jnp.bfloat16, loss_fn=loss_fn)
    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.randn(batch, 3, 224, 224), jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, 1000, (batch,)), jnp.int32)
    dt = _timed_steps(step, state, images, labels, steps, warmup)
    return {"metric": "resnet50_train_imgs_per_sec_per_chip",
            "value": round(batch * steps / dt, 1), "unit": "imgs/s"}


def bench_resnet_input(batch=64, n_batches=24, workers=4):
    """ResNet REAL-INPUT variant (VERDICT r4 directive #5): throughput of
    the host input pipeline — per-sample Python decode+augment (a
    GIL-bound transform, the class the thread pool serializes) through
    process workers with shared-memory transfer. Host-only by design:
    through the axon tunnel an end-to-end wall row measures H2D over the
    tunnel, not the chip or the pipeline (BASELINE.md round-5 notes);
    on co-located hosts this pipeline overlaps the synthetic-row compute.
    """
    import time as _time

    from paddle_hackathon_tpu import io

    class _AugmentedImages(io.Dataset):
        """Synthetic 'decode + augment': numpy image plus a deliberately
        Python-bound per-sample transform (~ms of pure bytecode, the
        PIL/albumentations cost class)."""

        def __len__(self):
            return batch * n_batches

        def __getitem__(self, i):
            rng = np.random.RandomState(i)
            img = rng.randint(0, 256, (3, 96, 96)).astype(np.float32)
            acc = 0
            for k in range(40000):  # GIL-bound python work
                acc = (acc + k * i) % 1000003
            img[0, 0, 0] += acc % 7
            return img / 255.0, np.int64(i % 1000)

    def run(nw, procs):
        # use_buffer_reader=False for the thread comparison: same plain
        # reorder pipeline both sides (the native staging ring is a
        # separate path with its own cost profile)
        loader = io.DataLoader(_AugmentedImages(), batch_size=batch,
                               num_workers=nw, use_process_workers=procs,
                               use_buffer_reader=False)
        t0 = _time.perf_counter()
        n = sum(x.shape[0] for x, _ in loader)
        return n / (_time.perf_counter() - t0)

    run(workers, True)  # warm fork/import costs
    proc_rate = run(workers, True)
    thread_rate = run(workers, False)
    import os as _os
    sys.stderr.write(
        f"resnet_input: {workers}-process {proc_rate:.0f} imgs/s vs "
        f"{workers}-thread {thread_rate:.0f} imgs/s "
        f"({proc_rate / thread_rate:.2f}x on {_os.cpu_count()} cpu)\n")
    return {"metric": "resnet50_input_pipeline_imgs_per_sec",
            "value": round(proc_rate, 1), "unit": "imgs/s"}


def bench_ppyoloe(batch=64, size=640, steps=100, warmup=5):
    # ~17 ms/step: anything under ~30 steps is dominated by the single
    # device->host sync latency through the axon tunnel (measured 2.4k
    # imgs/s at 10 steps vs 3.8k at 100 — same compiled program)
    """PP-YOLOE-s 640x640 bf16 jitted inference (driver config #5,
    conv-heavy compiled path)."""
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu.core.tensor import Tensor
    from paddle_hackathon_tpu.models.ppyoloe import ppyoloe_s
    from paddle_hackathon_tpu.nn.layer import functional_call

    paddle.seed(0)
    model = ppyoloe_s()
    model.eval()
    params, buffers = model.functional_state()

    def _bf16(d):
        return {k: v.astype(jnp.bfloat16)
                if jnp.issubdtype(v.dtype, jnp.floating) else v
                for k, v in d.items()}

    params, buffers = _bf16(params), _bf16(buffers)

    @jax.jit
    def fwd(params, x):
        cls_logits, reg_dists = functional_call(
            model, params, (Tensor(x),), buffers=buffers, training=False)
        # return BOTH heads — jit dead-code-eliminates unused outputs, and
        # dropping reg_dists would bench a truncated model
        unwrap = lambda t: t._value if isinstance(t, Tensor) else t
        return ([unwrap(c) for c in cls_logits],
                [unwrap(r) for r in reg_dists])

    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.rand(batch, 3, size, size), jnp.bfloat16)
    out = None
    for _ in range(warmup):
        out = fwd(params, images)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fwd(params, images)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    return {"metric": "ppyoloe_s_infer_imgs_per_sec_per_chip",
            "value": round(batch * steps / dt, 1), "unit": "imgs/s"}


def bench_ppyoloe_train(batch=16, size=640, steps=50, warmup=3):
    """PP-YOLOE-s TRAINING step (VERDICT r4 weak #3: driver config #5 is
    a train config — 'conv-heavy static-graph' — and the r2 415 imgs/s
    number was never gated): fwd + TAL-assigned det loss + bwd + Adam in
    one jitted step, bf16 params."""
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu import parallel
    from paddle_hackathon_tpu.core import random as core_random
    from paddle_hackathon_tpu.core.tensor import Tensor
    from paddle_hackathon_tpu.models.ppyoloe import ppyoloe_s

    paddle.seed(0)
    model = ppyoloe_s()
    model.train()

    def loss_fn(model, params, buffers, batch_, rng_key):
        (images, gt_boxes), gt_labels = batch_
        from paddle_hackathon_tpu.core import autograd
        with model._swap_state(params, dict(buffers)), autograd.no_grad(), \
                core_random.rng_scope(rng_key):
            loss = model.loss(Tensor(images), Tensor(gt_boxes),
                              Tensor(gt_labels))
        return loss._value if isinstance(loss, Tensor) else loss

    mesh = parallel.create_mesh({"dp": 1}, devices=jax.devices()[:1])
    step, state = parallel.make_sharded_train_step(
        model, mesh, rule=None, learning_rate=1e-4, zero_stage=0,
        param_dtype=jnp.bfloat16, loss_fn=loss_fn)
    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.rand(batch, 3, size, size), jnp.bfloat16)
    # 8 boxes per image, xyxy within the canvas, zero rows = padding
    boxes = np.zeros((batch, 8, 4), np.float32)
    x0 = rng.rand(batch, 8) * (size - 64)
    y0 = rng.rand(batch, 8) * (size - 64)
    boxes[..., 0], boxes[..., 1] = x0, y0
    boxes[..., 2] = x0 + 16 + rng.rand(batch, 8) * 48
    boxes[..., 3] = y0 + 16 + rng.rand(batch, 8) * 48
    boxes[:, 6:] = 0.0  # padded gt rows
    gt_boxes = jnp.asarray(boxes)
    gt_labels = jnp.asarray(rng.randint(0, 80, (batch, 8)), jnp.int32)
    dt = _timed_steps(step, state, (images, gt_boxes), gt_labels, steps,
                      warmup)
    return {"metric": "ppyoloe_s_train_imgs_per_sec_per_chip",
            "value": round(batch * steps / dt, 1), "unit": "imgs/s"}


class _LMLoss:
    """Callable loss for hapi fit: mean fused softmax-CE over all rows —
    the same math the hand-rolled step's default loss_fn uses."""

    def __call__(self, logits, labels):
        from paddle_hackathon_tpu.core.tensor import Tensor
        from paddle_hackathon_tpu.nn.functional.loss import \
            fused_softmax_ce_rows
        lg = logits._value if isinstance(logits, Tensor) else logits
        lab = labels._value if isinstance(labels, Tensor) else labels
        return Tensor(jnp.mean(fused_softmax_ce_rows(lg, lab)))


def _hapi_fit_tps(seqlen, batch, steps, warmup, jit_compile, k=8,
                  param_dtype=jnp.bfloat16, preset="gpt2-small-en",
                  log_freq=10 ** 9, checkpoint_dir=None, zero_stage=0,
                  master_weights=False, zero_offload=False, **cfg_kw):
    """tokens/s through ``Model.fit`` (compiled or eager path).

    Timing via a callback: t0 after the warmup window's loss is fetched
    (drains the dispatch pipeline), t1 at on_train_end (fit has already
    block_until_ready'd the last window) — compile time excluded, async
    dispatch included, matching how the hand-rolled `_timed_steps` rows
    measure."""
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu import hapi, io, nn
    from paddle_hackathon_tpu import optimizer as optim
    from paddle_hackathon_tpu.models import GPTForCausalLM, gpt_config

    if jit_compile:
        assert warmup % k == 0 and steps % k == 0, (warmup, steps, k)
    paddle.seed(0)
    cfg = gpt_config(preset, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0, **cfg_kw)
    cfg.max_position_embeddings = max(cfg.max_position_embeddings, seqlen)
    net = GPTForCausalLM(cfg)
    if param_dtype is not None:
        for _, p in net.named_parameters():
            if jnp.issubdtype(p._value.dtype, jnp.floating):
                p._set_value(p._value.astype(param_dtype))

    rng = np.random.RandomState(0)
    n = batch * (warmup + steps)

    class _IdsDS(io.Dataset):
        def __len__(self):
            return n

        def __getitem__(self, i):
            r = np.random.RandomState(i)
            return (r.randint(0, cfg.vocab_size, (seqlen,)).astype(np.int32),
                    r.randint(0, cfg.vocab_size, (seqlen,)).astype(np.int64))

    model = hapi.Model(net)
    # same rule the hand-rolled step compiles: adam(0.9, 0.95) + global
    # norm clip 1.0 — the two programs must be comparable
    model.prepare(
        optimizer=optim.Adam(learning_rate=1e-4, beta1=0.9, beta2=0.95,
                             parameters=net.parameters(),
                             grad_clip=nn.ClipGradByGlobalNorm(1.0)),
        loss=_LMLoss())

    class _Timer(hapi.callbacks.Callback):
        def __init__(self):
            self.t0 = self.t1 = None
            self.last = -1

        def on_train_batch_end(self, step, logs=None):
            if step == warmup - 1:
                assert np.isfinite(float(logs["loss"]))  # drain pipeline
                self.t0 = time.perf_counter()
            self.last = step

        def on_train_end(self, logs=None):
            self.t1 = time.perf_counter()

    timer = _Timer()
    model.fit(_IdsDS(), epochs=1, batch_size=batch, shuffle=False,
              verbose=0, log_freq=log_freq, num_iters=warmup + steps,
              jit_compile=jit_compile if jit_compile else False,
              steps_per_execution=k if jit_compile else 1,
              callbacks=[timer], checkpoint=checkpoint_dir,
              zero_stage=zero_stage, master_weights=master_weights,
              zero_offload=zero_offload)
    assert timer.last == warmup + steps - 1
    if jit_compile:
        assert model._fit_used_compiled, "compiled fit path did not engage"
    return batch * seqlen * steps / (timer.t1 - timer.t0)


def bench_hapi_fit(seqlen=1024, batch=32, steps=48, warmup=8, k=8):
    """GPT-2-small pretraining tokens/s THROUGH ``Model.fit``'s compiled
    multi-step trainer (fused donated step + K-step scan + device
    prefetch) — the five-line-trainer path, gated so it cannot silently
    fall behind the hand-rolled `gpt2` row."""
    value = _hapi_fit_tps(seqlen, batch, steps, warmup, jit_compile=True,
                          k=k)
    from paddle_hackathon_tpu.observability import get_registry
    reg = get_registry()
    row = {"metric": "hapi_fit_tokens_per_sec",
           "value": round(value, 1), "unit": "tokens/s"}
    fam = reg.get("train_step_seconds")
    series = [c for c in fam.children() if c.count] if fam else []
    mfu_fam = reg.get("train_mfu")
    mfu = [c.value for c in mfu_fam.children()
           if dict(c.labels).get("path") == "hapi_compiled"] \
        if mfu_fam else []
    row["metrics"] = {
        "jit_builds_total": int(reg.total("jit_builds_total",
                                          site="hapi.compiled_trainer")),
        "step_p50_ms": round(series[0].quantile(0.5) * 1e3, 3)
        if series else None,
        # set only where cost_model.device_peak_flops knows the chip
        # (or PHT_PEAK_FLOPS pins it); None on this CPU container
        "mfu": round(mfu[0], 4) if mfu else None,
    }
    # ZeRO comparison anchors for the hapi_fit_zero1 ratio gate: the
    # dense row is by construction replicated (stage 0, ratio 1.0)
    row["zero_stage"] = 0
    row["opt_state_bytes_vs_replicated"] = 1.0
    row["metrics"]["checkpoint"] = _hapi_fit_checkpoint_evidence(
        seqlen, batch, steps, warmup, k)
    return row


def _opt_state_bytes_ratio(path="hapi_compiled"):
    """sharded/replicated per-device optimizer-state bytes from the
    ``train_opt_state_bytes`` gauge the trainer build just set; 1.0 when
    the build did not shard (no mesh data axis)."""
    from paddle_hackathon_tpu.observability import get_registry
    fam = get_registry().get("train_opt_state_bytes")
    vals = {dict(c.labels).get("sharded"): c.value
            for c in (fam.children() if fam else [])
            if dict(c.labels).get("path") == path}
    if vals.get("false") and vals.get("true") is not None:
        return round(vals["true"] / vals["false"], 4)
    return 1.0


def bench_hapi_fit_zero1(seqlen=1024, batch=32, steps=48, warmup=8, k=8):
    """The SAME ``Model.fit`` recipe as the hapi_fit row with a ZeRO-1
    sharded optimizer over a dp=<all devices> mesh: moments owned 1/dp
    per chip, grads reduce-scattered, params all-gathered per tensor
    with the gathers overlapping the update tail inside the donated
    K-step scan.  tools/perf_gate.py holds the row to >= 0.9x the
    same-run hapi_fit row (the gather/overlap design must not tax the
    step), and the embedded ``opt_state_bytes_vs_replicated`` evidences
    the ~1/dp HBM shrink.  ``builds_warm_delta`` must be 0: exactly one
    program build (steps and warmup are multiples of k, so there is no
    ragged-tail second program and no mid-run recompile)."""
    import paddle_hackathon_tpu.parallel as parallel
    from paddle_hackathon_tpu.observability import get_registry
    reg = get_registry()
    ndev = len(jax.devices())
    parallel.create_mesh({"dp": ndev})

    def builds():
        return int(reg.total("jit_builds_total",
                             site="hapi.compiled_trainer"))

    b0 = builds()
    value = _hapi_fit_tps(seqlen, batch, steps, warmup, jit_compile=True,
                          k=k, zero_stage=1)
    built = builds() - b0
    return {"metric": "hapi_fit_zero1_tokens_per_sec",
            "value": round(value, 1), "unit": "tokens/s",
            "zero_stage": 1, "dp": ndev,
            "opt_state_bytes_vs_replicated": _opt_state_bytes_ratio(),
            "metrics": {"jit_builds_total": built,
                        "builds_warm_delta": built - 1}}


def _opt_state_host_bytes(path="hapi_compiled"):
    """``placement=host`` bytes from the same gauge — the host-RAM cost
    the offload row must state next to its HBM win (0 when the build
    kept state device-resident)."""
    from paddle_hackathon_tpu.observability import get_registry
    fam = get_registry().get("train_opt_state_bytes")
    for c in (fam.children() if fam else []):
        lab = dict(c.labels)
        if lab.get("path") == path and lab.get("placement") == "host":
            return int(c.value)
    return 0


def bench_hapi_fit_offload(seqlen=1024, batch=32, steps=48, warmup=8,
                           k=8):
    """The hapi_fit_zero1 recipe with ``zero_offload=True``: moments
    live in host RAM and every superstep streams the update per tensor
    through the h2d/d2h pipe.  The trade is EXPLICIT in the row:
    ``opt_state_bytes_vs_replicated`` ~ 0 (opt-state HBM freed outright
    — the capacity win) and ``opt_state_host_bytes`` > 0 (where it
    went), while tokens/s is gated only >= 0.3x the same-run resident
    ZeRO row (tools/perf_gate.py): on a PCIe-attached host the stream
    is the price of fitting a model whose moments cannot fit HBM at
    all — the gate catches the pipe collapsing (serialized h2d/d2h,
    per-step recompiles), not the stated stream cost.
    ``compare_zero_offload`` fails the row when the evidence is vacuous
    (dp=1, device bytes not ~0, or no host bytes)."""
    import paddle_hackathon_tpu.parallel as parallel
    from paddle_hackathon_tpu.observability import get_registry
    reg = get_registry()
    ndev = len(jax.devices())
    parallel.create_mesh({"dp": ndev})

    def builds():
        return int(reg.total("jit_builds_total",
                             site="hapi.compiled_trainer"))

    b0 = builds()
    value = _hapi_fit_tps(seqlen, batch, steps, warmup, jit_compile=True,
                          k=k, zero_stage=1, zero_offload=True)
    built = builds() - b0
    return {"metric": "hapi_fit_offload_tokens_per_sec",
            "value": round(value, 1), "unit": "tokens/s",
            "zero_stage": 1, "zero_offload": True, "dp": ndev,
            "opt_state_bytes_vs_replicated": _opt_state_bytes_ratio(),
            "opt_state_host_bytes": _opt_state_host_bytes(),
            "metrics": {"jit_builds_total": built,
                        "builds_warm_delta": built - 1}}


def _hapi_fit_checkpoint_evidence(seqlen, batch, steps, warmup, k,
                                  **fit_kw):
    """Async-checkpoint overlap evidence for the hapi_fit row: the SAME
    recipe run twice with real log_freq sync points — without and with
    crash-safe checkpointing into a scratch dir.  Honest overlap means
    (a) tokens/s with checkpointing within noise of without, (b) the
    compiled trainer's program-build count identical between the runs
    (the snapshot is its own tiny program on a separate jit site), and
    (c) a non-trivial number of checkpoints actually committed inside
    the timed window (write_p50_ms is their on-writer-thread cost)."""
    import shutil
    import tempfile

    from paddle_hackathon_tpu.observability import get_registry
    reg = get_registry()

    def builds():
        return int(reg.total("jit_builds_total",
                             site="hapi.compiled_trainer"))

    saves0 = int(reg.total("checkpoint_saves_total"))
    b0 = builds()
    tps_plain = _hapi_fit_tps(seqlen, batch, steps, warmup,
                              jit_compile=True, k=k, log_freq=k, **fit_kw)
    b1 = builds()
    ckdir = tempfile.mkdtemp(prefix="pht_bench_ckpt_")
    try:
        tps_ckpt = _hapi_fit_tps(seqlen, batch, steps, warmup,
                                 jit_compile=True, k=k, log_freq=k,
                                 checkpoint_dir=ckdir, **fit_kw)
    finally:
        shutil.rmtree(ckdir, ignore_errors=True)
    b2 = builds()
    fam = reg.get("checkpoint_write_seconds")
    writes = [c for c in fam.children() if c.count] if fam else []
    return {
        "tokens_per_sec": round(tps_ckpt, 1),
        "tokens_per_sec_no_ckpt": round(tps_plain, 1),
        "overlap_ratio": round(tps_ckpt / tps_plain, 4),
        "write_p50_ms": round(writes[-1].quantile(0.5) * 1e3, 3)
        if writes else None,
        "saves_committed": int(reg.total("checkpoint_saves_total"))
        - saves0,
        "builds_warm_delta": (b2 - b1) - (b1 - b0),
    }


def bench_fit_compare():
    """--fit mode: compiled Model.fit vs the hand-rolled jitted step vs
    eager Model.fit, one JSON line with the two ratios the acceptance
    gate reads (compiled within 10% of hand-rolled; >=2x eager).  On CPU
    the config scales down like the cpu smoke path (same model family,
    f32) — ratios remain meaningful, absolute tokens/s are not chip
    numbers."""
    on_tpu = any(d.platform in ("tpu", "axon") for d in jax.devices())
    if on_tpu:
        fit_kw = dict(seqlen=1024, batch=32, steps=48, warmup=8, k=8,
                      param_dtype=jnp.bfloat16)
        hand_kw = dict(seqlen=1024, batch=32, steps=48, warmup=8,
                       param_dtype=jnp.bfloat16)
        eager_steps = 8
        metric = "hapi_fit_tokens_per_sec"
    else:
        small = dict(num_layers=2, hidden_size=128, num_heads=4,
                     vocab_size=1024)
        fit_kw = dict(seqlen=128, batch=4, steps=16, warmup=8, k=4,
                      param_dtype=None, **small)
        hand_kw = dict(seqlen=128, batch=4, steps=16, warmup=2,
                       param_dtype=jnp.float32,
                       metric="hapi_fit_tokens_per_sec_cpu_smoke", **small)
        eager_steps = 8
        metric = "hapi_fit_tokens_per_sec_cpu_smoke"
    fit_tps = _hapi_fit_tps(jit_compile=True, **fit_kw)
    hand_tps = bench_gpt2(**hand_kw)["value"]
    eager_kw = dict(fit_kw, steps=eager_steps, warmup=2, k=1)
    eager_tps = _hapi_fit_tps(jit_compile=False, **eager_kw)
    row = {"metric": metric, "value": round(fit_tps, 1),
           "unit": "tokens/s",
           "handrolled_tokens_per_sec": round(hand_tps, 1),
           "eager_fit_tokens_per_sec": round(eager_tps, 1),
           "vs_handrolled": round(fit_tps / hand_tps, 4),
           "vs_eager_fit": round(fit_tps / eager_tps, 4)}
    print(json.dumps(row))
    return row


def _trace_device_ms(fn):
    """Run ``fn`` under the jax profiler and return ``(ms, timing)`` —
    the single owner of the trace-measurement scaffold for the
    decode/serving rows (raise-safe stop, tools path, temp-dir cleanup).

    ``timing`` is ``"device"`` (summed top-level XLA-op device time) on
    accelerators, or ``"host"`` on CPU-only containers: jax.profiler
    emits no XLA device events on CPU, so the old hard ``assert`` made
    every serving/decode row crash there — fall back to wall clock
    around ``fn`` instead, marked so a host number can never be read as
    (or gated against) a chip number."""
    import shutil
    import tempfile

    outdir = tempfile.mkdtemp(prefix="bench_trace")
    try:
        jax.profiler.start_trace(outdir)
        t0 = time.perf_counter()
        try:
            fn()
        finally:
            # a raise mid-trace must not leave the profiler running for
            # every subsequent suite row
            host_ms = (time.perf_counter() - t0) * 1e3
            jax.profiler.stop_trace()
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tools"))
        from trace_util import toplevel_device_ms
        try:
            dev_ms = toplevel_device_ms(outdir)
        except Exception:
            dev_ms = 0.0
    finally:
        shutil.rmtree(outdir, ignore_errors=True)
    if dev_ms > 0:
        return dev_ms, "device"
    return host_ms, "host"


def bench_decode(batch=8, prompt=64, new_tokens=128, spec_k=0,
                 metric="gpt2_greedy_decode_device_tokens_per_sec_per_chip"):
    """One-program greedy decoding DEVICE throughput: one traced
    generate() call, summed top-level XLA-op device time (nested while
    bodies counted once). Wall clock through the axon tunnel is
    round-trip-bound (~100-160 ms per RTT, varying day to day) and
    measures the tunnel, not the chip — the round-3 "4,032 tok/s" row was
    ~2/3 tunnel latency (BASELINE.md round-4 decode notes).

    ``spec_k>0`` = the `decode_spec` row: the draft-and-verify loop
    (n-gram self-drafting) over the same workload, with the acceptance
    rate recorded — exact greedy equivalence means any rate > 0 is free
    throughput."""
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu.core.tensor import Tensor
    from paddle_hackathon_tpu.models import GPTForCausalLM, gpt_config

    paddle.seed(0)
    cfg = gpt_config("gpt2-small-en", hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    for _, p in model.named_parameters():
        if jnp.issubdtype(p._value.dtype, jnp.floating):
            p._set_value(p._value.astype(jnp.bfloat16))
    rng = np.random.RandomState(0)
    ids = Tensor(jnp.asarray(rng.randint(0, cfg.vocab_size,
                                         (batch, prompt)), jnp.int32))
    gen = lambda: np.asarray(model.generate(  # noqa: E731
        ids, max_new_tokens=new_tokens, temperature=0.0,
        spec_k=spec_k).numpy())
    gen()  # compile+sync
    outs = []
    dev_ms, timing = _trace_device_ms(lambda: outs.append(gen()))
    assert outs[0].shape == (batch, prompt + new_tokens)
    row = {"metric": metric,
           "value": round(batch * new_tokens / (dev_ms / 1e3), 1),
           "unit": "tokens/s", "timing": timing}
    if spec_k:
        st = model._last_spec_stats
        row["acceptance_rate"] = round(
            st["accepted"] / max(st["proposed"], 1), 4)
        row["spec_ticks"] = st["ticks"]
    return row


def bench_serving(streams=8, prompt=64, new_tokens=128, chunk=32, spec_k=0,
                  metric="gpt2_serving_8stream_device_tokens_per_sec_per_chip",
                  cache_mode="dense", page_size=16, num_pages=None,
                  max_len=None, quant=None, moe=False):
    """Continuous-batching serving (VERDICT r4 directive #2): aggregate
    DEVICE tokens/s across `streams` concurrent requests through the
    ServingEngine's slot-batched tick. Trace-measured like bench_decode —
    per-tick wall through the axon tunnel is RTT-bound (one small D2H per
    tick) and measures the tunnel, not the chip.

    ``spec_k>0`` = the `serving_spec` row: identical workload through the
    fused verify tick with the n-gram drafter; acceptance rate recorded,
    and tools/perf_gate.py holds it to >= 1.0x the same-run `serving`
    row (exact greedy equivalence makes speculation strictly free unless
    the verify width itself costs more than it recovers).

    ``quant="int8"`` = the `serving_int8` row: the SAME workload served
    from a weight-only quantized artifact (save_for_serving(quant=) ->
    load_for_serving round trip, so the row measures what a production
    deploy measures: the fused dequant GEMM ticks plus quantize-at-load).
    Embeds the achieved weight-HBM bytes and the bf16 ratio as evidence;
    tools/perf_gate.py holds the row to >= 1.3x the same-run bf16
    `serving` row on device timing (decode is weight-bandwidth-bound, so
    halved weight bytes must buy real throughput)."""
    import shutil
    import tempfile

    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu.inference.serving import (ServingEngine,
                                                        load_for_serving,
                                                        save_for_serving)
    from paddle_hackathon_tpu.models import GPTForCausalLM, gpt_config

    paddle.seed(0)
    moe_kw = {}
    if moe:
        # the serving-side MoE flagship: matched ACTIVE params vs the
        # dense `serving` row (8 experts x ffn 2h, top-2), so the ratio
        # against that row prices exactly the MoE decode tax — ~2.6x the
        # weight bytes per token on a weight-bandwidth-bound tick, plus
        # in-tick routing/dispatch
        moe_kw = dict(moe_num_experts=8, moe_topk=2, moe_gate="gshard",
                      moe_capacity_factor=1.25, intermediate_size=2 * 768)
    cfg = gpt_config("gpt2-small-en", hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0, **moe_kw)
    model = GPTForCausalLM(cfg)
    model.eval()
    for _, p in model.named_parameters():
        if jnp.issubdtype(p._value.dtype, jnp.floating):
            p._set_value(p._value.astype(jnp.bfloat16))
    bf16_bytes = sum(int(p._value.nbytes)
                     for _, p in model.named_parameters())
    quant_dir = None
    if quant is not None:
        quant_dir = tempfile.mkdtemp(prefix="bench_quant_artifact")
        try:
            save_for_serving(model, quant_dir, quant=quant)
            model = load_for_serving(quant_dir)
        finally:
            shutil.rmtree(quant_dir, ignore_errors=True)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (prompt,)).astype(np.int32)
               for _ in range(streams)]
    from paddle_hackathon_tpu.observability import get_registry
    eng = ServingEngine(model, max_slots=streams,
                        max_len=max_len or (prompt + new_tokens + chunk),
                        spec_k=spec_k,
                        auto_run=False, decode_window=32, chunk=chunk,
                        cache_mode=cache_mode, page_size=page_size,
                        num_pages=num_pages)
    reg = get_registry()
    builds = lambda: int(  # noqa: E731 — this engine's program builds
        reg.total("jit_builds_total", engine=eng._engine_id))
    # warm phase compiles every tick flavor this run can hit: a random
    # prompt covers the chunk-prefill and multi-step decode programs
    # (ticks where the drafter proposes nothing demote to the fused
    # window), then — under spec_k — a REPEATED prompt makes the n-gram
    # drafter actually propose, compiling the fused verify program now
    # rather than mid-measurement
    warm = eng.submit(prompts[0], 2)
    eng.run_until_idle()
    assert warm.done
    if spec_k:
        warm2 = eng.submit(np.tile(prompts[0][:8], 4), 8)
        eng.run_until_idle()
        assert warm2.done
    builds_warm = builds()
    reqs = [eng.submit(p, new_tokens) for p in prompts]
    dev_ms, timing = _trace_device_ms(eng.run_until_idle)
    assert all(r.done for r in reqs)
    total = streams * new_tokens
    row = {"metric": metric,
           "value": round(total / (dev_ms / 1e3), 1),
           "unit": "tokens/s", "timing": timing}
    if spec_k:
        row["acceptance_rate"] = round(
            eng.stats["spec_accepted"] / max(eng.stats["spec_drafted"], 1),
            4)
        row["spec_ticks"] = eng.stats["spec_ticks"]
    # telemetry snapshot for tools/perf_gate.py: builds growing past the
    # warm phase = the tick recompiled mid-run (the regression tripwire);
    # the latency percentiles ride along for the record
    def _slo_ms(name, q):
        # rolling-window percentile from the request-level SLO telemetry
        # (the /load report's source); None (not NaN — invalid JSON)
        # when the window saw nothing
        h = eng._slo[name]
        return round(h.quantile(q) * 1e3, 3) if h.count else None

    gp = eng.load_report()["goodput"]
    row["metrics"] = {
        "jit_builds_warm": builds_warm,
        "jit_builds_total": builds(),
        "ttft_p50_ms": round(eng._h_ttft.quantile(0.5) * 1e3, 3),
        "tpot_p50_ms": round(eng._h_tpot.quantile(0.5) * 1e3, 3),
        "e2e_p50_ms": round(eng._h_e2e.quantile(0.5) * 1e3, 3),
        # SLO-trajectory fields (extra JSON only — no gate reads them):
        # p50/p99 from the rolling windows + goodput, so the bench
        # history grows an SLO record alongside tokens/s
        "slo_ttft_p50_ms": _slo_ms("ttft", 0.5),
        "slo_ttft_p99_ms": _slo_ms("ttft", 0.99),
        "slo_tpot_p50_ms": _slo_ms("tpot", 0.5),
        "slo_tpot_p99_ms": _slo_ms("tpot", 0.99),
        "goodput": gp["ratio"],
        "ticks": eng.stats["ticks"],
    }
    if quant is not None:
        # achieved weight HBM (the serving_weight_bytes gauge) and the
        # bf16 ratio — evidence the artifact/HBM halving actually landed
        wb = int(eng._g_weight_bytes.value)
        row["metrics"].update({
            "serving_weight_bytes": wb,
            "weight_bytes_vs_bf16": round(wb / bf16_bytes, 4),
        })
        row["quant"] = quant
    if cache_mode == "paged":
        # pool-leak tripwire for tools/perf_gate.py: after the drain the
        # only live pages are the prefix cache's; dropping it must
        # return the pool to 0 allocated — anything left is a refcount
        # leak and compare_metrics fails the suite on it.  streams rides
        # along as the paged-vs-dense admitted-concurrency evidence.
        cached = eng.drop_prefix_cache()
        row["metrics"].update({
            "kv_pages_leaked": eng.kv_pages_in_use,
            "prefix_cached_pages_dropped": cached,
            "peak_concurrent_streams": eng._peak_occupancy,
            "prefix_hit_rate": round(eng.stats["prefix_hit_rate"], 4),
        })
        row["streams"] = streams
    if moe:
        # router-telemetry evidence: every tick observed entropy/load
        # (the PR 4 registry rows docs/OBSERVABILITY.md catalogs)
        row["moe"] = True
        row["metrics"].update({
            "moe_router_entropy_p50": round(
                eng._h_moe_ent.quantile(0.5), 4),
            "moe_ticks_observed": int(eng._h_moe_ent.count),
        })
    return row


def bench_serving_chat(
        conversations=8, turns=4, prompt=128, follow=16, new_tokens=128,
        chunk=32, page_size=16,
        metric="gpt2_serving_chat_8conv_device_tokens_per_sec_per_chip"):
    """Multi-turn conversation serving (PR 16): ``conversations``
    concurrent chats, each running ``turns`` turns through
    ``submit(session=)`` — every turn's prompt is the FULL conversation
    so far plus a short follow-up, exactly the production chat shape.
    Turn 1 pays the real prefill; returning turns resume the retained
    session page chain, so their TTFT is page-hit-dominated — the row
    embeds ``ttft_turn1_ms`` vs ``ttft_turnN_ms`` (per-request
    lifecycle stamps, not the engine histograms, which the warm phase
    also feeds) and the session hit rate, and tools/perf_gate.py gates
    the improvement (``compare_chat_ttft``) plus the aggregate
    throughput >= 1.0x the same-run dense `serving` row.  Runs on CPU
    through the same host-timing fallback as every serving row."""
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu.inference.serving import ServingEngine
    from paddle_hackathon_tpu.models import GPTForCausalLM, gpt_config

    paddle.seed(0)
    cfg = gpt_config("gpt2-small-en", hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    for _, p in model.named_parameters():
        if jnp.issubdtype(p._value.dtype, jnp.floating):
            p._set_value(p._value.astype(jnp.bfloat16))
    rng = np.random.RandomState(0)
    # final-turn worst case: prompt + (turns-1) * (new + follow) history
    # rows, plus this turn's new tokens and the write-window reserve
    max_len = prompt + turns * (new_tokens + follow) + chunk
    from paddle_hackathon_tpu.observability import get_registry
    eng = ServingEngine(model, max_slots=conversations, max_len=max_len,
                        auto_run=False, decode_window=32, chunk=chunk,
                        cache_mode="paged", page_size=page_size,
                        num_pages=conversations * max_len // page_size + 1)
    reg = get_registry()
    builds = lambda: int(  # noqa: E731 — this engine's program builds
        reg.total("jit_builds_total", engine=eng._engine_id))
    warm = eng.submit(rng.randint(0, cfg.vocab_size, (prompt,))
                      .astype(np.int32), 2)
    eng.run_until_idle()
    assert warm.done
    builds_warm = builds()

    convs = [rng.randint(0, cfg.vocab_size, (prompt,)).astype(np.int32)
             for _ in range(conversations)]
    ttfts = [[] for _ in range(turns)]     # [turn][conversation] seconds

    def drive():
        for t in range(turns):
            reqs = [eng.submit(convs[c], new_tokens, session=f"chat{c}")
                    for c in range(conversations)]
            eng.run_until_idle()
            for c, r in enumerate(reqs):
                ttfts[t].append(r.lifecycle["ttft_s"])
                convs[c] = np.concatenate([
                    r.result(),
                    rng.randint(0, cfg.vocab_size, (follow,))
                    .astype(np.int32)])

    dev_ms, timing = _trace_device_ms(drive)
    total = conversations * turns * new_tokens
    t1 = float(np.mean(ttfts[0])) * 1e3
    tN = float(np.mean([x for t in ttfts[1:] for x in t])) * 1e3
    hit = eng.stats["session_hit_tokens"] / max(
        eng.stats["prompt_tokens"], 1)
    sessions = len(eng._sessions)
    dropped = eng.drop_sessions()
    cached = eng.drop_prefix_cache()
    row = {"metric": metric,
           "value": round(total / (dev_ms / 1e3), 1),
           "unit": "tokens/s", "timing": timing,
           "conversations": conversations, "turns": turns}
    row["metrics"] = {
        "jit_builds_warm": builds_warm,
        "jit_builds_total": builds(),
        # the tentpole evidence: returning turns resume the retained
        # session chain instead of re-prefilling the history, so their
        # TTFT must sit measurably below turn 1's (compare_chat_ttft)
        "ttft_turn1_ms": round(t1, 3),
        "ttft_turnN_ms": round(tN, 3),
        "session_hit_rate": round(hit, 4),
        "session_resumes": int(eng.stats["session_resumes"]),
        "sessions_retained": sessions,
        "sessions_dropped": dropped,
        # pool-leak tripwire: after sessions + prefix cache are
        # dropped the pool must read 0 (compare_pool_leaks)
        "kv_pages_leaked": eng.kv_pages_in_use,
        "prefix_cached_pages_dropped": cached,
        "ticks": eng.stats["ticks"],
    }
    return row


def bench_serving_slo(
        batch_reqs=3, batch_prompt=192, batch_new=64,
        inter_reqs=6, inter_prompt=24, inter_new=8,
        chunk=32, page_size=16,
        metric="gpt2_serving_slo_mixed_priority_device_tokens_per_sec_per_chip"):
    """SLO-aware scheduling under overload (PR 17): the same mixed
    workload — ``batch_reqs`` long batch requests submitted FIRST, then
    ``inter_reqs`` short interactive ones — served twice from identical
    engines: a FIFO baseline (every request default class, no budget,
    no preemption) and the priority scheduler (classes + per-tick
    prefill budget + paged preemption).  The pool is sized so roughly
    two batch requests fill it: under FIFO the interactive arrivals sit
    behind the whole batch backlog; under the scheduler they admit
    first, preempting a batch stream when pages run short.

    Arrivals are staggered exactly the same way in both runs: the
    batch requests are submitted and stepped until they hold the pool
    mid-flight, THEN the interactive burst lands — under FIFO it waits
    for slots; under the scheduler it preempts batch streams (pages
    donated to the prefix cache, request re-queued).

    The row embeds the evidence tools/perf_gate.py gates
    (``compare_slo_scheduling``): per-class TTFT p99 from the request
    lifecycles, batch goodput (batch tokens / run wall ms — preempted
    work is re-queued, not aborted, so completed counts alone would
    mask replay cost), and ``scheduling_lossless`` — every request in
    both runs completes its full token budget with no error (token
    CONTENT exactness across the two runs is not checkable here: bf16
    weights + different chunk boundaries drift numerically; the
    same-geometry f32 exactness pins live in tests/test_priority.py).
    Gate: interactive ttft_p99 <= 0.75x FIFO while batch goodput
    >= 0.8x FIFO."""
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu.inference.serving import ServingEngine
    from paddle_hackathon_tpu.models import GPTForCausalLM, gpt_config

    paddle.seed(0)
    cfg = gpt_config("gpt2-small-en", hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    for _, p in model.named_parameters():
        if jnp.issubdtype(p._value.dtype, jnp.floating):
            p._set_value(p._value.astype(jnp.bfloat16))
    rng = np.random.RandomState(0)
    work = ([("batch", rng.randint(0, cfg.vocab_size, (batch_prompt,))
              .astype(np.int32), batch_new) for _ in range(batch_reqs)]
            + [("interactive",
                rng.randint(0, cfg.vocab_size, (inter_prompt,))
                .astype(np.int32), inter_new)
               for _ in range(inter_reqs)])

    def build(priority_mode):
        from paddle_hackathon_tpu.inference.paged import pages_for
        reserve = chunk
        # ~2 batch footprints + 1 interactive: admission pressure by
        # construction — the third batch request and every interactive
        # must queue (FIFO) or preempt (scheduler)
        pool = (2 * pages_for(batch_prompt + batch_new, reserve,
                              page_size)
                + pages_for(inter_prompt + inter_new, reserve, page_size)
                + 1)
        kw = {}
        if not priority_mode:
            kw = dict(preempt=False, priority_aging_s=None)
        eng = ServingEngine(
            model, max_slots=4,
            max_len=batch_prompt + batch_new + chunk,
            auto_run=False, decode_window=32, chunk=chunk,
            cache_mode="paged", page_size=page_size, num_pages=pool,
            # 2x chunk: two batch prefills co-resident run at full
            # width (no deferral waste); the budget only bites when an
            # interactive prefill must be granted width first
            prefill_budget=(2 * chunk if priority_mode else None), **kw)
        warm = eng.submit(work[0][1][:chunk + 4], 2)
        eng.run_until_idle()
        assert warm.done
        return eng

    def drive(eng, priority_mode):
        box = {}

        def full_run():
            # batch lands first and is stepped until it holds the pool
            # mid-flight; the interactive burst then arrives into a
            # saturated engine — identical arrival pattern both runs
            reqs = [eng.submit(p, n,
                               priority=(role if priority_mode else None))
                    for role, p, n in work if role == "batch"]
            for _ in range(4):
                eng.step()
            reqs += [eng.submit(p, n,
                                priority=(role if priority_mode else None))
                     for role, p, n in work if role == "interactive"]
            eng.run_until_idle()
            box["reqs"] = reqs

        dev_ms, timing = _trace_device_ms(full_run)
        reqs = box["reqs"]
        assert all(r.done for r in reqs)
        out = {"timing": timing, "dev_ms": dev_ms}
        for role in ("batch", "interactive"):
            tt = [r.lifecycle["ttft_s"] for (ro, _, _), r in zip(work, reqs)
                  if ro == role]
            out[role + "_ttft_p99_ms"] = round(
                float(np.percentile(tt, 99)) * 1e3, 3)
        batch_tokens = sum(len(r.tokens) for (ro, _, _), r in
                           zip(work, reqs) if ro == "batch")
        # goodput = useful batch tokens per wall second: preempted work
        # re-queues instead of aborting, so token counts match across
        # runs — what preemption can crater is the TIME those tokens
        # take (replay cost); rate is the honest denominator
        out["batch_goodput_tokens_per_s"] = round(
            batch_tokens / (dev_ms / 1e3), 1)
        # lossless scheduling: preemption re-queues, never truncates —
        # every request must deliver its full token budget, no errors
        out["lossless"] = all(
            r.error is None and len(r.tokens) == n
            for (_, _, n), r in zip(work, reqs))
        out["goodput_ratio"] = eng.load_report()["goodput"]["ratio"]
        out["preemptions"] = eng.load_report()["scheduler"]["preemptions"]
        cached = eng.drop_prefix_cache()
        out["kv_pages_leaked"] = eng.kv_pages_in_use
        out["prefix_cached_pages_dropped"] = cached
        return out

    eng_f = build(False)
    fifo = drive(eng_f, False)
    eng_f.shutdown()
    eng_p = build(True)
    prio = drive(eng_p, True)
    total = sum(n for _, _, n in work)
    row = {"metric": metric,
           "value": round(total / (prio["dev_ms"] / 1e3), 1),
           "unit": "tokens/s", "timing": prio["timing"]}
    row["metrics"] = {
        "interactive_ttft_p99_ms_priority": prio["interactive_ttft_p99_ms"],
        "interactive_ttft_p99_ms_fifo": fifo["interactive_ttft_p99_ms"],
        "batch_ttft_p99_ms_priority": prio["batch_ttft_p99_ms"],
        "batch_ttft_p99_ms_fifo": fifo["batch_ttft_p99_ms"],
        "batch_goodput_tokens_per_s_priority":
            prio["batch_goodput_tokens_per_s"],
        "batch_goodput_tokens_per_s_fifo":
            fifo["batch_goodput_tokens_per_s"],
        "goodput_ratio_priority": prio["goodput_ratio"],
        "goodput_ratio_fifo": fifo["goodput_ratio"],
        "preemptions": prio["preemptions"],
        # preempt->replay->resume must never drop or truncate a stream
        "scheduling_lossless": prio["lossless"] and fifo["lossless"],
        "kv_pages_leaked": (prio["kv_pages_leaked"]
                            + fifo["kv_pages_leaked"]),
        "prefix_cached_pages_dropped":
            prio["prefix_cached_pages_dropped"],
    }
    return row


def bench_serving_fleet(
        streams=8, prompt=32, new_tokens=32, chunk=16,
        metric="gpt2tiny_serving_fleet_2replica_host_tokens_per_sec"):
    """Fleet-tier serving with the observability plane ARMED (PR 19):
    two small engines behind a FleetRouter, tracing + span sink live
    for the whole measured phase.  The row is telemetry evidence, not
    a throughput flagship — a deliberately tiny model keeps the two
    replicas' compiles cheap, and HOST wall time is the honest clock
    for a row whose work spans two engines' background loops (the
    metric name carries no "device", so compare_timing_fallbacks never
    mistakes it for a degraded device row).

    Embeds what tools/perf_gate.py gates (``compare_fleet_telemetry``):
    ``jit_builds_warm == jit_builds_total`` summed over BOTH replicas —
    armed tracing/federation must add ZERO program builds (spans,
    trace-context plumbing and metric labels are host-side only) — plus
    the router's own dispatch percentiles and retry rate as the
    fleet-health record."""
    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu.inference.fleet import FleetRouter
    from paddle_hackathon_tpu.inference.serving import ServingEngine
    from paddle_hackathon_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_hackathon_tpu.observability import get_registry, tracing

    paddle.seed(0)
    max_len = prompt + new_tokens + chunk
    cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                    num_heads=4, max_position_embeddings=max_len,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                    use_flash_attention=False)
    engines = []
    for _ in range(2):
        m = GPTForCausalLM(cfg)
        m.eval()
        engines.append(ServingEngine(m, max_slots=streams, max_len=max_len,
                                     chunk=chunk, decode_window=8))
    reg = get_registry()

    def builds():
        return sum(int(reg.total("jit_builds_total", engine=e._engine_id))
                   for e in engines)

    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (prompt,)).astype(np.int32)
               for _ in range(streams)]
    # warm EVERY replica directly (the router's least-loaded pick could
    # send all warmup to one engine and leave the other to compile
    # mid-measurement, which is exactly what the gate must not excuse)
    for e in engines:
        w = e.submit(prompts[0], 2)
        assert w.wait(300) and w.error is None, w.error
    builds_warm = builds()
    router = FleetRouter(engines)
    spans = []
    tracing.set_span_sink(
        lambda name, t0, t1, tid, attrs: spans.append(name))
    tracing.enable_tracing()
    try:
        t0 = time.perf_counter()
        frs = [router.submit(p, new_tokens) for p in prompts]
        for fr in frs:
            assert fr.wait(300), "fleet request timed out"
        wall_s = time.perf_counter() - t0
    finally:
        tracing.disable_tracing()
        tracing.set_span_sink(None)
    assert all(fr.error is None for fr in frs)
    rep = router.load_report()
    disp = (rep.get("dispatch") or {}).get("hit") or {}
    retries = sum(fr.retries for fr in frs)
    row = {"metric": metric,
           "value": round(streams * new_tokens / wall_s, 1),
           "unit": "tokens/s", "timing": "host"}
    row["metrics"] = {
        "jit_builds_warm": builds_warm,
        "jit_builds_total": builds(),
        "fleet_dispatch_p50_ms": (round(disp["p50_s"] * 1e3, 3)
                                  if disp.get("p50_s") is not None
                                  else None),
        "fleet_dispatch_p99_ms": (round(disp["p99_s"] * 1e3, 3)
                                  if disp.get("p99_s") is not None
                                  else None),
        "fleet_retry_rate": round(retries / len(frs), 4),
        "fleet_replicas": len(engines),
        "fleet_spans_recorded": len(spans),
    }
    router.shutdown()
    return row


SUITE = {
    "gpt2": lambda: bench_gpt2(),
    "ernie": lambda: bench_ernie(),
    # bs6 + bf16 Adam moments: the round-3 winning 1.3B config (+26%
    # over bs4/f32 — BASELINE.md; convergence parity pinned by
    # tests/test_moment_dtype.py; default moment dtype stays f32)
    "gpt3_1p3b": lambda: bench_gpt2(
        preset="gpt3-1.3B-en", batch=6, moment_dtype="bfloat16",
        metric="gpt3_1p3b_pretrain_tokens_per_sec_per_chip"),
    "long_context": lambda: bench_gpt2(
        seqlen=4096, batch=4,
        metric="gpt2_long_context_s4096_tokens_per_sec_per_chip"),
    "resnet": lambda: bench_resnet(),
    "resnet_input": lambda: bench_resnet_input(),
    "ppyoloe": lambda: bench_ppyoloe(),
    "ppyoloe_train": lambda: bench_ppyoloe_train(),
    "decode": lambda: bench_decode(),
    "serving": lambda: bench_serving(),
    # speculative draft-and-verify rows (PR 3): same workloads, spec_k=8
    # n-gram self-drafting; the serving_spec/serving same-run ratio is
    # gated >= 1.0x by tools/perf_gate.py
    "decode_spec": lambda: bench_decode(
        spec_k=8,
        metric="gpt2_greedy_decode_spec_device_tokens_per_sec_per_chip"),
    "serving_spec": lambda: bench_serving(
        spec_k=8,
        metric="gpt2_serving_spec_8stream_device_tokens_per_sec_per_chip"),
    # paged-KV serving (PR 6): 16 streams through a page pool sized to
    # the HBM an 8-slot dense engine provisioned for a max_len=512 worst
    # case reserves (8*512 rows = 256 usable pages + the null page) —
    # each 64+128-token request footprints 14 pages, so 2x the streams
    # fit where dense strands the max_len slack; tools/perf_gate.py
    # holds the row to >= 1.0x the same-run dense `serving` row and
    # fails on any leaked page
    "serving_paged": lambda: bench_serving(
        streams=16, max_len=512, cache_mode="paged", page_size=16,
        num_pages=8 * 512 // 16 + 1,
        metric="gpt2_serving_paged_16stream_device_tokens_per_sec_per_chip"),
    # multi-turn conversational serving (PR 16): 8 concurrent chats x 4
    # turns through submit(session=) — returning turns resume retained
    # session KV instead of re-prefilling the conversation, so turn-N
    # TTFT is page-hit-dominated (compare_chat_ttft gates the embedded
    # turn1-vs-turnN improvement) and the row holds >= 1.0x the
    # same-run dense `serving` row
    "serving_chat": lambda: bench_serving_chat(),
    # SLO-aware scheduling under overload (PR 17): one mixed
    # batch+interactive workload served FIFO then priority-scheduled
    # from identical engines — compare_slo_scheduling gates the
    # embedded interactive ttft_p99 <= 0.75x FIFO, batch goodput
    # >= 0.8x FIFO, token-exact preemption, and zero leaked pages
    "serving_slo": lambda: bench_serving_slo(),
    # fleet observability plane (PR 19): 2 replicas behind a FleetRouter
    # with tracing armed for the whole measured phase —
    # compare_fleet_telemetry gates jit_builds_total == jit_builds_warm
    # across both replicas (armed telemetry compiles NOTHING) and
    # requires the dispatch-latency percentiles to be present
    "serving_fleet": lambda: bench_serving_fleet(),
    # weight-only int8 serving (PR 8): identical workload to `serving`
    # through the quantized artifact (save -> quantize-at-load ->
    # fused dequant GEMM ticks); decode streams half the weight bytes
    # per token, so tools/perf_gate.py holds the row to >= 1.3x the
    # same-run bf16 `serving` row wherever device timing is available
    "serving_int8": lambda: bench_serving(
        quant="int8",
        metric="gpt2_serving_int8_8stream_device_tokens_per_sec_per_chip"),
    # the high-level trainer's compiled fast path (hapi/compiled.py):
    # tokens/s through Model.fit must track the hand-rolled gpt2 row
    "hapi_fit": lambda: bench_hapi_fit(),
    # ZeRO-1 sharded optimizer through the same Model.fit recipe on a
    # dp=<all chips> mesh (moments 1/dp per chip, reduce-scattered
    # grads, per-tensor overlapped param all-gathers); gated >= 0.9x
    # the same-run hapi_fit row by tools/perf_gate.py
    "hapi_fit_zero1": lambda: bench_hapi_fit_zero1(),
    # ZeRO-offload (PR 18): same recipe, moments parked in host RAM and
    # streamed per tensor through the h2d/d2h pipe — opt-state HBM ~ 0
    # with the host cost stated in the row; gated >= 0.3x the same-run
    # resident zero1 row (the stream is a stated capacity trade, the
    # gate catches the pipe collapsing)
    "hapi_fit_offload": lambda: bench_hapi_fit_offload(),
    # MoE-GPT flagship (PR 9, ROADMAP item 5): expert-parallel training
    # at matched ACTIVE params — the row embeds its own same-run dense
    # reference and tools/perf_gate.py holds vs_dense_active_params
    # >= 0.6x (plus the cross-row ratio gate on TPU suite runs)
    "gpt2_moe": lambda: bench_gpt2_moe(),
    # MoE serving through the same tick programs (routing in-program,
    # router entropy/expert-load histograms embedded as evidence);
    # sanity-floored against the same-run dense `serving` row — at
    # matched active params the MoE decode streams ~2.6x the weight
    # bytes, so the floor prices the indirection, not parity
    "serving_moe": lambda: bench_serving(
        moe=True,
        metric="gpt2_moe_serving_8stream_device_tokens_per_sec_per_chip"),
}


def run_suite():
    """Each config runs in a FRESH subprocess: HBM-hungry rows (1.3B bs6
    fills ~15 of 16 GB) are not squeezed by buffers the earlier benches
    leave behind, and a transient axon-tunnel error fails one row, not
    the sweep (one retry per row).

    A row that fails BOTH attempts is recorded as an ``{"error": ...}``
    row and the sweep CONTINUES — the r04 round lost its entire bench
    record to one rc=1 dtype crash because the old behavior raised here.
    tools/perf_gate.py fails loudly on any error row
    (``compare_error_rows``), so a crash is a named gate failure with
    the stderr tail attached, never a silently missing metric."""
    import subprocess
    rows = []
    me = os.path.abspath(__file__)
    for name in SUITE:
        row, last_err = None, ""
        for attempt in (1, 2):
            try:
                proc = subprocess.run(
                    [sys.executable, me, "--one", name],
                    capture_output=True, text=True, timeout=1500)
            except subprocess.TimeoutExpired as e:
                last_err = f"timeout after {e.timeout}s"
                sys.stderr.write(
                    f"suite row {name} attempt {attempt} timed out\n")
                continue
            line = next((ln for ln in proc.stdout.splitlines()[::-1]
                         if ln.startswith("{")), None)
            if proc.returncode == 0 and line:
                row = json.loads(line)
                break
            last_err = proc.stderr[-1500:]
            sys.stderr.write(
                f"suite row {name} attempt {attempt} failed:\n"
                f"{last_err}\n")
        if row is None:
            row = {"metric": name, "suite_row": name,
                   "error": last_err[-800:] or "no JSON line produced"}
            sys.stderr.write(f"suite row {name} failed twice — recording "
                             f"an error row and continuing\n")
        rows.append(row)
        print(json.dumps(row))
    return rows


HEADLINE_METRIC = "gpt2_small_pretrain_tokens_per_sec_per_chip"

# Substrings that mark a failure as TPU/tunnel outage rather than a code
# bug (the round-4 BENCH died at backend *init* with "Unable to initialize
# backend 'axon': UNAVAILABLE" and was recorded as a code failure).
_OUTAGE_SIGNS = ("UNAVAILABLE", "Unable to initialize backend",
                 "DEADLINE_EXCEEDED", "Socket closed", "failed to connect",
                 "GOAWAY", "RESOURCE_EXHAUSTED: Attempting to reserve")


def _looks_like_outage(text):
    return any(s in text for s in _OUTAGE_SIGNS)


def _run_sub(args, timeout):
    """Run a bench subprocess; returns (rc, json_line|None, stderr_tail,
    timed_out)."""
    import subprocess
    try:
        proc = subprocess.run(args, capture_output=True, text=True,
                              timeout=timeout)
    except subprocess.TimeoutExpired as e:
        err = (e.stderr or b"")
        err = err.decode("utf-8", "replace") if isinstance(err, bytes) else err
        return -1, None, err[-2000:], True
    line = next((ln for ln in proc.stdout.splitlines()[::-1]
                 if ln.startswith("{")), None)
    return proc.returncode, line, proc.stderr[-2000:], False


def _probe_chip(timeout=180):
    """Can the accelerator run one op right now? Bounded subprocess so a
    hanging tunnel (round 4: bare jax.devices() stalled 4 minutes) cannot
    hang the bench driver.  Returns (ok, platform|stderr, timed_out) —
    platform distinguishes a live chip from a CPU-only environment."""
    import subprocess
    # "cpu+axon" = jax answered on CPU but the axon plugin is installed:
    # that is a TPU box whose tunnel silently fell back (an outage), NOT a
    # CPU-only dev machine — the two must not be conflated or an outage on
    # the driver host would print a cpu_smoke row instead of the
    # structured tpu_unreachable record
    code = ("import os, jax;"
            "p = os.environ.get('JAX_PLATFORMS');"
            "p and jax.config.update('jax_platforms', p);"
            "import jax.numpy as jnp, importlib.util as iu;"
            "d = jax.devices();"
            "assert float(jnp.ones(()).sum()) == 1.0;"
            "ax = iu.find_spec('axon') is not None;"
            "tag = d[0].platform + ("
            "'+axon' if ax and d[0].platform == 'cpu' and not p else '');"
            "print('PROBE_OK', tag)")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout)
    except subprocess.TimeoutExpired:
        return False, "probe timed out", True
    for ln in proc.stdout.splitlines():
        if ln.startswith("PROBE_OK") and proc.returncode == 0:
            return True, ln.split()[-1], False
    return False, proc.stderr[-500:], False


def robust_headline():
    """The default `python bench.py` entry: classify wall-bench failures,
    retry outages with backoff, fall back to trace-measured device op time
    when the chip works but the tunnel poisons wall clock, and emit a
    structured outage record (rc=0) instead of a traceback when the TPU is
    truly unreachable — the evidence-producing-gate philosophy of the
    reference's perf CI (tools/ci_model_benchmark.sh:50-60): a gate that
    dies without structured output gates nothing.  VERDICT r4 directive #1.

    Worst-case wall budget ~BENCH_MAX_SECONDS (default 1500s) so an outer
    driver timeout cannot kill us with no output at all."""
    me = os.path.abspath(__file__)
    deadline = time.time() + float(os.environ.get("BENCH_MAX_SECONDS", 1500))
    attempts, fail_log, smoke_line = 0, [], None
    for attempt in range(3):
        if time.time() + 420 > deadline and attempt > 0:
            break
        attempts += 1
        rc, line, err, timed_out = _run_sub(
            [sys.executable, me, "--headline-inline"], timeout=420)
        if rc == 0 and line:
            try:
                metric = json.loads(line).get("metric")
            except ValueError:
                metric = None
            if metric == HEADLINE_METRIC:
                print(line)
                return 0
            # a cpu_smoke row under rc=0 means jax fell back to CPU —
            # for the driver that IS an outage (the axon init failure is
            # a warning, not an exception); keep the row in case the
            # probe confirms this is a genuinely CPU-only dev box
            smoke_line = line
        outage = (timed_out or _looks_like_outage(err)
                  or smoke_line is not None)
        fail_log.append({"attempt": attempts, "timed_out": timed_out,
                         "outage": outage,
                         "cpu_fallback": smoke_line is not None,
                         "tail": err[-500:]})
        sys.stderr.write(f"headline attempt {attempts}: "
                         f"{'timeout' if timed_out else f'rc={rc}'} "
                         f"(outage={outage})\n{err}\n")
        if not outage:
            return 1          # real code failure: fail loudly
        if smoke_line is not None:
            break             # deterministic CPU fallback — retries won't help
        if timed_out:
            # a HANG will not clear in a 30s backoff (round-4 stalls ran
            # for hours) — and burning the budget on more 420s hangs
            # would starve the probe+trace fallback, the one path that
            # can still produce a number
            break
        if attempt < 2:
            time.sleep(min(30 * (attempt + 1),
                           max(0, deadline - time.time() - 420)))
    # Wall attempts exhausted on outage signatures.  If the chip itself
    # responds, wall clock was tunnel-poisoned — measure device op time
    # from a profiler trace instead (the decode row's method).
    probe_ok, probe_info = False, ""
    if time.time() + 120 < deadline:
        probe_ok, probe_info, _ = _probe_chip(timeout=120)
        if probe_ok and probe_info == "cpu" and smoke_line is not None:
            # genuinely CPU-only environment (no axon tunnel at all):
            # the smoke row is the honest result, under its own metric.
            # "cpu+axon" (TPU box, tunnel fell back to CPU) falls THROUGH
            # to the structured outage record instead.
            print(smoke_line)
            return 0
        if probe_ok and probe_info not in ("cpu", "cpu+axon") \
                and time.time() + 600 < deadline:
            rc, line, err, timed_out = _run_sub(
                [sys.executable, me, "--headline-trace"], timeout=600)
            if rc == 0 and line:
                print(line)
                return 0
            fail_log.append({"attempt": "trace", "timed_out": timed_out,
                             "tail": err[-500:]})
    print(json.dumps({
        "metric": HEADLINE_METRIC, "value": None, "unit": "tokens/s",
        "vs_baseline": None, "error": "tpu_unreachable",
        "attempts": attempts, "probe_ok": probe_ok,
        "probe_info": probe_info[-500:],
        "failures": fail_log[-3:]}))
    return 0


def headline_trace():
    """Trace-measured device-op-time headline (fallback when the tunnel
    poisons wall clock but the chip works).  Method matches
    tools/trace_step.py; tagged "method": "trace" so the driver/judge can
    distinguish it from the wall rows."""
    import shutil
    import tempfile

    import paddle_hackathon_tpu as paddle
    from paddle_hackathon_tpu import parallel
    from paddle_hackathon_tpu.models import (GPTForCausalLM, gpt_config,
                                             param_sharding_spec)
    paddle.seed(0)
    batch, seqlen, nsteps = 32, 1024, 3
    cfg = gpt_config("gpt2-small-en", hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    model = GPTForCausalLM(cfg)
    mesh = parallel.create_mesh({"dp": 1}, devices=jax.devices()[:1])
    step, state = parallel.make_sharded_train_step(
        model, mesh, rule=param_sharding_spec, learning_rate=1e-4,
        zero_stage=0, param_dtype=jnp.bfloat16)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seqlen)),
                      jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seqlen)),
                         jnp.int32)
    key = jax.random.key(0)
    for i in range(3):
        state, loss = step(state, ids, labels, jax.random.fold_in(key, i))
    float(loss)
    outdir = tempfile.mkdtemp(prefix="bench_headline_trace")
    try:
        jax.profiler.start_trace(outdir)
        try:
            for i in range(nsteps):
                state, loss = step(state, ids, labels,
                                   jax.random.fold_in(key, 100 + i))
            float(loss)
        finally:
            jax.profiler.stop_trace()
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tools"))
        from trace_util import toplevel_device_ms
        dev_ms = toplevel_device_ms(outdir)
    finally:
        shutil.rmtree(outdir, ignore_errors=True)
    assert dev_ms > 0, "empty profiler trace"
    value = round(batch * seqlen * nsteps / (dev_ms / 1e3), 1)
    history = load_bench_history()
    prev = history[-1][1] if history else None
    print(json.dumps({"metric": HEADLINE_METRIC, "value": value,
                      "unit": "tokens/s", "method": "trace",
                      "vs_baseline": round(value / prev, 4) if prev else 1.0}))


def main():
    if "--suite" in sys.argv:
        run_suite()
        return
    if "--fit" in sys.argv:
        bench_fit_compare()
        return
    if "--one" in sys.argv:
        name = sys.argv[sys.argv.index("--one") + 1]
        row = SUITE[name]()
        if isinstance(row, dict):
            row.setdefault("programs", _programs_block())
        print(json.dumps(row))
        return
    if "--headline-trace" in sys.argv:
        headline_trace()
        return
    if "--headline-inline" not in sys.argv:
        return robust_headline()

    on_tpu = any(d.platform in ("tpu", "axon") for d in jax.devices())
    if on_tpu:
        # batch 32: round-2 sweep with the packed-heads kernels — 24/32/
        # 40/48 all ~137k tok/s, 32 edges ahead.  100 steps + best-of-2
        # (headline method since round 4: 10-step rows were ~5%
        # sync-diluted through the tunnel).
        row = bench_gpt2()
    else:
        # CPU smoke path so the script always works; its own metric name
        # so a tunnel outage that silently falls back to CPU can never be
        # mistaken for (or gated against) a chip number.
        row = bench_gpt2(
            seqlen=128, batch=2, steps=3, warmup=1,
            preset="gpt2-small-en", num_layers=2, hidden_size=128,
            num_heads=4, vocab_size=1024, param_dtype=jnp.float32,
            metric="gpt2_small_pretrain_tokens_per_sec_cpu_smoke")
    history = load_bench_history()
    prev = history[-1][1] if history else None
    row["vs_baseline"] = round(row["value"] / prev, 4) if (
        prev and on_tpu) else 1.0
    row.setdefault("programs", _programs_block())
    print(json.dumps(row))


if __name__ == "__main__":
    sys.exit(main())
