"""Dynamic-to-static AST conversion of Python control flow.

The reference rewrites ``if``/``while``/``for`` on tensor values into graph
ops via ~20 AST transformers
(``dygraph_to_static/program_translator.py:340``, ``ifelse_transformer.py``,
``loop_transformer.py``, ``logical_transformer.py``).  Trace-based
``to_static`` alone silently bakes one branch into the program (or crashes
on ``bool(tracer)``) whenever a branch condition depends on tensor *values*.

TPU-native design: the same AST rewrite, but the target ops are XLA's
structured control flow — ``lax.cond`` and ``lax.while_loop`` — dispatched
at *runtime*: every rewritten site calls a ``convert_*`` helper that keeps
plain Python semantics when the predicate is a concrete Python/NumPy value
and lowers to the lax primitive only when it is a traced tensor.  One
rewritten function therefore serves both eager and compiled execution, like
the reference's ``convert_ifelse``/``convert_while_loop`` runtime layer
(``dygraph_to_static/convert_operators.py``).

Scope (documented, checked, and erroring loudly otherwise):

- ``if``/``elif``/``else`` with tensor predicates: both branches must bind
  the same set of traced locals with matching shapes/dtypes.
- ``while`` with tensor conditions: loop-carried locals must keep stable
  shapes/dtypes across iterations.
- ``for i in range(...)``: desugared to ``while``; ``for x in <tensor>``
  iterates leading-dim slices via ``Tensor.__iter__`` (exact unroll — the
  dim is static under trace); other iterables keep Python semantics.
- ``and`` / ``or`` / ``not`` on tensors: ``jnp.logical_*`` (short-circuit
  preserved for plain Python values).
- ``break`` / ``continue`` / ``return`` inside loops ARE convertible (ref
  ``break_continue_transformer.py`` / ``return_transformer.py``): escapes
  desugar into boolean guard flags threaded through the loop carry —
  ``break`` joins the loop test, ``continue`` guards the body tail, and a
  ``return e`` site sets a flag whose post-loop handler re-evaluates ``e``
  (legal because once any flag is set the guards freeze all loop state, so
  ``e``'s constituents hold their escape-time values; ``e`` must therefore
  be side-effect-free).  A tensor-pred mid-function return additionally
  needs the loop in a tail-foldable position (the post-loop ``if flag:
  return e`` goes through the guard-clause fold).  ``yield``, loop
  ``else`` clauses, and escapes inside non-range ``for`` iterables keep
  Python semantics.
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Any, Callable

import jax
import jax.numpy as jnp

__all__ = ["convert_function", "convert_ifelse", "convert_while",
           "convert_logical_and", "convert_logical_or", "convert_logical_not",
           "Undefined", "undef_or"]


class _UndefinedType:
    """Placeholder for a local that is not yet bound at the rewrite site."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<undefined local>"

    def __bool__(self):
        raise NameError(
            "local variable referenced before assignment inside converted "
            "control flow")


Undefined = _UndefinedType()


class _UndefWithFallback:
    """A local unbound before converted control flow, with a typed fallback
    for the lax path.  Eager semantics: behaves like :data:`Undefined` (the
    body writes before any read; an empty loop leaves it undefined).  The
    ``lax.while_loop`` path must carry a concrete typed init, so it uses
    the fallback value instead (for-range desugar: the range start)."""

    __slots__ = ("fallback",)

    def __init__(self, fallback):
        self.fallback = fallback

    def __repr__(self):
        return "<undefined local (typed fallback)>"

    def __bool__(self):
        raise NameError(
            "local variable referenced before assignment inside converted "
            "control flow")


def undef_or(fallback):
    return _UndefWithFallback(fallback)


def _tensor_cls():
    from ..core.tensor import Tensor
    return Tensor


def _raw(x):
    T = _tensor_cls()
    return x._value if isinstance(x, T) else x


def _is_traced(x) -> bool:
    return isinstance(_raw(x), jax.core.Tracer)


# ---------------------------------------------------------------------------
# Runtime converters
# ---------------------------------------------------------------------------

def _to_carry(val, site):
    """A control-flow-carried local -> jax value (or raise helpfully)."""
    if isinstance(val, _UndefWithFallback):
        val = val.fallback
    if val is Undefined:
        raise ValueError(
            f"{site}: a local is assigned on only one side of tensor-"
            "dependent control flow; bind it before the branch so both "
            "sides carry the same variables")
    v = _raw(val)
    if isinstance(v, (jax.Array, jax.core.Tracer)):
        return v
    try:
        return jnp.asarray(v)
    except (TypeError, ValueError) as e:
        raise TypeError(
            f"{site}: local of type {type(val).__name__} cannot be carried "
            "through tensor-dependent control flow (only tensors and "
            "numeric values can)") from e


def _wrap_carry(vals):
    T = _tensor_cls()
    return tuple(T(v) for v in vals)


def convert_ifelse(pred, true_fn: Callable, false_fn: Callable,
                   vars: tuple) -> tuple:
    """Rewritten ``if``: dispatches to ``lax.cond`` on traced predicates.

    The branch callables receive the *current* values of every name either
    branch assigns (``Undefined`` for names not yet bound — legal as long
    as the branch writes before it reads); everything else they read
    through their closures, which ``lax.cond`` traces inline.  Only the
    branch *outputs* must be carryable and structurally identical."""
    p = _raw(pred)
    if not _is_traced(p):
        # Python semantics (covers concrete device arrays via __bool__)
        return true_fn(*vars) if p else false_fn(*vars)

    site = ("if on a traced tensor (branches must assign the same locals "
            "with matching shapes/dtypes)")

    def _branch(fn):
        def run(_):
            out = fn(*vars)
            return tuple(_to_carry(o, site) for o in out)
        return run

    out = jax.lax.cond(jnp.reshape(p, ()).astype(bool),
                       _branch(true_fn), _branch(false_fn), ())
    return _wrap_carry(out)


def convert_ifelse_ret(pred, true_fn: Callable, false_fn: Callable,
                       vars: tuple):
    """Rewritten *returning* ``if`` (tail position): both branches end in
    ``return``; the whole construct's value is the function's result."""
    p = _raw(pred)
    if not _is_traced(p):
        return true_fn(*vars) if p else false_fn(*vars)

    T = _tensor_cls()
    site = ("returning if on a traced tensor (both return values must have "
            "matching structure/shapes/dtypes)")

    def _unwrap_tree(out):
        return jax.tree.map(
            lambda t: _to_carry(t, site) if isinstance(t, T) else t, out,
            is_leaf=lambda t: isinstance(t, T))

    def _branch(fn):
        def run(_):
            return _unwrap_tree(fn(*vars))
        return run

    out = jax.lax.cond(jnp.reshape(p, ()).astype(bool),
                       _branch(true_fn), _branch(false_fn), ())
    return jax.tree.map(
        lambda v: T(v) if isinstance(v, (jax.Array, jax.core.Tracer)) else v,
        out)


def convert_while(cond_fn: Callable, body_fn: Callable,
                  vars: tuple) -> tuple:
    """Rewritten ``while``: dispatches to ``lax.while_loop`` on traced
    conditions."""
    test = cond_fn(*vars)
    if not _is_traced(test):
        while bool(_raw(test)):
            vars = tuple(body_fn(*vars))
            test = cond_fn(*vars)
            if _is_traced(test):
                # condition became traced mid-loop (e.g. first iteration
                # produced a tracer) — hand off to the traced path
                return convert_while(cond_fn, body_fn, vars)
        return tuple(vars)

    site = "while on a traced tensor"
    carried = tuple(_to_carry(v, site) for v in vars)

    def cond(vs):
        t = cond_fn(*_wrap_carry(vs))
        return jnp.reshape(_raw(t), ()).astype(bool)

    def body(vs):
        out = body_fn(*_wrap_carry(vs))
        return tuple(_to_carry(o, site) for o in out)

    out = jax.lax.while_loop(cond, body, carried)
    return _wrap_carry(out)


def convert_logical_and(lhs_fn: Callable, rhs_fn: Callable):
    l = lhs_fn()
    if _is_traced(l):
        return _tensor_cls()(jnp.logical_and(
            jnp.asarray(_raw(l)).astype(bool), _bool_val(rhs_fn())))
    if not l:
        return l
    r = rhs_fn()
    if _is_traced(r):
        return _tensor_cls()(_bool_val(r))
    return r


def convert_logical_or(lhs_fn: Callable, rhs_fn: Callable):
    l = lhs_fn()
    if _is_traced(l):
        return _tensor_cls()(jnp.logical_or(
            jnp.asarray(_raw(l)).astype(bool), _bool_val(rhs_fn())))
    if l:
        return l
    r = rhs_fn()
    if _is_traced(r):
        return _tensor_cls()(_bool_val(r))
    return r


def convert_logical_not(x):
    if _is_traced(x):
        return _tensor_cls()(jnp.logical_not(_bool_val(x)))
    return not x


def _bool_val(x):
    return jnp.asarray(_raw(x)).astype(bool)


# ---------------------------------------------------------------------------
# AST analysis helpers
# ---------------------------------------------------------------------------

def _assigned_names(stmts) -> list:
    """Names bound by a statement list (not descending into nested defs)."""
    names = []

    def add(n):
        if n not in names:
            names.append(n)

    def add_target(t):
        if isinstance(t, ast.Name):
            add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                add_target(e)
        elif isinstance(t, ast.Starred):
            add_target(t.value)

    def walrus_targets(node):
        """NamedExpr bindings inside expressions of this statement, not
        descending into nested function/lambda scopes (where := binds
        locally... except lambda, where it binds in the enclosing scope —
        close enough to flag it as bound here)."""
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                continue
            if isinstance(sub, ast.NamedExpr):
                add_target(sub.target)
            walrus_targets(sub)

    def walk(body):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                # nested defs (incl. our generated branch helpers) are
                # re-created on every execution of the suite and cannot be
                # carried through lax control flow — not state
                continue  # do not descend
            walrus_targets(node)
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    add_target(t)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                add_target(node.target)
            elif isinstance(node, ast.For):
                add_target(node.target)
            elif isinstance(node, ast.With):
                for item in node.items:
                    if item.optional_vars is not None:
                        add_target(item.optional_vars)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    add((alias.asname or alias.name).split(".")[0])
            elif isinstance(node, ast.Try):
                for handler in node.handlers:
                    if handler.name:
                        add(handler.name)
                    walk(handler.body)
            for attr in ("body", "orelse", "finalbody"):
                walk(getattr(node, attr, []) or [])
    walk(stmts)
    return names


def _read_names(node) -> set:
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _has_return_at_level(stmts) -> bool:
    """Return present at this control-flow level (descending through nested
    ifs — a return there still exits the function — but not into nested
    function definitions; returns inside nested *loops* also count, since
    they exit the function too)."""
    for node in stmts:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(node, ast.Return):
            return True
        for attr in ("body", "orelse", "finalbody"):
            if _has_return_at_level(getattr(node, attr, []) or []):
                return True
    return False


def _has_loop_escape_at_level(stmts) -> bool:
    """break/continue/yield at this level that would escape the fold."""
    for node in stmts:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(node, (ast.Break, ast.Continue, ast.Yield,
                             ast.YieldFrom)):
            return True
        if isinstance(node, (ast.For, ast.While)):
            continue  # break/continue inside bind to that loop
        for attr in ("body", "orelse", "finalbody"):
            if _has_loop_escape_at_level(getattr(node, attr, []) or []):
                return True
    return False


def _terminates(stmts) -> bool:
    """True when every execution path through the suite ends in ``return``
    (conservative: only Return endings and exhaustive if/else are
    recognized)."""
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, ast.Return):
        return True
    if isinstance(last, ast.If):
        return (_terminates(last.body) and last.orelse
                and _terminates(last.orelse))
    return False


def _has_flow_escape(stmts, *, loop: bool) -> bool:
    """True when the statement list contains return/break/continue/yield at
    this control-flow level (not inside nested functions or nested loops for
    break/continue)."""
    for node in stmts:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
            return True
        if isinstance(node, (ast.Break, ast.Continue)):
            return True
        if isinstance(node, (ast.For, ast.While)):
            # break/continue inside a nested loop bind to that loop — only
            # return/yield still escape
            for sub in ast.walk(node):
                if isinstance(sub, (ast.Return, ast.Yield, ast.YieldFrom)):
                    return True
            continue
        for attr in ("body", "orelse", "finalbody"):
            if _has_flow_escape(getattr(node, attr, []) or [], loop=loop):
                return True
    return False


# ---------------------------------------------------------------------------
# The transformer
# ---------------------------------------------------------------------------

_JST = "__jst__"  # module alias injected into the compiled namespace


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0
        self.bound_names: set = set()  # approximation of names bound so far
        # loop vars unbound before their for-range loop: name -> induction
        # var whose value types the lax carry (see _UndefWithFallback)
        self._undef_fallbacks: dict = {}
        # generated induction vars: mutated per-iteration, so they must be
        # loop-carried despite the __jst_ temp prefix
        self._carry_ok: set = set()

    def _uid(self, kind):
        self.counter += 1
        return f"__jst_{kind}_{self.counter}"

    # -- boolean operators -------------------------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        helper = ("convert_logical_and" if isinstance(node.op, ast.And)
                  else "convert_logical_or")
        expr = node.values[-1]
        for v in reversed(node.values[:-1]):
            expr = ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id=_JST, ctx=ast.Load()),
                    attr=helper, ctx=ast.Load()),
                args=[ast.Lambda(args=_empty_args(), body=v),
                      ast.Lambda(args=_empty_args(), body=expr)],
                keywords=[])
        return ast.copy_location(expr, node)

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.copy_location(ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id=_JST, ctx=ast.Load()),
                    attr="convert_logical_not", ctx=ast.Load()),
                args=[node.operand], keywords=[]), node)
        return node

    # -- statements --------------------------------------------------------
    def _track(self, stmts):
        self.bound_names.update(_assigned_names(stmts))

    def visit_FunctionDef(self, node):
        # collect parameter names, then rewrite the body
        args = node.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            self.bound_names.add(a.arg)
        if args.vararg:
            self.bound_names.add(args.vararg.arg)
        if args.kwarg:
            self.bound_names.add(args.kwarg.arg)
        node.body = self._rewrite_block(node.body, tail=True)
        return node

    def _rewrite_block(self, stmts, tail=False):
        """Rewrite a suite.  ``tail`` marks suites whose end is the end of
        the function (so a returning ``if`` can fold the rest of the suite
        into its else branch — the guard-clause pattern)."""
        out = []
        for idx, s in enumerate(stmts):
            if (tail and isinstance(s, ast.If)
                    and _has_return_at_level([s])
                    and not _has_loop_escape_at_level([s])):
                # Folding the rest of the suite into one branch is only
                # sound when the *other* branch never falls through.  If
                # the body terminates, the rest belongs to the else; if
                # only the else terminates, swap branches (negating the
                # predicate).  Neither terminating: leave Python semantics.
                if _terminates(s.body):
                    out.extend(self._fold_return_if(s, stmts[idx + 1:]))
                    return out  # the rest of the suite was consumed
                if _terminates(s.orelse):
                    s.test = ast.copy_location(ast.UnaryOp(
                        op=ast.Not(), operand=s.test), s.test)
                    s.body, s.orelse = s.orelse, s.body
                    out.extend(self._fold_return_if(s, stmts[idx + 1:]))
                    return out
            if isinstance(s, (ast.While, ast.For)):
                des = self._try_desugar_escapes(s)
                if des is not None:
                    # re-process the flag-desugared replacement inline so
                    # its post-loop `if flag: return e` guards reach the
                    # tail-position return folding
                    out.extend(self._rewrite_block(
                        des + list(stmts[idx + 1:]), tail=tail))
                    return out
            res = self.visit(s)
            if isinstance(res, list):
                out.extend(res)
            elif res is not None:
                out.append(res)
            # names bound by this statement become visible to later ones
            self.bound_names.update(_assigned_names([s]))
        return out

    def _fold_return_if(self, node, rest):
        """Rewrite a tail-position ``if`` that returns into
        ``return convert_ifelse_ret(...)``, folding the remainder of the
        suite into the else branch (exact Python semantics: when the
        condition is false, control falls through to the rest)."""
        node.test = self.visit(node.test)
        body_src = list(node.body)
        orelse_src = list(node.orelse) + list(rest)
        assigned = _assigned_names(body_src + orelse_src)
        assigned = [n for n in assigned
                    if n in self._carry_ok or not n.startswith("__jst_")]

        outer_bound = set(self.bound_names)
        body_r = self._rewrite_block(body_src, tail=True)
        self.bound_names = set(outer_bound)
        orelse_r = self._rewrite_block(orelse_src, tail=True)
        self.bound_names = outer_bound

        def ensure_ret(block):
            if not block or not isinstance(block[-1], ast.Return):
                block.append(ast.Return(value=ast.Constant(value=None)))
            return block

        true_name = self._uid("rtrue")
        false_name = self._uid("rfalse")
        t_fn = ast.FunctionDef(
            name=true_name, args=_plain_args(assigned),
            body=ensure_ret(body_r), decorator_list=[], returns=None,
            type_comment=None, **_tp())
        f_fn = ast.FunctionDef(
            name=false_name, args=_plain_args(assigned),
            body=ensure_ret(orelse_r), decorator_list=[], returns=None,
            type_comment=None, **_tp())
        ret = ast.Return(value=ast.Call(
            func=ast.Attribute(value=ast.Name(id=_JST, ctx=ast.Load()),
                               attr="convert_ifelse_ret", ctx=ast.Load()),
            args=[node.test,
                  ast.Name(id=true_name, ctx=ast.Load()),
                  ast.Name(id=false_name, ctx=ast.Load()),
                  _name_tuple_or_undefined(assigned, self.bound_names)],
            keywords=[]))
        nodes = [t_fn, f_fn, ret]
        for n in nodes:
            ast.copy_location(n, node)
            ast.fix_missing_locations(n)
        return nodes

    def visit_If(self, node):
        node.test = self.visit(node.test)
        outer_bound = set(self.bound_names)  # names bound BEFORE the branch
        node.body = self._rewrite_block(node.body)
        self.bound_names = set(outer_bound)
        node.orelse = self._rewrite_block(node.orelse)
        self.bound_names = outer_bound
        if (_has_flow_escape(node.body, loop=False)
                or _has_flow_escape(node.orelse, loop=False)):
            return node  # early return/break: leave Python semantics
        assigned = [n for n in _assigned_names(node.body + node.orelse)
                    if n in self._carry_ok or not n.startswith("__jst_")]
        if not assigned:
            # no state change: still needs the runtime dispatch for side
            # effects? a tensor-pred if with no assignments is either dead
            # or side-effecting — keep Python semantics (trace errors will
            # name the site)
            return node
        true_name = self._uid("true")
        false_name = self._uid("false")
        tmp = self._uid("ifout")

        def mk_branch(name, body):
            fn = ast.FunctionDef(
                name=name,
                args=_plain_args(assigned),
                body=(body or [ast.Pass()]) + [_return_tuple(assigned)],
                decorator_list=[], returns=None, type_comment=None,
                **_tp(),
            )
            return fn

        call = ast.Assign(
            targets=[ast.Name(id=tmp, ctx=ast.Store())],
            value=ast.Call(
                func=ast.Attribute(value=ast.Name(id=_JST, ctx=ast.Load()),
                                   attr="convert_ifelse", ctx=ast.Load()),
                args=[node.test,
                      ast.Name(id=true_name, ctx=ast.Load()),
                      ast.Name(id=false_name, ctx=ast.Load()),
                      _name_tuple_or_undefined(assigned, self.bound_names)],
                keywords=[]))
        unpack = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in assigned],
                ctx=ast.Store())],
            value=ast.Name(id=tmp, ctx=ast.Load()))
        nodes = [mk_branch(true_name, node.body),
                 mk_branch(false_name, node.orelse), call, unpack]
        for n in nodes:
            ast.copy_location(n, node)
            ast.fix_missing_locations(n)
        return nodes

    def visit_While(self, node):
        node.test = self.visit(node.test)
        outer_bound = set(self.bound_names)
        node.body = self._rewrite_block(node.body)
        self.bound_names = outer_bound
        if node.orelse or _has_flow_escape(node.body, loop=True):
            return node
        assigned = _assigned_names(node.body)
        carried = sorted(
            n for n in set(assigned) | (_read_names(node.test)
                                        & (self.bound_names
                                           | set(assigned)))
            if n in self._carry_ok or not n.startswith("__jst_"))
        if not carried:
            return node
        cond_name = self._uid("cond")
        body_name = self._uid("body")
        tmp = self._uid("whileout")
        cond_fn = ast.FunctionDef(
            name=cond_name, args=_plain_args(carried),
            body=[ast.Return(value=node.test)],
            decorator_list=[], returns=None, type_comment=None, **_tp())
        body_fn = ast.FunctionDef(
            name=body_name, args=_plain_args(carried),
            body=node.body + [_return_tuple(carried)],
            decorator_list=[], returns=None, type_comment=None, **_tp())
        call = ast.Assign(
            targets=[ast.Name(id=tmp, ctx=ast.Store())],
            value=ast.Call(
                func=ast.Attribute(value=ast.Name(id=_JST, ctx=ast.Load()),
                                   attr="convert_while", ctx=ast.Load()),
                args=[ast.Name(id=cond_name, ctx=ast.Load()),
                      ast.Name(id=body_name, ctx=ast.Load()),
                      _name_tuple_or_undefined(carried, self.bound_names,
                                               self._undef_fallbacks)],
                keywords=[]))
        unpack = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in carried],
                ctx=ast.Store())],
            value=ast.Name(id=tmp, ctx=ast.Load()))
        nodes = [cond_fn, body_fn, call, unpack]
        for n in nodes:
            ast.copy_location(n, node)
            ast.fix_missing_locations(n)
        return nodes

    @staticmethod
    def _is_range_for(node) -> bool:
        it = node.iter
        return (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range" and not it.keywords
                and 1 <= len(it.args) <= 3
                and isinstance(node.target, ast.Name))

    def visit_For(self, node):
        # Desugar `for x in range(...)` / `for x in <expr>` into a while
        # (the while visitor then decides python-vs-lax at runtime).  Only
        # range() iteration is desugared — generic iterables keep Python
        # semantics (matching the reference's for_loop transformer scope).
        if node.orelse or _has_flow_escape(node.body, loop=True):
            self.generic_visit(node)
            return node
        res = self._for_range_to_while(node)
        if res is None:
            # generic iterables keep Python semantics — Tensor.__iter__
            # yields leading-dim slices in eager AND traced modes, so
            # tensor iteration needs no rewrite (exact unroll; the
            # leading dim is static under trace)
            self.generic_visit(node)
            return node
        init, loop = res
        rewritten = []
        for n in init:
            rewritten.append(n)
            self.bound_names.update(_assigned_names([n]))
        out = self.visit(loop)
        self._undef_fallbacks.pop(node.target.id, None)
        rewritten.extend(out if isinstance(out, list) else [out])
        return rewritten

    # -- break/continue/return desugar (ref break_continue_transformer.py,
    #    return_transformer.py: bool guard variables) ----------------------

    def _can_desugar_escapes(self, stmts) -> bool:
        """True when every flow escape in the suite can be converted to
        guard flags: escapes directly at loop level or inside plain ifs;
        nested loops only if their returns are themselves desugarable;
        try/with/yield involvement bails to Python semantics."""
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            if isinstance(s, (ast.Try, ast.With)):
                if any(isinstance(x, (ast.Break, ast.Continue, ast.Return,
                                      ast.Yield, ast.YieldFrom))
                       for x in ast.walk(s)):
                    return False
                continue
            if isinstance(s, (ast.While, ast.For)):
                if any(isinstance(x, ast.Return) for x in ast.walk(s)):
                    if s.orelse:
                        return False
                    if isinstance(s, ast.For) and not self._is_range_for(s):
                        return False
                    if not self._can_desugar_escapes(s.body):
                        return False
                continue
            if isinstance(s, ast.If):
                if not self._can_desugar_escapes(s.body) \
                        or not self._can_desugar_escapes(s.orelse):
                    return False
        return True

    def _try_desugar_escapes(self, node):
        """Loop containing break/continue/return -> flag-carried
        replacement statement list, or None when not applicable."""
        if not isinstance(node, (ast.While, ast.For)) or node.orelse:
            return None
        if not _has_flow_escape(node.body, loop=True):
            return None
        if any(isinstance(x, (ast.Yield, ast.YieldFrom))
               for x in ast.walk(node)):
            return None
        if not self._can_desugar_escapes(node.body):
            return None
        if isinstance(node, ast.For):
            res = self._for_range_to_while(node)
            if res is None:
                return None
            init, loop = res
            # the induction-variable increment appended by the range
            # desugar must run on EVERY iteration — a continue guard that
            # swallowed it would freeze the loop forever.  (Running it
            # after break/return is harmless: the user-visible loop var is
            # re-bound from the induction var at the top of each
            # iteration, so it keeps its escape-time value.)
            return init + self._desugar_while_escapes(loop, keep_tail=1)
        return self._desugar_while_escapes(node)

    def _desugar_while_escapes(self, node, keep_tail: int = 0):
        """``while`` with break/continue/return -> bool guard flags (the
        reference's transformer trick retargeted at the lax carry):

        - ``break`` -> ``__jst_brk = True``; joins the loop test;
        - ``continue`` -> ``__jst_cont = True``; reset at body top;
        - ``return e`` -> per-site ``__jst_ret_k = True``; joins the loop
          test; post-loop ``if __jst_ret_k: return e`` (state is frozen by
          the guards after any flag sets, so ``e`` evaluates to its
          escape-time value — ``e`` must be side-effect-free);
        - after any statement that may set a flag, the rest of its suite
          is wrapped in ``if not (<flags>):``.

        All flags are pre-initialised to False (typed for the lax carry)
        and registered carry-eligible.
        """
        flags: dict = {"brk": None, "cont": None}
        ret_sites: list = []

        def new_flag(kind):
            name = self._uid(kind)
            self._carry_ok.add(name)
            # a nested loop's flag is (re)initialised inside the enclosing
            # loop's body, so the enclosing carry needs a typed fallback
            self._undef_fallbacks[name] = ast.Constant(False)
            return name

        def get(kind):
            if flags[kind] is None:
                flags[kind] = new_flag(kind)
            return flags[kind]

        def assign_true(name):
            return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                              value=ast.Constant(True))

        def universe():
            return {f for f in flags.values() if f} | \
                {f for f, _ in ret_sites}

        def assigned_flags(stmt):
            uni = universe()
            return {t.id for sub in ast.walk(stmt)
                    if isinstance(sub, ast.Assign)
                    for t in sub.targets
                    if isinstance(t, ast.Name) and t.id in uni}

        def rew(stmts):
            out = []
            for i, s in enumerate(stmts):
                if isinstance(s, ast.Break):
                    out.append(assign_true(get("brk")))
                    return out          # rest of the suite is unreachable
                if isinstance(s, ast.Continue):
                    out.append(assign_true(get("cont")))
                    return out
                if isinstance(s, ast.Return):
                    f = new_flag("ret")
                    ret_sites.append((f, s.value or ast.Constant(None)))
                    out.append(assign_true(f))
                    return out
                set_here: set = set()
                if isinstance(s, ast.If) and _has_flow_escape([s],
                                                              loop=True):
                    s = ast.If(test=s.test, body=rew(s.body) or [ast.Pass()],
                               orelse=rew(s.orelse))
                    out.append(s)
                    set_here = assigned_flags(s)
                elif isinstance(s, (ast.While, ast.For)) and any(
                        isinstance(x, ast.Return) for x in ast.walk(s)):
                    # nested loop with returns: desugar it, then keep
                    # rewriting its replacement (whose trailing
                    # `if flag: return e` re-enters the Return path above,
                    # migrating the return outward level by level)
                    repl = self._try_desugar_escapes(s)
                    if repl is None:       # checked by _can_desugar_escapes
                        out.append(s)
                        continue
                    out.extend(rew(repl + list(stmts[i + 1:])))
                    return out
                else:
                    out.append(s)
                if set_here and i < len(stmts) - 1:
                    names = sorted(set_here)
                    pred = ast.Name(id=names[0], ctx=ast.Load()) \
                        if len(names) == 1 else ast.BoolOp(
                            op=ast.Or(),
                            values=[ast.Name(id=n, ctx=ast.Load())
                                    for n in names])
                    guard = ast.If(
                        test=ast.UnaryOp(op=ast.Not(), operand=pred),
                        body=rew(list(stmts[i + 1:])) or [ast.Pass()],
                        orelse=[])
                    out.append(guard)
                    return out
            return out

        body_src = list(node.body)
        tail = body_src[len(body_src) - keep_tail:] if keep_tail else []
        if keep_tail:
            body_src = body_src[:len(body_src) - keep_tail]
        new_body = (rew(body_src) or [ast.Pass()]) + tail
        if flags["cont"] is not None:
            new_body = [ast.Assign(
                targets=[ast.Name(id=flags["cont"], ctx=ast.Store())],
                value=ast.Constant(False))] + new_body
        exit_flags = ([flags["brk"]] if flags["brk"] else []) + \
            [f for f, _ in ret_sites]
        test = node.test
        if exit_flags:
            test = ast.BoolOp(
                op=ast.And(),
                values=[test] + [
                    ast.UnaryOp(op=ast.Not(),
                                operand=ast.Name(id=f, ctx=ast.Load()))
                    for f in exit_flags])
        inits = [ast.Assign(targets=[ast.Name(id=f, ctx=ast.Store())],
                            value=ast.Constant(False))
                 for f in exit_flags + ([flags["cont"]]
                                        if flags["cont"] else [])]
        post = [ast.If(test=ast.Name(id=f, ctx=ast.Load()),
                       body=[ast.Return(value=e)], orelse=[])
                for f, e in ret_sites]
        new_loop = ast.While(test=test, body=new_body, orelse=[])
        result = inits + [new_loop] + post
        for n in result:
            ast.copy_location(n, node)
            ast.fix_missing_locations(n)
        return result

    def _for_range_to_while(self, node):
        """range-style ``for`` -> ([init stmts], While), or None.

        Registers the internal induction var as carry-eligible and, when
        the loop var is unbound before the loop, its typed lax fallback."""
        if not self._is_range_for(node):
            return None
        args = node.iter.args
        if len(args) == 1:
            start, stop, step = ast.Constant(0), args[0], ast.Constant(1)
        elif len(args) == 2:
            start, stop, step = args[0], args[1], ast.Constant(1)
        else:
            start, stop, step = args
        ivar = node.target.id
        ind_var = self._uid("i")   # internal induction variable
        stop_var = self._uid("stop")
        step_var = self._uid("step")
        # Iterate the internal induction variable and bind the user's loop
        # variable from it at the top of each iteration: the post-loop value
        # of `i` is then the last in-range value (Python semantics), body
        # mutations of `i` don't perturb iteration, and an empty range never
        # rebinds a previously-bound `i`.  When `i` is unbound before the
        # loop it gets an Undefined-with-fallback init: eager empty ranges
        # leave it undefined, while the lax.while_loop path (which must
        # carry a typed value) falls back to `start`.
        init = [
            ast.Assign(targets=[ast.Name(id=ind_var, ctx=ast.Store())],
                       value=start),
            ast.Assign(targets=[ast.Name(id=stop_var, ctx=ast.Store())],
                       value=stop),
            ast.Assign(targets=[ast.Name(id=step_var, ctx=ast.Store())],
                       value=step),
        ]
        # (__jst_i - stop) * sign(step) < 0  — handles negative steps
        test = ast.Compare(
            left=ast.BinOp(
                left=ast.BinOp(left=ast.Name(id=ind_var, ctx=ast.Load()),
                               op=ast.Sub(),
                               right=ast.Name(id=stop_var, ctx=ast.Load())),
                op=ast.Mult(),
                right=ast.Name(id=step_var, ctx=ast.Load())),
            ops=[ast.Lt()], comparators=[ast.Constant(0)])
        bind = ast.Assign(targets=[ast.Name(id=ivar, ctx=ast.Store())],
                          value=ast.Name(id=ind_var, ctx=ast.Load()))
        incr = ast.AugAssign(target=ast.Name(id=ind_var, ctx=ast.Store()),
                             op=ast.Add(),
                             value=ast.Name(id=step_var, ctx=ast.Load()))
        # note: test compares (i-stop)*step < 0, so step sign is honored;
        # a zero step loops forever exactly like Python range() forbids —
        # range() would have raised already in the original code
        loop = ast.While(test=test, body=[bind] + node.body + [incr],
                         orelse=[])
        self._carry_ok.add(ind_var)
        if ivar not in self.bound_names:
            self._undef_fallbacks[ivar] = ind_var
        for n in init + [loop]:
            ast.copy_location(n, node)
            ast.fix_missing_locations(n)
        return init, loop


def _tp():
    """Python-version-dependent extra FunctionDef fields."""
    import sys
    return {"type_params": []} if sys.version_info >= (3, 12) else {}


def _empty_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None, kwonlyargs=[],
                         kw_defaults=[], kwarg=None, defaults=[])


def _plain_args(names):
    return ast.arguments(
        posonlyargs=[], args=[ast.arg(arg=n) for n in names], vararg=None,
        kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[])


def _return_tuple(names):
    return ast.Return(value=ast.Tuple(
        elts=[ast.Name(id=n, ctx=ast.Load()) for n in names],
        ctx=ast.Load()))


def _name_tuple_or_undefined(names, bound, fallbacks=None):
    elts = []
    for n in names:
        if n in bound:
            elts.append(ast.Name(id=n, ctx=ast.Load()))
        elif fallbacks and n in fallbacks:
            fb = fallbacks[n]
            fb_node = ast.Name(id=fb, ctx=ast.Load()) \
                if isinstance(fb, str) else fb
            elts.append(ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id=_JST, ctx=ast.Load()),
                    attr="undef_or", ctx=ast.Load()),
                args=[fb_node],
                keywords=[]))
        else:
            elts.append(ast.Attribute(
                value=ast.Name(id=_JST, ctx=ast.Load()),
                attr="Undefined", ctx=ast.Load()))
    return ast.Tuple(elts=elts, ctx=ast.Load())


# ---------------------------------------------------------------------------
# Function conversion
# ---------------------------------------------------------------------------

_conversion_cache: dict = {}


def convert_function(fn: Callable) -> Callable:
    """AST-convert ``fn``'s control flow; returns ``fn`` unchanged when the
    source is unavailable or conversion is disabled for it."""
    if getattr(fn, "__not_to_static__", False):
        return fn
    inner = fn.__func__ if inspect.ismethod(fn) else fn
    cached = _conversion_cache.get(inner)
    if cached is not None:
        converted = cached
    else:
        converted = _convert_inner(inner)
        _conversion_cache[inner] = converted
    if converted is inner:
        return fn
    if inspect.ismethod(fn):
        return converted.__get__(fn.__self__, type(fn.__self__))
    return converted


def _convert_inner(fn):
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fdef.decorator_list = []  # decorators already applied to the original

    needs = any(isinstance(n, (ast.If, ast.While, ast.For, ast.BoolOp))
                or (isinstance(n, ast.UnaryOp) and isinstance(n.op, ast.Not))
                for n in ast.walk(fdef))
    if not needs:
        return fn

    _ControlFlowTransformer().visit(fdef)
    ast.fix_missing_locations(tree)

    # Rebuild closure access: wrap in a factory taking the free variables.
    freevars = fn.__code__.co_freevars
    factory_name = "__jst_factory__"
    factory = ast.FunctionDef(
        name=factory_name,
        args=_plain_args(list(freevars)),
        body=[fdef, ast.Return(value=ast.Name(id=fdef.name, ctx=ast.Load()))],
        decorator_list=[], returns=None, type_comment=None, **_tp())
    mod = ast.Module(body=[factory], type_ignores=[])
    ast.fix_missing_locations(mod)

    from . import dy2static as _self
    namespace = dict(fn.__globals__)
    namespace[_JST] = _self
    try:
        code = compile(mod, filename=f"<dy2static {fn.__qualname__}>",
                       mode="exec")
        exec(code, namespace)
        cells = [c.cell_contents for c in (fn.__closure__ or ())]
        new_fn = namespace[factory_name](*cells)
    except Exception:
        return fn  # any conversion failure falls back to the traced path
    new_fn = functools.wraps(fn)(new_fn)
    new_fn.__dy2static_converted__ = True
    return new_fn
