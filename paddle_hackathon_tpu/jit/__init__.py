"""paddle.jit equivalent — dygraph→static compilation.

Ref ``python/paddle/jit`` + ``fluid/dygraph/dygraph_to_static/``. The
reference rewrites Python AST into ProgramDesc ops and runs them through the
``run_program`` op (``program_translator.py:340``, ``partial_program.py``).

TPU-native replacement (SURVEY §7 phase 4): the *same* Python code that runs
eagerly is traced by jax.jit into a jaxpr/StableHLO program — no AST rewriting
needed because ops are jax-traceable and Python control flow is resolved at
trace time (per input-spec specialization, cached like the reference's
``get_concrete_program`` cache ``program_translator.py:441,475``). Training
through a compiled program attaches ONE tape node wrapping the program's
``jax.vjp`` — the exact role of the reference's ``run_program`` grad.
"""

from .api import (InputSpec, StaticFunction, _trace_state, ignore_module,  # noqa: F401
                  not_to_static, to_static)
from .save_load import TranslatedLayer, load, save  # noqa: F401


def set_code_level(level=100, also_to_stdout=False):
    """Ref jit/dy2static logging: here tracing is jax.jit, so 'code level'
    maps to printing the traced jaxpr; stored for StaticFunction to honor."""
    from . import api as _api
    _api._trace_state.code_level = level


def set_verbosity(level=0, also_to_stdout=False):
    from . import api as _api
    _api._trace_state.verbosity = level


class ProgramTranslator:
    """Singleton toggling dy2static globally (ref program_translator.py
    ProgramTranslator.enable)."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def enable(self, enable_to_static=True):
        from . import api as _api
        _api._trace_state.enabled = bool(enable_to_static)

    @staticmethod
    def get_instance():
        return ProgramTranslator()


class TracedLayer:
    """Ref fluid/dygraph/jit.py TracedLayer: trace a dygraph layer into a
    compiled callable. Here = jit.to_static specialization + save."""

    def __init__(self, layer, fn):
        self._layer = layer
        self._fn = fn

    @staticmethod
    def trace(layer, inputs):
        from .api import to_static
        fn = to_static(layer)
        outs = fn(*inputs)
        return outs, TracedLayer(layer, fn)

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)

    def save_inference_model(self, path, feed=None, fetch=None):
        from .save_load import save as jit_save
        jit_save(self._layer, path)
