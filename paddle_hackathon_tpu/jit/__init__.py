"""paddle.jit equivalent — dygraph→static compilation.

Ref ``python/paddle/jit`` + ``fluid/dygraph/dygraph_to_static/``. The
reference rewrites Python AST into ProgramDesc ops and runs them through the
``run_program`` op (``program_translator.py:340``, ``partial_program.py``).

TPU-native replacement (SURVEY §7 phase 4): the *same* Python code that runs
eagerly is traced by jax.jit into a jaxpr/StableHLO program — no AST rewriting
needed because ops are jax-traceable and Python control flow is resolved at
trace time (per input-spec specialization, cached like the reference's
``get_concrete_program`` cache ``program_translator.py:441,475``). Training
through a compiled program attaches ONE tape node wrapping the program's
``jax.vjp`` — the exact role of the reference's ``run_program`` grad.
"""

from .api import (InputSpec, StaticFunction, _trace_state, ignore_module,  # noqa: F401
                  not_to_static, to_static)
from .save_load import TranslatedLayer, load, save  # noqa: F401
