"""to_static: trace-and-cache compilation of Layers/functions.

The ``StaticFunction`` program cache is keyed by (shapes, dtypes, training
mode) — the same idea as the reference's ``ProgramCache`` keyed by InputSpec
(``program_translator.py:475``).
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp

from ..core import autograd, flags
from ..core import random as core_random
from ..core.autograd import GradNode, _LeafSlot
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor
from ..nn.layer import Layer

class _TraceState(threading.local):
    # threading.local subclass: every thread sees the default, not just the
    # importing thread.
    tracing = False


_trace_state = _TraceState()


def _trace_state_clean() -> bool:
    """True when no jax trace is active (safe to enter our own jit)."""
    try:
        return jax.core.trace_state_clean()
    except AttributeError:  # older/newer jax: conservative probe
        return not isinstance(jnp.zeros(()) + 0, jax.core.Tracer)


class InputSpec:
    """paddle.static.InputSpec equivalent."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = convert_dtype(dtype)
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    @classmethod
    def from_tensor(cls, t: Tensor, name=None):
        return cls(t.shape, str(t.dtype), name)


def _spec_key(args):
    parts = []
    for a in args:
        if isinstance(a, Tensor):
            parts.append(("T", tuple(a._value.shape), str(a._value.dtype)))
        elif isinstance(a, (jnp.ndarray, jax.Array)):
            parts.append(("A", tuple(a.shape), str(a.dtype)))
        elif isinstance(a, (int, float, bool, str, type(None))):
            parts.append(("S", a))
        else:
            parts.append(("O", type(a).__name__))
    return tuple(parts)


class StaticFunction:
    """Compiled wrapper over a function or Layer method
    (ref ``StaticFunction`` ``program_translator.py:340``)."""

    def __init__(self, function, input_spec: Optional[Sequence[InputSpec]] = None,
                 build_strategy=None, backend=None):
        self._raw_fn = function
        self._conv_fn = None  # dy2static-converted, built lazily
        self._input_spec = input_spec
        self._cache = {}
        self._layer: Optional[Layer] = getattr(function, "__self__", None)
        # program-cache key of a just-traced build, consumed by __call__ to
        # time the (lazy) first compile+run and report it to the program
        # observatory
        self._pending_build = None
        functools.update_wrapper(self, function)

    def _site_label(self) -> str:
        """Observatory site label: the layer class when bound (one label
        per user Layer type — bounded, PHT005), else the function name."""
        if self._layer is not None:
            return f"to_static.{type(self._layer).__name__}"
        return f"to_static.{getattr(self._raw_fn, '__name__', 'fn')}"

    def _report_build(self, key, t0) -> None:
        """Report a program build (cache-miss trace + lazy compile) to the
        program observatory; best-effort — telemetry never fails user code."""
        if key is None:
            return
        try:
            from ..observability.programs import observe_static_build
            observe_static_build(self._site_label(), key,
                                 time.perf_counter() - t0)
        except Exception:
            pass

    @property
    def _fn(self):
        """The function to trace: AST control-flow-converted (dy2static) so
        Python if/while/for on tensor values become lax.cond/while_loop
        (ref program_translator.py:340 + ifelse/loop transformers)."""
        if self._conv_fn is None:
            from . import dy2static
            self._conv_fn = dy2static.convert_function(self._raw_fn)
        return self._conv_fn

    # -- program construction ---------------------------------------------
    def _build(self, key, n_args, training):
        layer = self._layer
        fn = self._fn

        def pure(param_list, buffer_list, rng_key, *jax_args):
            param_keys, buffer_keys = key_meta
            params = dict(zip(param_keys, param_list))
            buffers = dict(zip(buffer_keys, buffer_list))
            targs = [Tensor(a) if isinstance(a, jax.Array) else a
                     for a in jax_args]
            prev = getattr(_trace_state, "tracing", False)
            _trace_state.tracing = True
            try:
                with core_random.rng_scope(rng_key), autograd.no_grad():
                    if layer is not None:
                        with layer._swap_state(params, buffers):
                            out = fn(*targs)
                            new_buffers = [
                                b._value for b in _buffer_tensors(layer)]
                    else:
                        out = fn(*targs)
                        new_buffers = []
            finally:
                _trace_state.tracing = prev
            out_vals = jax.tree.map(
                lambda t: t._value if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor))
            return out_vals, new_buffers

        if layer is not None:
            params, buffers = layer.functional_state()
            key_meta = (sorted(params), sorted(buffers))
        else:
            key_meta = ((), ())
        # Non-tensor python args are static: they are already baked into the
        # program-cache key, so each distinct value gets its own trace (the
        # reference's per-InputSpec specialization).
        spec_key = key[0]
        static_argnums = tuple(
            3 + i for i, part in enumerate(spec_key) if part[0] == "S")
        jitted = jax.jit(pure, static_argnums=static_argnums)
        return jitted, key_meta

    def get_concrete_program(self, *args):
        """Trace (or fetch) the program for this arg signature."""
        training = self._layer.training if self._layer is not None else False
        key = (_spec_key(args), training)
        if key not in self._cache:
            if len(self._cache) >= flags.flag("jit_cache_size"):
                self._cache.pop(next(iter(self._cache)))  # evict oldest
                try:
                    from ..observability.programs import \
                        observe_static_eviction
                    observe_static_eviction(self._site_label())
                except Exception:
                    pass
            self._cache[key] = self._build(key, len(args), training)
            self._pending_build = key
        return self._cache[key]

    # -- execution ---------------------------------------------------------
    def __call__(self, *args):
        layer = self._layer
        # Nested-trace transparency: when invoked inside another jax trace
        # (e.g. a to_static layer used from a compiled train step /
        # functional_call), inline the raw function into the enclosing trace
        # instead of nesting jax.jit — nesting re-traces needlessly and a
        # split of the global RNG under the outer trace would poison it with
        # a tracer (the run_program op composes for the same reason in the
        # reference). Detected from the trace state itself so raw-array /
        # container / closure tracers are covered too.
        if not _trace_state_clean():
            if layer is None:
                return self._fn(*args)
            # guard in-place buffer updates (BN stats): if the enclosing
            # caller did not swap state (functional_call does), a traced
            # update would corrupt the live layer — snapshot and drop any
            # buffer value that became a tracer.
            bufs = list(_buffer_tensors(layer))
            saved = [b._value for b in bufs]
            try:
                return self._fn(*args)
            finally:
                for b, old in zip(bufs, saved):
                    if isinstance(b._value, jax.core.Tracer):
                        b._value = old
        jitted, (param_keys, buffer_keys) = self.get_concrete_program(*args)
        build_key, self._pending_build = self._pending_build, None
        t_build = time.perf_counter()
        if layer is not None:
            params, buffers = layer.functional_state()
            param_list = [params[k] for k in param_keys]
            buffer_list = [buffers[k] for k in buffer_keys]
            param_tensors = dict(layer.named_parameters())
        else:
            param_list, buffer_list, param_tensors = [], [], {}
        jax_args = [a._value if isinstance(a, Tensor) else a for a in args]
        rng_key = core_random.split_key()

        # Which inputs require grad? (tape integration = run_program grad)
        tape_on = autograd.is_grad_enabled()
        diff_params = [k for k in param_keys
                       if tape_on and not param_tensors[k].stop_gradient]
        diff_args = [i for i, a in enumerate(args)
                     if tape_on and isinstance(a, Tensor)
                     and not a.stop_gradient
                     and jnp.issubdtype(a._value.dtype, jnp.inexact)]

        if not diff_params and not diff_args:
            out_vals, new_buffers = jitted(param_list, buffer_list, rng_key,
                                           *jax_args)
            self._report_build(build_key, t_build)
            self._write_buffers(buffer_keys, new_buffers)
            return _wrap_tree(out_vals, None)

        dp_vals = [params[k] for k in diff_params]
        da_vals = [jax_args[i] for i in diff_args]

        def closed(dp, da):
            plist = list(param_list)
            for k, v in zip(diff_params, dp):
                plist[param_keys.index(k)] = v
            alist = list(jax_args)
            for i, v in zip(diff_args, da):
                alist[i] = v
            return jitted(plist, buffer_list, rng_key, *alist)

        (out_vals, new_buffers), vjp_fn = jax.vjp(closed, dp_vals, da_vals)
        self._report_build(build_key, t_build)
        self._write_buffers(buffer_keys, new_buffers)

        flat_out, treedef = jax.tree.flatten(out_vals)
        n_out = len(flat_out)
        out_avals = [(o.shape, o.dtype) for o in flat_out]
        # buffers receive zero cotangent automatically (they are not node
        # outputs); vjp runs on the full (out, new_buffers) structure.
        zero_bufs = [jnp.zeros(b.shape, b.dtype) for b in new_buffers]

        def node_vjp(cotangents):
            with autograd.no_grad():
                cot_tree = jax.tree.unflatten(treedef, list(cotangents))
                dp_g, da_g = vjp_fn((cot_tree, zero_bufs))
                return tuple(dp_g) + tuple(da_g)

        parents = []
        for k in diff_params:
            t = param_tensors[k]
            parents.append((t._grad_node, t._out_idx) if t._grad_node
                           else _LeafSlot(t))
        for i in diff_args:
            t = args[i]
            parents.append((t._grad_node, t._out_idx) if t._grad_node
                           else _LeafSlot(t))
        node = GradNode("static_program", node_vjp, parents, n_out, out_avals)

        wrapped_flat = [Tensor(o, stop_gradient=False, _grad_node=node,
                               _out_idx=i) for i, o in enumerate(flat_out)]
        return jax.tree.unflatten(treedef, wrapped_flat)

    def _write_buffers(self, buffer_keys, new_buffers):
        if self._layer is None or not buffer_keys:
            return
        lookup = {}
        for name, b in _named_buffer_tensors(self._layer):
            lookup[name] = b
        for k, v in zip(buffer_keys, new_buffers):
            lookup[k]._set_value(v)

    @property
    def concrete_programs(self):
        return list(self._cache.values())

    def rollback(self):
        """Return the original (eager) function."""
        return self._raw_fn


def _named_buffer_tensors(layer):
    for name, sub in layer._traverse("", True):
        for bname, b in sub._buffers.items():
            if b is not None:
                yield (f"{name}.{bname}" if name else bname), b


def _buffer_tensors(layer):
    return [b for name, b in sorted(_named_buffer_tensors(layer))]


def _wrap_tree(out_vals, node):
    return jax.tree.map(lambda v: Tensor(v) if isinstance(v, jax.Array) else v,
                        out_vals)


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """@paddle.jit.to_static equivalent."""

    def deco(fn):
        if isinstance(fn, Layer):
            # decorate the layer's forward; keep layer callable semantics
            static = StaticFunction(fn.forward, input_spec, build_strategy)
            fn.forward = static
            return fn
        return StaticFunction(fn, input_spec, build_strategy)

    if function is not None:
        return deco(function)
    return deco


def not_to_static(fn):
    fn.__not_to_static__ = True
    return fn


def ignore_module(modules):
    return None
