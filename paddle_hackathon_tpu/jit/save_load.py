"""jit.save / jit.load — deployable inference artifacts.

Ref ``paddle.jit.save`` (``__model__`` + params via ``save_inference_model``)
and the C++ ``jit.Layer`` loader (``paddle/fluid/jit/layer.h``). TPU-native
artifact: the traced program is serialized as **StableHLO** via ``jax.export``
(portable across jax versions/hardware — the role ProgramDesc protobuf plays
in the reference), parameters ride in an npz member, and ``TranslatedLayer``
replays the program through XLA.
"""

from __future__ import annotations

import io as _io
import json
import os
import zipfile

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as core_random
from ..core.tensor import Tensor
from ..nn.layer import Layer
from .api import InputSpec, StaticFunction

_MAGIC = "paddle_hackathon_tpu.jit.v1"


def save(layer, path, input_spec=None, **configs):
    """Trace ``layer`` (eval mode) for ``input_spec`` and serialize."""
    if isinstance(layer, StaticFunction):
        static = layer
    elif isinstance(layer, Layer):
        fwd = layer.forward
        static = fwd if isinstance(fwd, StaticFunction) else StaticFunction(fwd)
    else:
        raise TypeError("jit.save expects a Layer or a to_static function")

    target_layer = static._layer
    if input_spec is None:
        spec = static._input_spec
        if spec is None:
            raise ValueError("input_spec is required to jit.save")
    else:
        spec = input_spec
    # -1 / None dims become jax.export symbolic dimensions, so the exported
    # StableHLO program is shape-polymorphic over them (e.g. variable batch).
    example_args = []
    sym_names = iter(f"_d{i}" for i in range(64))
    for s in spec:
        if isinstance(s, Tensor):
            s = InputSpec.from_tensor(s)
        if -1 in s.shape:
            dims = ",".join(next(sym_names) if d == -1 else str(d)
                            for d in s.shape)
            shape = jax.export.symbolic_shape(dims)
            example_args.append(jax.ShapeDtypeStruct(shape, s.dtype))
        else:
            example_args.append(jnp.zeros(tuple(s.shape), s.dtype))

    was_training = target_layer.training if target_layer is not None else False
    if target_layer is not None:
        target_layer.eval()
    try:
        # build the program directly (example args may be symbolic
        # ShapeDtypeStructs, which cannot pass through the Tensor cache path)
        build_key = (tuple(("A", i, str(a.dtype))
                           for i, a in enumerate(example_args)), False)
        jitted, (param_keys, buffer_keys) = static._build(
            build_key, len(example_args), False)
        if target_layer is not None:
            params, buffers = target_layer.functional_state()
            param_list = [params[k] for k in param_keys]
            buffer_list = [buffers[k] for k in buffer_keys]
        else:
            param_list, buffer_list = [], []
        # a RAW uint32 key, not jax.random.key(0): typed key avals
        # (key<fry>) are not serializable by jax.export on jax<0.6, and
        # every jax.random op accepts the raw form
        key = jax.random.PRNGKey(0)
        exported = jax.export.export(jitted, platforms=("cpu", "tpu"))(
            param_list, buffer_list, key, *example_args)
    finally:
        if target_layer is not None and was_training:
            target_layer.train()

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    if not path.endswith(".pdmodel"):
        path = path + ".pdmodel"
    arrays = {f"p{i}": np.asarray(v) for i, v in enumerate(param_list)}
    arrays.update({f"b{i}": np.asarray(v) for i, v in enumerate(buffer_list)})
    meta = {
        "n_params": len(param_list),
        "n_buffers": len(buffer_list),
        "param_keys": param_keys,
        "buffer_keys": list(buffer_keys),
        "input_specs": [{"shape": [str(d) for d in a.shape],
                         "dtype": str(a.dtype)} for a in example_args],
    }
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("MAGIC", _MAGIC)
        zf.writestr("program.stablehlo", exported.serialize())
        zf.writestr("meta.json", json.dumps(meta))
        buf = _io.BytesIO()
        np.savez(buf, **arrays)
        zf.writestr("params.npz", buf.getvalue())
    return path


class TranslatedLayer(Layer):
    """Runs a deserialized StableHLO program (ref ``TranslatedLayer`` in
    ``fluid/dygraph/io.py`` / C++ ``jit::Layer``)."""

    def __init__(self, exported, param_arrays, buffer_arrays, meta):
        super().__init__()
        self._exported = exported
        self._param_arrays = [jnp.asarray(p) for p in param_arrays]
        self._buffer_arrays = [jnp.asarray(b) for b in buffer_arrays]
        self._meta = meta

    def forward(self, *args):
        jax_args = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
                    for a in args]
        key = core_random.split_key()
        if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
            # the artifact was exported against a RAW uint32 key (typed
            # key avals don't serialize on jax<0.6)
            key = jax.random.key_data(key)
        out_vals, _new_buffers = self._exported.call(
            self._param_arrays, self._buffer_arrays, key, *jax_args)
        return jax.tree.map(
            lambda v: Tensor(v) if isinstance(v, jax.Array) else v, out_vals)


def load(path, **configs):
    if not path.endswith(".pdmodel"):
        path = path + ".pdmodel"
    with zipfile.ZipFile(path, "r") as zf:
        if zf.read("MAGIC").decode() != _MAGIC:
            raise ValueError(f"not a jit artifact: {path}")
        exported = jax.export.deserialize(zf.read("program.stablehlo"))
        meta = json.loads(zf.read("meta.json"))
        npz = np.load(_io.BytesIO(zf.read("params.npz")))
        params = [npz[f"p{i}"] for i in range(meta["n_params"])]
        buffers = [npz[f"b{i}"] for i in range(meta["n_buffers"])]
    return TranslatedLayer(exported, params, buffers, meta)
