"""Actor-style pipeline runtime: interceptors + message bus.

Ref ``paddle/fluid/distributed/fleet_executor/``: ``FleetExecutor``
(``fleet_executor.cc``), ``Carrier`` (``carrier.cc``), ``Interceptor`` /
``ComputeInterceptor`` / ``AmplifierInterceptor`` (``*.cc``), ``MessageBus``
(``message_bus.cc``, brpc inter-rank) and ``TaskNode`` (``task_node.cc``).

TPU-native stance: *within* a slice, pipeline parallelism is compiled into
one SPMD program (``parallel/pipeline.py``) — XLA schedules it. This runtime
covers what compilation cannot: host-side orchestration of heterogeneous
stages (data feeders, eval loops, multi-program serving, DCN-separated
super-stages) with back-pressure. Messages are Python objects on bounded
in-process queues; the bus interface mirrors the brpc one so a TCP transport
can plug in for multi-controller deployments.

Flow control follows the reference's credit scheme (ComputeInterceptor's
``DATA_IS_READY`` / ``DATA_IS_USELESS`` pair): an edge has a buffer depth;
upstream may only fire while it holds credits, downstream returns a credit
when it consumes a message — 1F1B falls out of depth-1 buffers.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..observability.sanitizers import make_lock

__all__ = ["TaskNode", "Carrier", "FleetExecutor", "Interceptor",
           "ComputeInterceptor", "AmplifierInterceptor", "MessageBus"]


# -- messages ----------------------------------------------------------------

DATA_IS_READY = "DATA_IS_READY"
DATA_IS_USELESS = "DATA_IS_USELESS"   # credit return
STOP = "STOP"


@dataclass
class InterceptorMessage:
    """Ref ``interceptor_message.proto``."""
    src: int
    dst: int
    type: str
    payload: Any = None
    scope_idx: int = 0  # microbatch index


# -- task graph --------------------------------------------------------------

@dataclass
class TaskNode:
    """Ref ``task_node.cc``: a stage of work replicated over microbatches."""
    task_id: int
    fn: Optional[Callable[[Any, int], Any]] = None  # (payload, mb_idx) -> out
    role: str = "compute"
    max_run_times: int = 1           # number of microbatches
    run_per_steps: int = 1           # amplifier: fire every k inputs
    run_at_offset: int = 0
    downstream: Dict[int, int] = field(default_factory=dict)  # id -> buffsize
    upstream: Dict[int, int] = field(default_factory=dict)

    def add_downstream_task(self, task_id: int, buff_size: int = 2) -> None:
        self.downstream[task_id] = buff_size

    def add_upstream_task(self, task_id: int, buff_size: int = 2) -> None:
        self.upstream[task_id] = buff_size


class MessageBus:
    """In-process bus (ref ``message_bus.cc``); route by interceptor id."""

    def __init__(self):
        self._boxes: Dict[int, "queue.Queue[InterceptorMessage]"] = {}

    def register(self, interceptor_id: int) -> "queue.Queue":
        q = queue.Queue()
        self._boxes[interceptor_id] = q
        return q

    def send(self, msg: InterceptorMessage) -> bool:
        box = self._boxes.get(msg.dst)
        if box is None:
            return False
        box.put(msg)
        return True


# -- interceptors ------------------------------------------------------------

class Interceptor(threading.Thread):
    """Ref ``interceptor.cc``: an actor with an inbox and a handler."""

    def __init__(self, node: TaskNode, bus: MessageBus, carrier: "Carrier"):
        super().__init__(daemon=True, name=f"interceptor-{node.task_id}")
        self.node = node
        self.bus = bus
        self.carrier = carrier
        self.inbox = bus.register(node.task_id)
        self._stopped = False

    def send(self, dst: int, mtype: str, payload: Any = None,
             scope_idx: int = 0) -> None:
        self.bus.send(InterceptorMessage(self.node.task_id, dst, mtype,
                                         payload, scope_idx))

    def run(self) -> None:
        while not self._stopped:
            msg = self.inbox.get()
            if msg.type == STOP:
                self._stopped = True
                break
            self.handle(msg)

    def handle(self, msg: InterceptorMessage) -> None:
        raise NotImplementedError


class ComputeInterceptor(Interceptor):
    """Ref ``compute_interceptor.cc``: credit-based fire rule.

    Fires when (a) every upstream edge holds a ready input, and (b) every
    downstream edge has a free credit; consuming an input returns a credit
    upstream (``DATA_IS_USELESS``).
    """

    def __init__(self, node, bus, carrier):
        super().__init__(node, bus, carrier)
        self._ready: Dict[int, List[InterceptorMessage]] = {
            u: [] for u in node.upstream}
        self._credits: Dict[int, int] = dict(node.downstream)
        self._run_count = 0

    def _try_fire(self) -> None:
        while (self._run_count < self.node.max_run_times
               and all(q for q in self._ready.values())
               and all(c > 0 for c in self._credits.values())):
            mb = self._run_count
            inputs = {}
            for u, q in self._ready.items():
                m = q.pop(0)
                inputs[u] = m.payload
                self.send(u, DATA_IS_USELESS, scope_idx=m.scope_idx)
            payload = (inputs if len(inputs) > 1 else
                       next(iter(inputs.values())) if inputs else None)
            out = self.node.fn(payload, mb) if self.node.fn else payload
            self._run_count += 1
            for d in self._credits:
                self._credits[d] -= 1
                self.send(d, DATA_IS_READY, out, scope_idx=mb)
            if not self.node.downstream:
                self.carrier.collect(self.node.task_id, mb, out)
            if self._run_count >= self.node.max_run_times:
                self.carrier.done(self.node.task_id)

    def handle(self, msg: InterceptorMessage) -> None:
        if msg.type == DATA_IS_READY:
            if msg.src in self._ready:
                self._ready[msg.src].append(msg)
            # else: kickoff trigger for a source node — nothing to buffer
        elif msg.type == DATA_IS_USELESS:
            self._credits[msg.src] += 1
        self._try_fire()

    def kickoff(self) -> None:
        """Source nodes (no upstream) self-start; credits pace them."""
        if not self.node.upstream:
            self.inbox.put(InterceptorMessage(-1, self.node.task_id,
                                              DATA_IS_READY, None))


class AmplifierInterceptor(ComputeInterceptor):
    """Ref ``amplifier_interceptor.cc``: fire every ``run_per_steps`` inputs
    at ``run_at_offset`` (gradient-accumulation / LR-step style nodes)."""

    def __init__(self, node, bus, carrier):
        super().__init__(node, bus, carrier)
        self._seen = 0
        self._pending: List[Any] = []

    def handle(self, msg: InterceptorMessage) -> None:
        if msg.type == DATA_IS_READY:
            self._seen += 1
            self._pending.append(msg.payload)
            self.send(msg.src, DATA_IS_USELESS, scope_idx=msg.scope_idx)
            k = self.node.run_per_steps
            if (self._seen - self.node.run_at_offset) % k == 0:
                mb = self._run_count
                out = (self.node.fn(list(self._pending), mb)
                       if self.node.fn else list(self._pending))
                self._pending.clear()
                self._run_count += 1
                for d in self._credits:
                    self.send(d, DATA_IS_READY, out, scope_idx=mb)
                if not self.node.downstream:
                    self.carrier.collect(self.node.task_id, mb, out)
                if self._run_count >= self.node.max_run_times:
                    self.carrier.done(self.node.task_id)
        elif msg.type == DATA_IS_USELESS:
            self._credits[msg.src] += 1


# -- carrier / executor ------------------------------------------------------

class Carrier:
    """Ref ``carrier.cc``: owns this rank's interceptors and the bus."""

    INTERCEPTOR_TYPES = {"compute": ComputeInterceptor,
                         "amplifier": AmplifierInterceptor}

    def __init__(self, nodes: List[TaskNode]):
        self.bus = MessageBus()
        self.nodes = {n.task_id: n for n in nodes}
        # wire reverse edges
        for n in nodes:
            for d, buff in n.downstream.items():
                self.nodes[d].upstream.setdefault(n.task_id, buff)
        self.interceptors: Dict[int, Interceptor] = {}
        self.results: Dict[int, Dict[int, Any]] = {}
        self._done = threading.Event()
        self._finished: set = set()
        self._sinks = {n.task_id for n in nodes if not n.downstream}
        # make_lock: visible to the lock-order/race sanitizers (the
        # interceptor actor threads all report through this carrier)
        self._lock = make_lock("fleet.carrier")

    def collect(self, task_id: int, mb: int, value: Any) -> None:
        self.results.setdefault(task_id, {})[mb] = value

    def done(self, task_id: int) -> None:
        with self._lock:
            self._finished.add(task_id)
            if self._sinks <= self._finished:
                self._done.set()

    def start(self) -> None:
        for n in self.nodes.values():
            cls = self.INTERCEPTOR_TYPES.get(n.role, ComputeInterceptor)
            self.interceptors[n.task_id] = cls(n, self.bus, self)
        for i in self.interceptors.values():
            i.start()
        for i in self.interceptors.values():
            if isinstance(i, ComputeInterceptor):
                i.kickoff()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def stop(self) -> None:
        for i in self.interceptors.values():
            self.bus.send(InterceptorMessage(-1, i.node.task_id, STOP))
        for i in self.interceptors.values():
            i.join(timeout=1.0)


class FleetExecutor:
    """Ref ``fleet_executor.cc``: run a task graph for N microbatches."""

    def __init__(self, nodes: List[TaskNode]):
        self.nodes = nodes
        self.carrier: Optional[Carrier] = None

    def run(self, timeout: Optional[float] = 60.0) -> Dict[int, Dict[int, Any]]:
        self.carrier = Carrier(self.nodes)
        self.carrier.start()
        ok = self.carrier.wait(timeout)
        self.carrier.stop()
        if not ok:
            raise TimeoutError("fleet_executor: pipeline did not finish")
        return self.carrier.results
