"""Build/load + ctypes declarations for the native PS (``native/ps.cc``)."""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from pathlib import Path
from typing import Optional

from ...observability.sanitizers import make_lock

_SRC = Path(__file__).resolve().parent.parent.parent / "native" / "ps.cc"
_BUILD_DIR = _SRC.parent / "_build"

_lib = None
_lib_failed = False
_lock = make_lock("ps.native_build")


def _build() -> Path:
    src = _SRC.read_bytes()
    tag = hashlib.sha256(src).hexdigest()[:16]
    out = _BUILD_DIR / f"libphtps_{tag}.so"
    if out.exists():
        return out
    _BUILD_DIR.mkdir(exist_ok=True)
    tmp = out.with_suffix(".so.tmp%d" % os.getpid())
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           "-fvisibility=hidden", str(_SRC), "-o", str(tmp)]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, out)
    return out


def load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    if _lib is not None:
        return _lib
    if _lib_failed:
        return None
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            lib = ctypes.CDLL(str(_build()))
        except Exception:
            _lib_failed = True
            return None
        _declare(lib)
        _lib = lib
    return _lib


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    u64p = c.POINTER(c.c_uint64)
    f32p = c.POINTER(c.c_float)
    lib.pht_ps_server_start.argtypes = [c.c_int32]
    lib.pht_ps_server_start.restype = c.c_void_p
    lib.pht_ps_server_port.argtypes = [c.c_void_p]
    lib.pht_ps_server_port.restype = c.c_int32
    lib.pht_ps_server_stop.argtypes = [c.c_void_p]
    lib.pht_ps_connect.argtypes = [c.c_char_p, c.c_int32, c.c_int32]
    lib.pht_ps_connect.restype = c.c_void_p
    lib.pht_ps_disconnect.argtypes = [c.c_void_p]
    lib.pht_ps_create_table.argtypes = [c.c_void_p, c.c_uint32, c.c_uint32,
                                        c.c_uint8, c.c_uint8, c.c_float,
                                        c.c_float]
    lib.pht_ps_create_table.restype = c.c_int32
    lib.pht_ps_pull_sparse.argtypes = [c.c_void_p, c.c_uint32, u64p,
                                       c.c_uint32, f32p, c.c_uint32]
    lib.pht_ps_pull_sparse.restype = c.c_int32
    lib.pht_ps_push_sparse.argtypes = [c.c_void_p, c.c_uint32, u64p,
                                       c.c_uint32, f32p, c.c_uint32]
    lib.pht_ps_push_sparse.restype = c.c_int32
    lib.pht_ps_pull_dense.argtypes = [c.c_void_p, c.c_uint32, f32p,
                                      c.c_uint32]
    lib.pht_ps_pull_dense.restype = c.c_int32
    lib.pht_ps_push_dense.argtypes = [c.c_void_p, c.c_uint32, f32p,
                                      c.c_uint32]
    lib.pht_ps_push_dense.restype = c.c_int32
    lib.pht_ps_set_dense.argtypes = [c.c_void_p, c.c_uint32, f32p, c.c_uint32]
    lib.pht_ps_set_dense.restype = c.c_int32
    lib.pht_ps_push_show_click.argtypes = [c.c_void_p, c.c_uint32, u64p,
                                           c.c_uint32, f32p, f32p]
    lib.pht_ps_push_show_click.restype = c.c_int32
    lib.pht_ps_table_nkeys.argtypes = [c.c_void_p, c.c_uint32]
    lib.pht_ps_table_nkeys.restype = c.c_int64
    lib.pht_ps_shrink.argtypes = [c.c_void_p, c.c_uint32, c.c_uint32]
    lib.pht_ps_shrink.restype = c.c_int64
    lib.pht_ps_save.argtypes = [c.c_void_p, c.c_char_p]
    lib.pht_ps_save.restype = c.c_int32
    lib.pht_ps_load.argtypes = [c.c_void_p, c.c_char_p]
    lib.pht_ps_load.restype = c.c_int32
    lib.pht_ps_barrier.argtypes = [c.c_void_p, c.c_char_p, c.c_uint32,
                                   c.c_int32]
    lib.pht_ps_barrier.restype = c.c_int32
    lib.pht_ps_spill.argtypes = [c.c_void_p, c.c_uint32, c.c_uint32,
                                 c.c_char_p]
    lib.pht_ps_spill.restype = c.c_int64
    lib.pht_ps_geo_push.argtypes = [c.c_void_p, c.c_uint32, u64p,
                                    c.c_uint32, f32p, c.c_uint32]
    lib.pht_ps_geo_push.restype = c.c_int32
    lib.pht_ps_geo_pull_diff.argtypes = [c.c_void_p, c.c_uint32, c.c_uint32,
                                         u64p, f32p, c.c_uint32, c.c_uint32]
    lib.pht_ps_geo_pull_diff.restype = c.c_int64
    lib.pht_ps_geo_register.argtypes = [c.c_void_p, c.c_uint32, c.c_uint32]
    lib.pht_ps_geo_register.restype = c.c_int32
    u32p = c.POINTER(c.c_uint32)
    lib.pht_ps_graph_add_edges.argtypes = [c.c_void_p, c.c_uint32, u64p,
                                           u64p, c.c_uint32]
    lib.pht_ps_graph_add_edges.restype = c.c_int32
    lib.pht_ps_graph_sample_neighbors.argtypes = [
        c.c_void_p, c.c_uint32, u64p, c.c_uint32, c.c_uint32, c.c_uint64,
        u64p, u32p]
    lib.pht_ps_graph_sample_neighbors.restype = c.c_int64
    lib.pht_ps_graph_random_nodes.argtypes = [c.c_void_p, c.c_uint32,
                                              c.c_uint32, c.c_uint64, u64p]
    lib.pht_ps_graph_random_nodes.restype = c.c_int64
