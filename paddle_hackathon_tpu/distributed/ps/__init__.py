"""Parameter-server stack.

TPU-native counterpart of the reference's "the one PS"
(``paddle/fluid/distributed/ps``: brpc server/client, memory sparse/dense
tables, sparse SGD rules, CTR accessor; python runtime
``fleet/runtime/the_one_ps.py``; architecture ``ps/README.md``). The server
is native C++ (``native/ps.cc``), holding host-resident sparse embedding
state; the TPU keeps the dense compute. ``fleet.init_server/init_worker``
(ref ``fleet_base.py:625,669``) route here when the launcher sets
``PADDLE_ROLE``.
"""

from .api import (PsServerHandle, PsClient, AsyncCommunicator,  # noqa: F401
                  PsEmbeddingCache, SparseEmbedding, TableConfig,
                  cached_sparse_embedding_layer, init_server, init_worker,
                  ps_sparse_embedding, run_server, sparse_embedding_layer,
                  stop_server, get_client, shutdown)
