"""PS python surface: tables, sharded client, async communicator,
sparse-embedding layer, and the fleet server/worker lifecycle.

Ref mapping:
- ``TableConfig``            — the table section of ``the_one_ps.proto``
- ``PsClient``               — ``BrpcPsClient`` (client-side sharding of ids
                               across servers, ``brpc_ps_client.cc``)
- ``AsyncCommunicator``      — ``ps/service/communicator/`` (background
                               batched push)
- ``SparseEmbedding``        — the distributed lookup-table path
                               (``pscore`` send/recv ops + embedding layer)
- ``init_server/init_worker``— ``fleet_base.py:625,669`` / ``the_one_ps.py``
"""

from __future__ import annotations

import ctypes
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import _native
from ...observability.sanitizers import make_lock

RULES = {"sgd": 0, "adagrad": 1}


@dataclass
class TableConfig:
    table_id: int
    dim: int
    rule: str = "sgd"
    lr: float = 0.01
    init_range: float = 0.01
    dense: bool = False


class PsServerHandle:
    """A running in-process PS server (native thread pool owns the port)."""

    def __init__(self, port: int = 0):
        lib = _native.load()
        if lib is None:
            raise RuntimeError("native PS unavailable (g++ missing?)")
        self._lib = lib
        self._h = lib.pht_ps_server_start(port)
        if not self._h:
            raise RuntimeError(f"PS server failed to bind port {port}")
        self.port = lib.pht_ps_server_port(self._h)

    def stop(self):
        if self._h:
            self._lib.pht_ps_server_stop(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class _Conn:
    """One server connection (serialized; the wire protocol is not framed
    for interleaving — same rule as the TCPStore client)."""

    def __init__(self, host: str, port: int, timeout_ms: int = 30000):
        lib = _native.load()
        if lib is None:
            raise RuntimeError("native PS unavailable")
        self._lib = lib
        self._h = lib.pht_ps_connect(host.encode(), port, timeout_ms)
        if not self._h:
            raise TimeoutError(f"cannot reach PS server {host}:{port}")
        self._lock = make_lock("ps.client")

    def close(self):
        # under the client lock: close() racing an in-flight pull/push on
        # another thread (the AsyncCommunicator flush loop) would null
        # _h between that caller's check and its native call
        with self._lock:
            if self._h:
                self._lib.pht_ps_disconnect(self._h)
                self._h = None


def _f32p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _u64p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


class PsClient:
    """Sharded client over N servers: sparse ids route to server
    ``id % n_servers`` (the reference shards by id hash the same way);
    dense tables live on server 0."""

    def __init__(self, endpoints: Sequence[str], timeout: float = 30.0):
        self.endpoints = list(endpoints)
        self._conns = []
        for ep in self.endpoints:
            host, port = ep.rsplit(":", 1)
            self._conns.append(_Conn(host, int(port), int(timeout * 1000)))
        self._tables: Dict[int, TableConfig] = {}

    @property
    def n_servers(self) -> int:
        return len(self._conns)

    def close(self):
        for c in self._conns:
            c.close()

    # -- table lifecycle ----------------------------------------------------
    def create_table(self, cfg: TableConfig) -> None:
        for c in self._conns:
            with c._lock:
                rc = c._lib.pht_ps_create_table(
                    c._h, cfg.table_id, cfg.dim, RULES[cfg.rule],
                    1 if cfg.dense else 0, cfg.lr, cfg.init_range)
            if rc != 0:
                raise RuntimeError(
                    f"create_table({cfg.table_id}) rejected (spec conflict?)")
        self._tables[cfg.table_id] = cfg

    def _dim(self, table_id: int) -> int:
        return self._tables[table_id].dim

    # -- sparse -------------------------------------------------------------
    def _route(self, ids: np.ndarray):
        srv = (ids % np.uint64(self.n_servers)).astype(np.int64)
        return [np.nonzero(srv == s)[0] for s in range(self.n_servers)]

    def pull_sparse(self, table_id: int, ids) -> np.ndarray:
        ids = np.ascontiguousarray(np.asarray(ids, np.uint64).reshape(-1))
        dim = self._dim(table_id)
        out = np.empty((ids.size, dim), np.float32)
        for s, idx in enumerate(self._route(ids)):
            if idx.size == 0:
                continue
            sub = np.ascontiguousarray(ids[idx])
            buf = np.empty((idx.size, dim), np.float32)
            c = self._conns[s]
            with c._lock:
                rc = c._lib.pht_ps_pull_sparse(
                    c._h, table_id, _u64p(sub), idx.size, _f32p(buf), dim)
            if rc < 0:
                raise RuntimeError(f"pull_sparse failed on server {s}: {rc}")
            out[idx] = buf
        return out

    def push_sparse(self, table_id: int, ids, grads) -> None:
        ids = np.ascontiguousarray(np.asarray(ids, np.uint64).reshape(-1))
        dim = self._dim(table_id)
        grads = np.ascontiguousarray(
            np.asarray(grads, np.float32).reshape(ids.size, dim))
        # aggregate duplicate ids client-side so server-side optimizer rules
        # (adagrad) see one update per key per push
        uniq, inv = np.unique(ids, return_inverse=True)
        agg = np.zeros((uniq.size, dim), np.float32)
        np.add.at(agg, inv, grads)
        for s, idx in enumerate(self._route(uniq)):
            if idx.size == 0:
                continue
            sub = np.ascontiguousarray(uniq[idx])
            g = np.ascontiguousarray(agg[idx])
            c = self._conns[s]
            with c._lock:
                rc = c._lib.pht_ps_push_sparse(
                    c._h, table_id, _u64p(sub), idx.size, _f32p(g), dim)
            if rc != 0:
                raise RuntimeError(f"push_sparse failed on server {s}: {rc}")

    def push_show_click(self, table_id: int, ids, shows, clicks) -> None:
        ids = np.ascontiguousarray(np.asarray(ids, np.uint64).reshape(-1))
        shows = np.ascontiguousarray(np.asarray(shows, np.float32).reshape(-1))
        clicks = np.ascontiguousarray(
            np.asarray(clicks, np.float32).reshape(-1))
        for s, idx in enumerate(self._route(ids)):
            if idx.size == 0:
                continue
            c = self._conns[s]
            sub, sh, cl = (np.ascontiguousarray(a[idx])
                           for a in (ids, shows, clicks))
            with c._lock:
                rc = c._lib.pht_ps_push_show_click(
                    c._h, table_id, _u64p(sub), idx.size, _f32p(sh),
                    _f32p(cl))
            if rc != 0:
                raise RuntimeError(f"push_show_click failed: {rc}")

    # -- dense --------------------------------------------------------------
    def pull_dense(self, table_id: int) -> np.ndarray:
        dim = self._dim(table_id)
        out = np.empty((dim,), np.float32)
        c = self._conns[0]
        with c._lock:
            rc = c._lib.pht_ps_pull_dense(c._h, table_id, _f32p(out), dim)
        if rc < 0:
            raise RuntimeError(f"pull_dense failed: {rc}")
        return out

    def push_dense(self, table_id: int, grads) -> None:
        g = np.ascontiguousarray(np.asarray(grads, np.float32).reshape(-1))
        c = self._conns[0]
        with c._lock:
            rc = c._lib.pht_ps_push_dense(c._h, table_id, _f32p(g), g.size)
        if rc != 0:
            raise RuntimeError(f"push_dense failed: {rc}")

    def set_dense(self, table_id: int, values) -> None:
        v = np.ascontiguousarray(np.asarray(values, np.float32).reshape(-1))
        c = self._conns[0]
        with c._lock:
            rc = c._lib.pht_ps_set_dense(c._h, table_id, _f32p(v), v.size)
        if rc != 0:
            raise RuntimeError(f"set_dense failed: {rc}")

    # -- maintenance --------------------------------------------------------
    def table_nkeys(self, table_id: int) -> int:
        total = 0
        for c in self._conns:
            with c._lock:
                n = c._lib.pht_ps_table_nkeys(c._h, table_id)
            if n < 0:
                raise RuntimeError("stats failed")
            total += n
        return total

    def shrink(self, table_id: int, max_unseen: int = 1) -> int:
        dropped = 0
        for c in self._conns:
            with c._lock:
                d = c._lib.pht_ps_shrink(c._h, table_id, max_unseen)
            if d < 0:
                raise RuntimeError("shrink failed")
            dropped += d
        return dropped

    def spill(self, table_id: int, max_unseen: int, path: str) -> int:
        """Evict rows unseen for more than ``max_unseen`` pull rounds to a
        per-server spill file (the SSD tier; ref ``ssd_sparse_table.cc``
        rocksdb cold storage).  Spilled rows leave server RAM; a later pull
        restores them transparently.  Returns total rows spilled."""
        total = 0
        for s, c in enumerate(self._conns):
            with c._lock:
                rc = c._lib.pht_ps_spill(c._h, table_id, max_unseen,
                                         f"{path}.srv{s}".encode())
            if rc < 0:
                raise RuntimeError(
                    f"spill failed on server {s}: rc={rc} (I/O error — "
                    "unspilled rows stay in RAM, nothing was lost)")
            total += int(rc)
        return total

    def geo_push(self, table_id: int, ids, deltas) -> None:
        """Geo-async push: merge raw weight deltas (the trainer ran its
        optimizer locally; ref ``memory_sparse_geo_table.cc``)."""
        ids = np.ascontiguousarray(np.asarray(ids, np.uint64).reshape(-1))
        dim = self._dim(table_id)
        deltas = np.ascontiguousarray(
            np.asarray(deltas, np.float32).reshape(ids.size, dim))
        for s, idx in enumerate(self._route(ids)):
            if idx.size == 0:
                continue
            sub = np.ascontiguousarray(ids[idx])
            d = np.ascontiguousarray(deltas[idx])
            c = self._conns[s]
            with c._lock:
                rc = c._lib.pht_ps_geo_push(
                    c._h, table_id, _u64p(sub), idx.size, _f32p(d), dim)
            if rc != 0:
                raise RuntimeError(f"geo_push failed on server {s}: {rc}")

    def geo_register(self, table_id: int, trainer_id: int) -> None:
        """Register a geo trainer's watermark up front (ADVICE r2: an
        unregistered trainer is invisible to the spill/shrink pending-
        delivery guard until its first ``geo_pull_diff``, so an early
        spill could permanently drop updates it never received).  Call
        once per expected trainer right after table creation; never
        rewinds an existing watermark."""
        for s, c in enumerate(self._conns):
            with c._lock:
                rc = c._lib.pht_ps_geo_register(c._h, table_id, trainer_id)
            if rc != 0:
                raise RuntimeError(f"geo_register failed on server {s}: {rc}")

    def geo_pull_diff(self, table_id: int, trainer_id: int,
                      cap_rows: int = 1 << 16):
        """Rows changed since this trainer's previous ``geo_pull_diff``
        (bounded staleness: each call delivers up to ``cap_rows`` oldest
        pending updates per server and advances the watermark only over
        what was delivered, so a burst larger than the buffer arrives over
        the following rounds instead of being lost).  Returns (ids, rows).
        """
        dim = self._dim(table_id)
        ids = np.empty(cap_rows, np.uint64)          # reused per server
        rows = np.empty((cap_rows, dim), np.float32)
        all_ids, all_rows = [], []
        for s, c in enumerate(self._conns):
            with c._lock:
                rc = c._lib.pht_ps_geo_pull_diff(
                    c._h, table_id, trainer_id, _u64p(ids), _f32p(rows),
                    cap_rows, dim)
            if rc < 0:
                raise RuntimeError(f"geo_pull_diff failed on server {s}: "
                                   f"{rc}")
            n = int(rc)
            if n:
                all_ids.append(ids[:n].copy())
                all_rows.append(rows[:n].copy())
        if not all_ids:
            return (np.empty(0, np.uint64), np.empty((0, dim), np.float32))
        return np.concatenate(all_ids), np.concatenate(all_rows)

    # -- graph table (ref common_graph_table.cc: node/edge storage +
    #    neighbor-sampling RPCs for graph learning) -----------------------

    def graph_add_edges(self, table_id: int, src, dst) -> None:
        """Add directed edges src[i] -> dst[i]; edges shard by SOURCE id
        (the same hash routing as sparse rows).  Add the reverse edge
        yourself for undirected graphs.  Node features live in the same
        table's sparse rows (pull/push_sparse on node ids)."""
        src = np.ascontiguousarray(np.asarray(src, np.uint64).reshape(-1))
        dst = np.ascontiguousarray(np.asarray(dst, np.uint64).reshape(-1))
        if src.size != dst.size:
            raise ValueError("src and dst must have equal length")
        for s, idx in enumerate(self._route(src)):
            if idx.size == 0:
                continue
            a = np.ascontiguousarray(src[idx])
            b = np.ascontiguousarray(dst[idx])
            c = self._conns[s]
            with c._lock:
                rc = c._lib.pht_ps_graph_add_edges(
                    c._h, table_id, _u64p(a), _u64p(b), idx.size)
            if rc != 0:
                raise RuntimeError(f"graph_add_edges failed on server {s}")

    def graph_sample_neighbors(self, table_id: int, ids, k: int,
                               seed: int = 0):
        """Sample up to ``k`` neighbors per node WITHOUT replacement,
        deterministic under (seed, node id) regardless of which client
        asks.  Returns (neighbors [n, k] uint64, counts [n] int32); rows
        are valid up to their count."""
        ids = np.ascontiguousarray(np.asarray(ids, np.uint64).reshape(-1))
        n = ids.size
        neighbors = np.zeros((n, k), np.uint64)
        counts = np.zeros(n, np.int32)
        import ctypes as ct
        for s, idx in enumerate(self._route(ids)):
            if idx.size == 0:
                continue
            sub = np.ascontiguousarray(ids[idx])
            nb = np.zeros((idx.size, k), np.uint64)  # tail beyond count = 0
            cn = np.empty(idx.size, np.uint32)
            c = self._conns[s]
            with c._lock:
                rc = c._lib.pht_ps_graph_sample_neighbors(
                    c._h, table_id, _u64p(sub), idx.size, k, seed,
                    _u64p(nb), cn.ctypes.data_as(ct.POINTER(ct.c_uint32)))
            if rc == -3:
                raise KeyError(f"graph table {table_id} does not exist on "
                               f"server {s} (create_table first)")
            if rc < 0:
                raise RuntimeError(
                    f"graph_sample_neighbors failed on server {s}")
            neighbors[idx] = nb
            counts[idx] = cn.astype(np.int32)
        return neighbors, counts

    def graph_random_nodes(self, table_id: int, k: int, seed: int = 0):
        """Up to ``k`` distinct node ids sampled across all servers,
        deterministic under seed."""
        per = []
        for s, c in enumerate(self._conns):
            out = np.empty(k, np.uint64)
            with c._lock:
                rc = c._lib.pht_ps_graph_random_nodes(c._h, table_id, k,
                                                      seed, _u64p(out))
            if rc == -3:
                raise KeyError(f"graph table {table_id} does not exist on "
                               f"server {s} (create_table first)")
            if rc < 0:
                raise RuntimeError(f"graph_random_nodes failed on {s}")
            per.append(out[:int(rc)])
        allnodes = np.sort(np.concatenate(per)) if per else \
            np.empty(0, np.uint64)
        if allnodes.size <= k:
            return allnodes
        # deterministic client-side subsample of the per-server samples
        r = np.random.RandomState(seed & 0x7FFFFFFF)
        pick = r.choice(allnodes.size, size=k, replace=False)
        return allnodes[np.sort(pick)]

    def save(self, dirname: str) -> None:
        os.makedirs(dirname, exist_ok=True)
        for s, c in enumerate(self._conns):
            with c._lock:
                rc = c._lib.pht_ps_save(
                    c._h, os.path.join(dirname, f"shard{s}.bin").encode())
            if rc != 0:
                raise RuntimeError(f"save failed on server {s}")

    def load(self, dirname: str) -> None:
        for s, c in enumerate(self._conns):
            with c._lock:
                rc = c._lib.pht_ps_load(
                    c._h, os.path.join(dirname, f"shard{s}.bin").encode())
            if rc != 0:
                raise RuntimeError(f"load failed on server {s}")

    def barrier(self, name: str, world: int, timeout: float = 600.0) -> None:
        # Dedicated connection: a barrier blocks server-side until all
        # participants arrive, so it must not hold the shared connection's
        # lock (concurrent participants would deadlock behind it).
        host, port = self.endpoints[0].rsplit(":", 1)
        c = _Conn(host, int(port), int(timeout * 1000))
        try:
            rc = c._lib.pht_ps_barrier(c._h, name.encode(), world,
                                       int(timeout * 1000))
            if rc != 0:
                raise TimeoutError(f"ps barrier {name!r} failed")
        finally:
            c.close()


class AsyncCommunicator:
    """Background batched push (ref ``ps/service/communicator/``:
    trainers enqueue grads; a send thread merges and flushes)."""

    def __init__(self, client: PsClient, flush_interval: float = 0.05,
                 max_pending: int = 64):
        self.client = client
        self.interval = flush_interval
        self._pending: List[tuple] = []
        # Condition over a make_lock: the send thread's lock shows up in
        # the sanitizers' graph like every other lock in the process
        self._cv = threading.Condition(make_lock("ps.communicator"))
        self._stop = False
        self._max = max_pending
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def push_sparse_async(self, table_id: int, ids, grads) -> None:
        with self._cv:
            self._pending.append((table_id, np.asarray(ids, np.uint64),
                                  np.asarray(grads, np.float32)))
            if len(self._pending) >= self._max:
                self._cv.notify()

    def _loop(self):
        while True:
            with self._cv:
                self._cv.wait(timeout=self.interval)
                batch, self._pending = self._pending, []
                stop = self._stop
            self._flush(batch)
            if stop:
                return

    def _flush(self, batch):
        by_table: Dict[int, list] = {}
        for tid, ids, grads in batch:
            by_table.setdefault(tid, []).append((ids, grads))
        for tid, items in by_table.items():
            ids = np.concatenate([i.reshape(-1) for i, _ in items])
            dim = self.client._dim(tid)
            grads = np.concatenate([g.reshape(-1, dim) for _, g in items])
            self.client.push_sparse(tid, ids, grads)

    def flush(self) -> None:
        with self._cv:
            batch, self._pending = self._pending, []
        self._flush(batch)

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._thread.join(timeout=5.0)
        self.flush()


class SparseEmbedding:
    """Distributed embedding lookup backed by the PS.

    Forward pulls rows for the batch's ids; backward pushes the row grads
    (optimizer rule applies server-side) — the reference's distributed
    lookup-table path (``pscore`` ops + ``communicator``). Use inside eager
    training; the dense model below it trains with a normal optimizer.
    """

    def __init__(self, client: PsClient, table_id: int, dim: int,
                 communicator: Optional[AsyncCommunicator] = None,
                 rule: str = "adagrad", lr: float = 0.05,
                 init_range: float = 0.05):
        self.client = client
        self.table_id = table_id
        self.dim = dim
        self.comm = communicator
        if table_id not in client._tables:
            client.create_table(TableConfig(table_id, dim, rule=rule, lr=lr,
                                            init_range=init_range))

    def __call__(self, ids):
        from ...core.autograd import GradNode, is_grad_enabled
        from ...core.tensor import Tensor
        import jax.numpy as jnp

        ids_np = np.asarray(
            ids.numpy() if isinstance(ids, Tensor) else ids)
        flat = ids_np.reshape(-1)
        rows = self.client.pull_sparse(self.table_id, flat)
        out_np = rows.reshape(ids_np.shape + (self.dim,))
        val = jnp.asarray(out_np)
        if not is_grad_enabled():
            return Tensor(val)

        client, comm, tid = self.client, self.comm, self.table_id

        def vjp_fn(cotangents):
            g = np.asarray(cotangents[0]).reshape(flat.size, self.dim)
            if comm is not None:
                comm.push_sparse_async(tid, flat, g)
            else:
                client.push_sparse(tid, flat, g)
            return ()

        node = GradNode("ps_embedding", vjp_fn, [], 1,
                        [(val.shape, val.dtype)])
        return Tensor(val, stop_gradient=False, _grad_node=node, _out_idx=0)


class PsEmbeddingCache:
    """Device-resident hot-row cache for a PS sparse table — the HeterPS
    role (ref ``framework/fleet/ps_gpu_wrapper.cc``: hot sparse-table rows
    cached in accelerator HBM so the training pass never leaves the
    device for them), TPU-native mechanism:

    - the cache is a ``(rows+1, dim)`` DEVICE array threaded through the
      jitted step as program state (``Program.add_state``) or held by the
      object in eager mode; row ``rows`` is scratch for padding;
    - the in-step op gathers/scatters by SLOT; only the host<->device
      traffic for MISSES (pull) and EVICTIONS (write-back) crosses the
      boundary — hits are pure device gathers;
    - LRU lives on the host; per-batch slot assignment is one tiny
      host callback (ids -> slots), not a row transfer;
    - write-back parity: the table rule must be plain ``sgd`` — local
      row updates then COMMUTE with the server's rule, so pushing the
      accumulated gradient ``(pulled - current)/lr`` at eviction leaves
      the server exactly where uncached training would
      (``tests/test_ps_cache.py`` pins parity).

    ``stats``: hits / misses / evictions / writebacks counters.
    """

    def __init__(self, client: PsClient, table_id: int, dim: int,
                 rows: int = 4096, lr: float = 0.05,
                 init_range: float = 0.05):
        import collections
        self.client = client
        self.table_id = table_id
        self.dim = int(dim)
        self.rows = int(rows)
        self.lr = float(lr)
        if table_id in client._tables:
            cfg = client._tables[table_id]
            if cfg.rule != "sgd":
                raise ValueError(
                    f"PsEmbeddingCache needs table rule 'sgd' (got "
                    f"{cfg.rule!r}): only linear updates commute with the "
                    "deferred write-back")
            if abs(cfg.lr - self.lr) > 1e-12 or cfg.dim != self.dim:
                raise ValueError(
                    f"PsEmbeddingCache(lr={self.lr}, dim={dim}) does not "
                    f"match table {table_id}'s (lr={cfg.lr}, "
                    f"dim={cfg.dim}): the write-back pushes "
                    "(pulled-current)/lr, so a mismatched lr silently "
                    "breaks parity")
        else:
            client.create_table(TableConfig(table_id, dim, rule="sgd",
                                            lr=lr, init_range=init_range))
        self.value = jnp.zeros((self.rows + 1, self.dim), jnp.float32)
        self._slot_of = collections.OrderedDict()  # id -> slot (LRU order)
        self._free = list(range(self.rows))
        self._pulled = np.zeros((self.rows, self.dim), np.float32)
        self._wb_queue = collections.deque()  # (ids, pulled_rows) pending
        # id(program) -> {"in": state input Variable, "cur": the latest
        # op's state output (chained lookups thread through it)}
        self._state_vars = {}
        self.stats = {"hits": 0, "misses": 0, "evictions": 0,
                      "writebacks": 0}

    # -- program-state protocol (static/program.py add_state) -----------
    def get(self):
        return self.value

    def set(self, arr):
        self.value = arr

    def updater(self, fwd_out, grad):
        """Pure (traced into the step): forward-updated cache (fills
        applied) minus the local sgd step on the batch's row gradients."""
        return fwd_out - self.lr * grad

    # -- host scheduling ------------------------------------------------
    def _assign(self, ids_np):
        """Map a batch of ids to slots; schedule fills (misses, pulled
        from the PS) and write-backs (LRU evictions). Returns
        (slots, fill_slots, fill_rows, wb_slots) with fixed width
        K = ids.size (padded with the scratch row)."""
        flat = np.asarray(ids_np).reshape(-1).astype(np.int64)
        K = flat.size
        uniq = list(dict.fromkeys(flat.tolist()))
        needed = set(uniq)
        miss_ids, fill_slot_list = [], []
        wb_ids, wb_pulled, wb_slot_list = [], [], []
        for uid in uniq:
            if uid in self._slot_of:
                self._slot_of.move_to_end(uid)
                self.stats["hits"] += 1
                continue
            self.stats["misses"] += 1
            if self._free:
                s = self._free.pop()
            else:
                victim = next((i for i in self._slot_of
                               if i not in needed), None)
                if victim is None:
                    raise RuntimeError(
                        f"PsEmbeddingCache rows={self.rows} is smaller "
                        f"than one batch's unique ids ({len(uniq)})")
                s = self._slot_of.pop(victim)
                self.stats["evictions"] += 1
                wb_ids.append(victim)
                wb_pulled.append(self._pulled[s].copy())
                wb_slot_list.append(s)
            self._slot_of[uid] = s
            miss_ids.append(uid)
            fill_slot_list.append(s)
        fill_rows = np.zeros((K, self.dim), np.float32)
        fill_slots = np.full(K, self.rows, np.int32)
        if miss_ids:
            rows = self.client.pull_sparse(
                self.table_id, np.asarray(miss_ids, np.uint64))
            fill_rows[:len(miss_ids)] = rows
            fill_slots[:len(miss_ids)] = fill_slot_list
            for s, r in zip(fill_slot_list, rows):
                self._pulled[s] = r
        wb_slots = np.full(K, self.rows, np.int32)
        wb_slots[:len(wb_slot_list)] = wb_slot_list
        self._wb_queue.append((np.asarray(wb_ids, np.uint64),
                               np.asarray(wb_pulled, np.float32)
                               if wb_ids else
                               np.zeros((0, self.dim), np.float32)))
        slots = np.asarray([self._slot_of[i] for i in flat.tolist()],
                           np.int32)
        return slots, fill_slots, fill_rows, wb_slots

    def _push_wb(self, wb_rows):
        """Write back the rows that left the cache: the server applies
        -lr * grad, so grad = (pulled - current)/lr lands it exactly on
        the locally-updated value."""
        ids, pulled = self._wb_queue.popleft()
        n = len(ids)
        if n:
            current = np.asarray(wb_rows[:n], np.float32)
            grads = (pulled - current) / self.lr
            self.client.push_sparse(self.table_id, ids, grads)
            self.stats["writebacks"] += n
        return np.zeros((), np.float32)

    def flush(self):
        """Write back every dirty cached row (end of training / before
        saving the table). The cache stays populated."""
        if not self._slot_of:
            return
        current = np.asarray(self.value)
        ids = np.asarray(list(self._slot_of.keys()), np.uint64)
        slots = np.asarray([self._slot_of[int(i)] for i in ids], np.int64)
        grads = (self._pulled[slots] - current[slots]) / self.lr
        self.client.push_sparse(self.table_id, ids, grads)
        self.stats["writebacks"] += len(ids)
        # rows are now in sync server-side: re-base the pull snapshot
        for s in slots:
            self._pulled[s] = current[s]

    # -- the op ----------------------------------------------------------
    def _fn(self, ids_arr, cache_arr):
        """Traceable op body shared by static recording: one host
        callback assigns slots (and pulls misses), the write-back rows
        leave through a second ordered callback, fills apply with a
        stop-gradient delta so dL/d(cache input) is the full scatter of
        the embedding gradient (including freshly filled rows)."""
        from jax.experimental import io_callback
        K = int(np.prod(ids_arr.shape))
        avals = (jax.ShapeDtypeStruct((K,), jnp.int32),
                 jax.ShapeDtypeStruct((K,), jnp.int32),
                 jax.ShapeDtypeStruct((K, self.dim), jnp.float32),
                 jax.ShapeDtypeStruct((K,), jnp.int32))
        slots, fill_slots, fill_rows, wb_slots = io_callback(
            self._assign, avals, ids_arr, ordered=True)
        wb_rows = jax.lax.stop_gradient(cache_arr)[wb_slots]
        io_callback(self._push_wb, jax.ShapeDtypeStruct((), jnp.float32),
                    wb_rows, ordered=True)
        base = jax.lax.stop_gradient(cache_arr)
        delta = jnp.zeros_like(base).at[fill_slots].set(
            fill_rows - base[fill_slots])
        cache_f = cache_arr + delta  # d cache_f / d cache_arr = identity
        emb = cache_f[slots].reshape(tuple(ids_arr.shape) + (self.dim,))
        return emb, cache_f


def cached_sparse_embedding_layer(ids, cache: PsEmbeddingCache):
    """Sparse-table lookup through a device-resident hot-row cache (the
    ``sparse_embedding_layer`` fast tier — see :class:`PsEmbeddingCache`).
    Works in static programs (the cache threads through the step as
    program state) and eager mode."""
    from ...core import autograd as _ag
    from ...core.tensor import Tensor

    sm = _ag._static_module
    if (sm is not None and sm.in_static_mode()
            and isinstance(ids, sm.Variable)):
        prog = sm.default_main_program()
        ent = cache._state_vars.get(id(prog))
        if ent is None:
            in_var = prog.add_state(
                cache, name=f"ps_cache_{cache.table_id}")
            ent = cache._state_vars[id(prog)] = {"in": in_var,
                                                 "cur": in_var}
        # a SECOND lookup through the same cache chains off the previous
        # op's output (its fills), not the original state input — the
        # state binding always points at the LAST op's output so every
        # fill persists; gradients flow through the chain's identity
        # Jacobian and sum across lookups
        emb_var, out_var = prog.record_op(
            "ps_cached_embedding", cache._fn, [ids, ent["cur"]],
            n_outputs=2)
        ent["cur"] = out_var
        prog.bind_state_out(ent["in"], out_var)
        return emb_var

    # eager: host scheduling directly, device gather/scatter, taped vjp
    ids_np = np.asarray(ids.numpy() if hasattr(ids, "numpy") else ids)
    slots, fill_slots, fill_rows, wb_slots = cache._assign(ids_np)
    cache._push_wb(np.asarray(cache.value[wb_slots]))
    cache.value = cache.value.at[fill_slots].set(jnp.asarray(fill_rows))
    val = cache.value[slots].reshape(ids_np.shape + (cache.dim,))
    from ...core.autograd import GradNode, is_grad_enabled
    if not is_grad_enabled():
        return Tensor(val)

    flat_ids = ids_np.reshape(-1).astype(np.int64)

    def vjp_fn(cotangents):
        # resolve slots at BACKWARD time, keyed by id: a later forward
        # on the same cache may have evicted/remapped slots since this
        # forward, and a stale slot index would land the gradient on
        # another id's row. Ids no longer cached push their gradient
        # straight to the PS (by id — the always-safe route).
        g = np.asarray(cotangents[0]).reshape(-1, cache.dim)
        cur_slots = np.asarray(
            [cache._slot_of.get(int(i), -1) for i in flat_ids], np.int64)
        here = cur_slots >= 0
        if here.any():
            scat = jnp.zeros_like(cache.value).at[
                jnp.asarray(cur_slots[here])].add(jnp.asarray(g[here]))
            cache.value = cache.value - cache.lr * scat
        if (~here).any():
            cache.client.push_sparse(
                cache.table_id, flat_ids[~here].astype(np.uint64),
                g[~here])
        return ()

    node = GradNode("ps_cached_embedding", vjp_fn, [], 1,
                    [(val.shape, val.dtype)])
    return Tensor(val, stop_gradient=False, _grad_node=node, _out_idx=0)


# ---------------------------------------------------------------------------
# fleet-style lifecycle driven by the launcher's env protocol
# ---------------------------------------------------------------------------

_server: Optional[PsServerHandle] = None
_client: Optional[PsClient] = None


def ps_sparse_embedding(ids, table_token, table_id: int, dim: int,
                        client: Optional[PsClient] = None,
                        communicator: Optional[AsyncCommunicator] = None):
    """Jit-compatible distributed embedding lookup (the reference's
    ``fluid.layers.embedding(is_sparse=True, is_distributed=True)`` /
    pscore ``distributed_lookup_table`` op).

    Runs *inside* a compiled program: the pull/push cross the host boundary
    as ordered ``io_callback``s around the jitted step (the dense compute
    stays on the TPU), and the backward pushes row gradients to the PS,
    where the server-side rule (sgd/adagrad) applies them.  This is what
    lets ``Executor.train_from_dataset`` drive a CTR program whose sparse
    tables live on the native PS.

    ``ids``: int array (any shape); ``table_token``: differentiable f32
    scalar standing in for the remote table (see ``lookup``'s docstring);
    returns float32 of shape ids.shape+(dim,).
    """
    from jax.experimental import io_callback

    def _client():
        c = client if client is not None else _client_global()
        if c is None:
            raise RuntimeError("ps_sparse_embedding: no PS client; call "
                               "init_worker() first")
        return c

    def _pull_host(ids_np):
        flat = np.asarray(ids_np).astype(np.uint64).reshape(-1)
        rows = _client().pull_sparse(table_id, flat)
        return rows.reshape(np.asarray(ids_np).shape + (dim,)).astype(
            np.float32)

    def _push_host(ids_np, grads_np):
        flat = np.asarray(ids_np).astype(np.uint64).reshape(-1)
        g = np.asarray(grads_np, np.float32).reshape(flat.size, dim)
        if communicator is not None:
            communicator.push_sparse_async(table_id, flat, g)
        else:
            _client().push_sparse(table_id, flat, g)
        return np.zeros((), np.float32)

    @jax.custom_vjp
    def lookup(ids_arr, table_token):
        # table_token is a trainable scalar standing in for the remote
        # table: reverse-mode only transposes ops on a path to a
        # differentiable input, and the real table lives host-side — the
        # token puts this op on the gradient path so the backward (the
        # grad *push*) actually runs, like the reference's lookup-table
        # var being a parameter of the block.
        out_aval = jax.ShapeDtypeStruct(ids_arr.shape + (dim,), jnp.float32)
        return io_callback(_pull_host, out_aval, ids_arr, ordered=True)

    def lookup_fwd(ids_arr, table_token):
        return lookup(ids_arr, table_token), ids_arr

    def lookup_bwd(ids_arr, g):
        # ordered io_callback is effectful — never dead-code-eliminated
        io_callback(_push_host, jax.ShapeDtypeStruct((), jnp.float32),
                    ids_arr, g, ordered=True)
        # integer primal -> float0 cotangent; the token's grad is zero
        return (np.zeros(ids_arr.shape, jax.dtypes.float0),
                jnp.zeros((), jnp.float32))

    lookup.defvjp(lookup_fwd, lookup_bwd)
    return lookup(ids, table_token)


def _client_global():
    return _client


def sparse_embedding_layer(ids, table_id: int, dim: int,
                           client: Optional[PsClient] = None,
                           communicator: Optional[AsyncCommunicator] = None,
                           rule: str = "adagrad", lr: float = 0.05,
                           init_range: float = 0.05):
    """Framework-op wrapper over :func:`ps_sparse_embedding`: works in
    eager mode (taped) AND inside static programs (recorded, then executed
    under the compiled step with host-callback pull/push) — the analog of
    ``fluid.layers.embedding(is_sparse=True, is_distributed=True)``.

    Creates the table on first use when a client is reachable.  A
    trainable zero scalar ("table token") joins the op's inputs so the
    backward — the gradient push — is on the autodiff path (the
    reference's lookup-table var is a block parameter for the same
    reason); its own gradient is zero, so optimizers never move it."""
    from ...core.autograd import apply_op

    c = client if client is not None else _client_global()
    if c is not None and table_id not in c._tables:
        c.create_table(TableConfig(table_id, dim, rule=rule, lr=lr,
                                   init_range=init_range))

    token = _table_tokens.get(table_id)
    if token is None:
        from ...nn.parameter import Parameter
        token = Parameter(jnp.zeros((), jnp.float32),
                          name=f"ps_table_token_{table_id}")
        _table_tokens[table_id] = token

    def fn(ids_arr, token_arr):
        return ps_sparse_embedding(ids_arr, token_arr, table_id, dim,
                                   client=c, communicator=communicator)

    return apply_op("ps_sparse_embedding", fn, [ids, token])


_table_tokens: Dict[int, object] = {}


def init_server(port: Optional[int] = None) -> PsServerHandle:
    """Start this process's PS shard (ref ``fleet.init_server``)."""
    global _server
    if _server is None:
        p = port if port is not None else int(os.environ.get("PADDLE_PORT", 0))
        _server = PsServerHandle(p)
    return _server


def run_server() -> None:
    """Serve until terminated (ref ``fleet.run_server`` blocking loop)."""
    srv = init_server()
    try:
        while srv._h:
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass


def stop_server() -> None:
    global _server
    if _server is not None:
        _server.stop()
        _server = None


def init_worker(endpoints: Optional[Sequence[str]] = None) -> PsClient:
    """Connect this trainer to all PS shards (ref ``fleet.init_worker``)."""
    global _client
    if _client is None:
        eps = (list(endpoints) if endpoints is not None else
               os.environ.get("PADDLE_PSERVER_ENDPOINTS", "").split(","))
        eps = [e for e in eps if e]
        if not eps:
            raise RuntimeError("no PS endpoints: set PADDLE_PSERVER_ENDPOINTS "
                               "or pass endpoints=")
        _client = PsClient(eps)
    return _client


def get_client() -> Optional[PsClient]:
    return _client


def shutdown() -> None:
    global _client
    if _client is not None:
        _client.close()
        _client = None
    stop_server()
