"""paddle.distributed — public distributed API namespace.

The implementation lives in ``paddle_hackathon_tpu.parallel`` (mesh/pjit
collectives, fleet, hybrid topology — SURVEY §2.4); this package gives it
the reference's import surface (``python/paddle/distributed/__init__.py``)
and hosts the process-level subsystems: ``launch`` (the
``python -m paddle.distributed.launch`` equivalent, ref ``launch/main.py:18``),
``elastic`` (ref ``fleet/elastic/manager.py:131``), ``ps`` (parameter
server, ref ``paddle/fluid/distributed/ps``) and ``fleet_executor``-style
pipeline orchestration.
"""

from ..parallel import *  # noqa: F401,F403
from ..parallel import (collective, auto_parallel, fleet,  # noqa: F401
                        get_rank, get_world_size, init_parallel_env)
from ..parallel.collective import (all_gather, all_reduce, alltoall,  # noqa: F401
                                   barrier, broadcast, new_group, reduce,
                                   reduce_scatter, scatter)

from . import launch  # noqa: F401,E402  (python -m ...distributed.launch)
from .compat import (CountFilterEntry, InMemoryDataset,  # noqa: F401,E402
                     ParallelEnv, ProbabilityEntry, QueueDataset,
                     ShowClickEntry, get_group, gloo_barrier,
                     gloo_init_parallel_env, gloo_release, irecv, isend,
                     recv, send, spawn, split, wait)
