"""Elastic training manager: node registry, heartbeats, scale events.

Ref ``fleet/elastic/manager.py:131`` (``ElasticManager``): the reference
keeps per-node keys under an etcd job prefix with TTL leases + a heartbeat
thread, watches for membership changes, and relaunches the local trainer
with a rewritten rank map. Here the registry is an abstract ``LeaseStore``
(TTL-lease KV): the default backing is the framework's native TCPStore on
the master node; tests use the in-memory ``MemLeaseStore`` the way the
reference's elastic tests mock etcd (``test_fleet_elastic_manager.py``).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, List, Optional

from ..observability.sanitizers import make_lock

__all__ = ["ElasticStatus", "LeaseLostError", "LeaseStore", "MemLeaseStore",
           "TCPLeaseStore", "ElasticManager"]


class LeaseLostError(RuntimeError):
    """A lease refresh could not reach the store after bounded retries —
    the node must assume its membership lapsed (peers see its TTL
    expire) and re-register / re-rendezvous rather than train on as if
    still a member."""


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"       # membership below np range: wait
    RESTART = "restart"  # membership changed: relaunch with new ranks
    EXIT = "exit"


class LeaseStore:
    """TTL-lease KV interface (the slice of etcd the manager needs)."""

    def put_with_lease(self, key: str, value: str, ttl: float) -> None:
        raise NotImplementedError

    def refresh(self, key: str, ttl: float) -> bool:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def list_prefix(self, prefix: str) -> Dict[str, str]:
        raise NotImplementedError


class MemLeaseStore(LeaseStore):
    """In-memory lease store (test double; ref mocked-etcd elastic tests)."""

    def __init__(self):
        self._data: Dict[str, tuple] = {}  # key -> (value, expiry)
        # make_lock: the heartbeat thread and watchers share this store
        self._lock = make_lock("elastic.lease")

    def put_with_lease(self, key, value, ttl):
        with self._lock:
            self._data[key] = (value, time.monotonic() + ttl)

    def refresh(self, key, ttl):
        with self._lock:
            if key not in self._data:
                return False
            v, _ = self._data[key]
            self._data[key] = (v, time.monotonic() + ttl)
            return True

    def delete(self, key):
        with self._lock:
            self._data.pop(key, None)

    def list_prefix(self, prefix):
        now = time.monotonic()
        with self._lock:
            self._data = {k: ve for k, ve in self._data.items()
                          if ve[1] > now}
            return {k: v for k, (v, e) in self._data.items()
                    if k.startswith(prefix)}


class TCPLeaseStore(LeaseStore):
    """Lease store over the native TCPStore: value is ``payload|expiry``;
    expiry is refreshed by heartbeats and filtered on read (TTL semantics
    without server-side timers).

    Store I/O is TRANSIENTLY fallible (the master restarting, a dropped
    connection): ``put_with_lease``/``refresh`` retry with bounded,
    seeded-jittered exponential backoff — each retry counted into
    ``elastic_store_retries_total{op=...}`` — instead of crashing the
    heartbeat thread on the first hiccup.  A ``refresh`` that exhausts
    its retries raises :class:`LeaseLostError` (a NAMED verdict the
    caller can act on: re-register, re-rendezvous) rather than leaking
    whatever socket exception the attempt died of.  Fault points
    ``elastic.put``/``elastic.refresh`` fire inside each attempt, so the
    drill harness exercises exactly this recovery path."""

    def __init__(self, store, retries: int = 4, backoff_base: float = 0.05,
                 backoff_max: float = 1.0, jitter_seed: int = 0):
        self._s = store
        self._registered = set()
        self.retries = max(int(retries), 0)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self._jitter = random.Random(int(jitter_seed))
        from ..observability import metrics as _obs
        self._c_retries = _obs.get_registry().counter(
            "elastic_store_retries_total",
            "lease-store operations retried after a transient error")

    def _with_retries(self, op: str, fn):
        """Run ``fn`` with up to ``retries`` retried attempts.  Returns
        ``fn()``'s value; re-raises the LAST error when exhausted."""
        attempt = 0
        while True:
            try:
                return fn()
            except Exception:  # noqa: BLE001 — any transport error is
                # retryable; non-transient errors surface after retries
                if attempt >= self.retries:
                    raise
                self._c_retries.labels(op=op).inc()
                # seeded jitter: deterministic under test, decorrelated
                # across members in production (each store instance
                # seeds differently)
                sleep = min(self.backoff_base * (2 ** attempt),
                            self.backoff_max)
                time.sleep(sleep * (0.5 + self._jitter.random() / 2))
                attempt += 1

    def put_with_lease(self, key, value, ttl):
        from ..observability import faults as _faults
        claimed = [None]   # slot survives across retried attempts

        def _put():
            _faults.point("elastic.put")
            self._s.set(key, f"{value}|{time.time() + ttl}")
            if key not in self._registered:
                # enumeration index: the store has no prefix scan, so
                # members claim an atomic slot (add) and publish their
                # key under it; deleted members leave tombstone slots
                # filtered by check().  The claim is hoisted out of the
                # retry body: a retried attempt must REUSE the slot the
                # failed attempt already claimed, or every transient
                # error grows the index every reader scans forever.
                if claimed[0] is None:
                    claimed[0] = self._s.add("__elastic_index/n", 1) - 1
                self._s.set(f"__elastic_index/{claimed[0]}", key)
                self._registered.add(key)

        self._with_retries("put_with_lease", _put)

    def refresh(self, key, ttl):
        from ..observability import faults as _faults

        def _refresh():
            _faults.point("elastic.refresh")
            if not self._s.check(key):
                return False
            raw = self._s.get(key).decode()
            payload = raw.rsplit("|", 1)[0]
            self._s.set(key, f"{payload}|{time.time() + ttl}")
            return True

        try:
            return self._with_retries("refresh", _refresh)
        except Exception as e:  # noqa: BLE001 — named verdict for callers
            raise LeaseLostError(
                f"lease refresh for {key!r} failed after "
                f"{self.retries + 1} attempts ({type(e).__name__}: {e}) — "
                f"assume the lease expired and re-register") from e

    def delete(self, key):
        self._s.delete_key(key)
        self._registered.discard(key)

    def _index(self) -> List[str]:
        if not self._s.check("__elastic_index/n"):
            return []
        n = self._s.add("__elastic_index/n", 0)
        keys = []
        for i in range(n):
            if self._s.check(f"__elastic_index/{i}"):
                k = self._s.get(f"__elastic_index/{i}").decode()
                if k not in keys:
                    keys.append(k)
        return keys

    def list_prefix(self, prefix):
        out = {}
        now = time.time()
        for k in self._index():
            if not k.startswith(prefix) or not self._s.check(k):
                continue
            payload, expiry = self._s.get(k).decode().rsplit("|", 1)
            if float(expiry) > now:
                out[k] = payload
        return out


class ElasticManager:
    """Ref ``ElasticManager`` (``fleet/elastic/manager.py:131``).

    ``np`` may be "N" or "N:M" (min:max nodes, the elastic range). The
    manager registers this node under ``/{job}/nodes/{host}``, heartbeats
    the lease (``manager.py:250-290``), and reports membership health;
    ``watch()`` returns an ``ElasticStatus`` the launcher acts on
    (``fleet/elastic/collective.py`` relaunch path).
    """

    def __init__(self, job_id: str, np: str, host: str,
                 store: Optional[LeaseStore] = None,
                 heartbeat_interval: float = 1.0, ttl: float = 5.0,
                 on_change: Optional[Callable[[List[str]], None]] = None):
        self.job_id = job_id
        parts = str(np).split(":")
        self.np_min = int(parts[0])
        self.np_max = int(parts[-1])
        self.host = host
        self.store = store or MemLeaseStore()
        self.interval = heartbeat_interval
        self.ttl = ttl
        self.on_change = on_change
        self.enable = self.np_min != self.np_max or ":" in str(np)
        self._prefix = f"/{job_id}/nodes/"
        self._key = f"{self._prefix}{host}"
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._last_members: List[str] = []
        self.elastic_startup_time: Optional[float] = None

    # -- registration / heartbeat -------------------------------------------
    def register(self) -> None:
        self.store.put_with_lease(self._key, self.host, self.ttl)
        self._last_members = self.hosts()
        self._hb_thread = threading.Thread(target=self._heartbeat,
                                           daemon=True)
        self._hb_thread.start()

    def _heartbeat(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                ok = self.store.refresh(self._key, self.ttl)
            except LeaseLostError:
                # retries exhausted: treat as an expired lease and fall
                # through to re-registration — a heartbeat thread that
                # dies on a store hiccup silently drops this node from
                # the job at the NEXT TTL expiry
                ok = False
            if not ok:
                # lease lost (e.g. store restarted): re-register
                try:
                    self.store.put_with_lease(self._key, self.host,
                                              self.ttl)
                except Exception:  # noqa: BLE001 — keep beating; the
                    # next interval retries (put_with_lease already did
                    # its own bounded retries)
                    pass

    def exit(self, completed: bool = True) -> None:
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2 * self.interval)
        self.store.delete(self._key)

    # -- membership ---------------------------------------------------------
    def hosts(self) -> List[str]:
        return sorted(self.store.list_prefix(self._prefix).values())

    def _stable(self) -> bool:
        n = len(self.hosts())
        return self.np_min <= n <= self.np_max

    def health(self) -> str:
        n = len(self.hosts())
        if n < self.np_min:
            return ElasticStatus.HOLD
        return "ok"

    def rank_map(self) -> Dict[str, int]:
        """Deterministic host→rank assignment after a scale event (the
        reference rewrites ``PADDLE_TRAINER_ENDPOINTS`` the same way)."""
        return {h: i for i, h in enumerate(self.hosts())}

    def watch(self, timeout: Optional[float] = None) -> str:
        """Block until membership changes or timeout; classify the event."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            cur = self.hosts()
            if cur != self._last_members:
                self._last_members = cur
                if self.on_change is not None:
                    self.on_change(cur)
                if len(cur) < self.np_min:
                    return ElasticStatus.HOLD
                return ElasticStatus.RESTART
            if deadline is not None and time.monotonic() >= deadline:
                return ElasticStatus.COMPLETED
            time.sleep(min(self.interval, 0.1))
