"""Containers and pods: per-rank process management.

Ref ``launch/job/container.py`` (process wrapper w/ log redirection and
status) and ``launch/job/pod.py`` (the set of containers on one node).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Dict, List, Optional


class Container:
    """One rank's OS process (ref ``launch/job/container.py``)."""

    def __init__(self, entrypoint: List[str], env: Dict[str, str],
                 out_path: str, err_path: Optional[str] = None,
                 essential: bool = True):
        self.entrypoint = list(entrypoint)
        self.env = dict(env)
        self.out_path = out_path
        self.err_path = err_path or out_path
        # essential containers define job completion (trainers); a PS server
        # is non-essential: it serves until the trainers are done, then is
        # stopped by the pod (ref launch watcher stopping pserver pods)
        self.essential = essential
        self._proc: Optional[subprocess.Popen] = None
        self._out_f = None
        self._err_f = None
        self.restarts = 0

    def start(self) -> None:
        d = os.path.dirname(self.out_path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._out_f = open(self.out_path, "ab")
        self._err_f = (self._out_f if self.err_path == self.out_path
                       else open(self.err_path, "ab"))
        full_env = dict(os.environ)
        full_env.update(self.env)
        self._proc = subprocess.Popen(
            self.entrypoint, env=full_env,
            stdout=self._out_f, stderr=self._err_f)

    @property
    def pid(self) -> Optional[int]:
        return self._proc.pid if self._proc else None

    def exit_code(self) -> Optional[int]:
        if self._proc is None:
            return None
        return self._proc.poll()

    def is_running(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        if self._proc is None:
            return None
        try:
            return self._proc.wait(timeout)
        except subprocess.TimeoutExpired:
            return None

    def terminate(self, force: bool = False) -> None:
        if self._proc is not None and self._proc.poll() is None:
            (self._proc.kill if force else self._proc.terminate)()
        for f in (self._out_f, self._err_f):
            try:
                if f and not f.closed:
                    f.close()
            except Exception:
                pass

    def logs(self, tail: int = 50) -> str:
        try:
            with open(self.out_path, "rb") as f:
                return b"\n".join(f.read().splitlines()[-tail:]).decode(
                    errors="replace")
        except OSError:
            return ""


class Pod:
    """All containers of this node (ref ``launch/job/pod.py``)."""

    def __init__(self):
        self.containers: List[Container] = []

    def add(self, c: Container) -> None:
        self.containers.append(c)

    def deploy(self) -> None:
        for c in self.containers:
            c.start()

    def is_running(self) -> bool:
        return any(c.is_running() for c in self.containers)

    def exit_codes(self) -> List[Optional[int]]:
        return [c.exit_code() for c in self.containers]

    def failed(self) -> bool:
        return any(rc not in (None, 0) for rc in self.exit_codes())

    def join(self, poll_interval: float = 0.2) -> int:
        """Wait until every essential container exits; on any failure stop
        the rest. Non-essential containers (PS servers) are stopped once the
        essential set completes. Returns the first non-zero exit code
        (0 on success)."""
        while True:
            # essential success is checked FIRST: once every trainer has
            # exited 0 the job succeeded — a PS server exiting non-zero
            # when its trainer connections drop must not fail the run
            essential = [c.exit_code() for c in self.containers if c.essential]
            if essential and all(rc == 0 for rc in essential):
                self.stop_graceful()  # reap the non-essential servers
                return 0
            bad = [rc for rc in self.exit_codes() if rc not in (None, 0)]
            if bad:
                self.stop(force=True)
                return bad[0]
            if not essential and all(rc == 0 for rc in self.exit_codes()):
                return 0
            time.sleep(poll_interval)

    def stop(self, force: bool = False) -> None:
        for c in self.containers:
            c.terminate(force=force)

    def stop_graceful(self, grace: float = 5.0) -> None:
        """SIGTERM, bounded wait, then SIGKILL stragglers — lets PS servers
        flush/save on shutdown (the reference's watcher stops pserver pods
        gracefully)."""
        for c in self.containers:
            c.terminate(force=False)
        deadline = time.monotonic() + grace
        for c in self.containers:
            c.wait(timeout=max(0.0, deadline - time.monotonic()))
        for c in self.containers:
            if c.is_running():
                c.terminate(force=True)

    def restart(self) -> None:
        self.stop(force=True)
        for c in self.containers:
            c.restarts += 1
        self.deploy()
