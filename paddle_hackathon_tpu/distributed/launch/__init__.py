"""Distributed job launcher.

TPU-native equivalent of ``python -m paddle.distributed.launch``
(ref ``python/paddle/distributed/launch/main.py:18``): parses job topology,
rendezvouses multi-node peers through the framework TCPStore (the role the
reference's HTTP/etcd master plays, ``launch/controllers/master.py``), spawns
one OS process per rank with the ``PADDLE_*`` env protocol, redirects
per-rank logs, watches exit codes and applies the restart policy.

On TPU pods the natural layout is one process per host (each owning all
local chips, SPMD inside), so ``--nproc_per_node`` defaults to 1; CPU-mesh
testing can raise it.
"""

from .main import launch  # noqa: F401
from .context import Context  # noqa: F401
