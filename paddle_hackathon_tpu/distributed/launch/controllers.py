"""Launch controllers: collective and parameter-server.

Ref ``launch/controllers/controller.py:35`` (watch loop + restart policy),
``launch/controllers/collective.py:23`` (CollectiveController),
``launch/controllers/ps.py`` (PSController) and
``launch/controllers/master.py`` (rendezvous master). The reference's
HTTP/etcd master is replaced by the framework's native TCPStore
(``parallel/store.py`` over ``native/runtime.cc``).
"""

from __future__ import annotations

import os
import sys
import time
from typing import List, Optional

from .context import Context, free_port
from .job import Container, Pod


class Master:
    """Multi-node rendezvous over TCPStore (ref ``controllers/master.py``:
    ``HTTPMaster:66``/``ETCDMaster:175`` sync_peers)."""

    def __init__(self, ctx: Context):
        self.ctx = ctx
        self._store = None

    def sync_peers(self, my_endpoint: str) -> tuple:
        """Register this node, wait for all; returns (rank, endpoints)."""
        from ...parallel.store import TCPStore
        args = self.ctx.args
        host, port = args.master.split(":")
        is_master = args.rank == 0 or (args.rank == -1 and
                                       host in ("127.0.0.1", "localhost",
                                                self.ctx.node.ip))
        # rank 0's launcher hosts the store; everyone connects
        if is_master:
            try:
                self._store = TCPStore(host="127.0.0.1", port=int(port),
                                       is_master=True, timeout=120.0)
            except RuntimeError:
                is_master = False  # another launcher on this host won the bind
        if self._store is None:
            self._store = TCPStore(host=host, port=int(port), timeout=120.0)
        s = self._store
        job = self.ctx.args.job_id
        rank = (self.ctx.args.rank if self.ctx.args.rank >= 0
                else s.add(f"{job}/nodes") - 1)
        s.set(f"{job}/ep/{rank}", my_endpoint)
        eps = [s.get(f"{job}/ep/{r}").decode()
               for r in range(self.ctx.args.nnodes)]
        return rank, eps

    def close(self):
        if self._store is not None:
            self._store.close()


class Controller:
    """Base controller: build pod → deploy → watch (ref
    ``controllers/controller.py:35``)."""

    def __init__(self, ctx: Context):
        self.ctx = ctx
        self.pod = Pod()
        self.master: Optional[Master] = None

    # -- subclass API -------------------------------------------------------
    def build_pod(self) -> None:
        raise NotImplementedError

    # -- lifecycle ----------------------------------------------------------
    def run(self) -> int:
        self.build_pod()
        self.pod.deploy()
        return self.watch()

    def watch(self) -> int:
        """Exit-code watch loop with bounded restart (ref controller.py
        pod-status loop + ``launch/job/job.py`` restart policy)."""
        restarts = 0
        while True:
            rc = self.pod.join()
            if rc == 0:
                return 0
            if restarts >= self.ctx.args.max_restart:
                sys.stderr.write(
                    f"[launch] job failed (exit={rc}) after {restarts} "
                    f"restarts; giving up\n")
                return rc
            restarts += 1
            sys.stderr.write(
                f"[launch] rank failure (exit={rc}); restart "
                f"{restarts}/{self.ctx.args.max_restart}\n")
            self.rebuild()

    def rebuild(self) -> None:
        self.pod.stop(force=True)
        self.pod = Pod()
        self.build_pod()
        self.pod.deploy()

    def stop(self) -> None:
        self.pod.stop(force=True)
        if self.master:
            self.master.close()

    # -- helpers ------------------------------------------------------------
    def _script_cmd(self) -> List[str]:
        a = self.ctx.args
        script = a.training_script
        if script.endswith(".py"):
            return [sys.executable, "-u", script] + a.training_script_args
        return [script] + a.training_script_args

    def _log_path(self, name: str) -> str:
        return os.path.join(self.ctx.args.log_dir,
                            f"{self.ctx.args.job_id}.{name}.log")


class CollectiveController(Controller):
    """One process per rank; env protocol consumed by
    ``parallel.env.init_parallel_env`` (ref ``collective.py:23``)."""

    def build_pod(self) -> None:
        ctx = self.ctx
        a = ctx.args
        nprocs = ctx.nprocs()

        if a.nnodes > 1:
            if not a.master:
                raise ValueError("--master host:port is required for "
                                 "multi-node jobs")
            self.master = Master(ctx)
            node_rank, _ = self.master.sync_peers(ctx.node.ip)
            # the jax coordinator lives in global rank 0's process on the
            # master node; its address is agreed through the store
            s = self.master._store
            if node_rank == 0:
                coord = f"{ctx.node.ip}:{free_port()}"
                s.set(f"{a.job_id}/coord", coord)
            else:
                coord = s.get(f"{a.job_id}/coord").decode()
        else:
            node_rank = 0
            coord = (f"127.0.0.1:{free_port()}"
                     if nprocs > 1 else None)

        world = a.nnodes * nprocs
        endpoints = [f"{self.ctx.node.ip}:{free_port()}"
                     for _ in range(nprocs)]
        for local_rank in range(nprocs):
            rank = node_rank * nprocs + local_rank
            env = {
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_LOCAL_RANK": str(local_rank),
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
                "PADDLE_CURRENT_ENDPOINT": endpoints[local_rank],
                "PADDLE_JOB_ID": a.job_id,
            }
            if coord:
                env["PADDLE_MASTER"] = coord
            self.pod.add(Container(self._script_cmd(), env,
                                   self._log_path(f"rank{rank}")))


class PSController(Controller):
    """Parameter-server topology: N servers + M trainers (ref
    ``controllers/ps.py``). Env protocol consumed by ``distributed.ps``."""

    def build_pod(self) -> None:
        a = self.ctx.args
        n_srv = a.server_num or 1
        n_trn = a.trainer_num or 1
        server_eps = [f"127.0.0.1:{free_port()}" for _ in range(n_srv)]
        common = {
            "PADDLE_PSERVER_ENDPOINTS": ",".join(server_eps),
            "PADDLE_TRAINERS_NUM": str(n_trn),
            "PADDLE_JOB_ID": a.job_id,
        }
        for i, ep in enumerate(server_eps):
            env = dict(common, PADDLE_ROLE="PSERVER", PADDLE_PORT=ep.split(":")[1],
                       PADDLE_SERVER_ID=str(i))
            self.pod.add(Container(self._script_cmd(), env,
                                   self._log_path(f"server{i}"),
                                   essential=False))
        for i in range(n_trn):
            env = dict(common, PADDLE_ROLE="TRAINER", PADDLE_TRAINER_ID=str(i))
            self.pod.add(Container(self._script_cmd(), env,
                                   self._log_path(f"trainer{i}")))


def make_controller(ctx: Context) -> Controller:
    if ctx.args.run_mode == "ps" or ctx.args.server_num > 0:
        return PSController(ctx)
    return CollectiveController(ctx)
