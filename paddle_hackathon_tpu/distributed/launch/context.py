"""Launcher context: arguments + node environment.

Ref ``launch/context/__init__.py:25`` (``Context``) and
``launch/context/node.py`` (local device discovery). Arguments mirror the
reference CLI (``launch/main.py`` argparse block) minus the vendor-specific
knobs that have no TPU meaning.
"""

from __future__ import annotations

import argparse
import os
import socket
from dataclasses import dataclass, field
from typing import List, Optional


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class Node:
    ip: str = "127.0.0.1"
    device_count: int = 1

    @classmethod
    def detect(cls) -> "Node":
        try:
            ip = socket.gethostbyname(socket.gethostname())
        except OSError:
            ip = "127.0.0.1"
        # count local accelerators lazily; jax import is heavy, so allow env
        # override (the reference reads CUDA_VISIBLE_DEVICES analogously)
        n = os.environ.get("PHT_VISIBLE_DEVICES")
        if n is not None:
            count = len([d for d in n.split(",") if d != ""])
        else:
            count = 1
        return cls(ip=ip, device_count=max(1, count))


@dataclass
class Args:
    master: Optional[str] = None          # host:port of rendezvous store
    nnodes: int = 1
    nproc_per_node: Optional[int] = None
    rank: int = -1                        # node rank; -1 = assigned by master
    job_id: str = "default"
    log_dir: str = "log"
    log_level: str = "INFO"
    run_mode: str = "collective"          # collective | ps
    server_num: int = 0                   # ps mode
    trainer_num: int = 0                  # ps mode
    max_restart: int = 3
    elastic_level: int = -1               # -1 off, >=0 on (ref elastic)
    training_script: str = ""
    training_script_args: List[str] = field(default_factory=list)


def parse_args(argv: Optional[List[str]] = None) -> Args:
    p = argparse.ArgumentParser(
        prog="paddle_hackathon_tpu.distributed.launch",
        description="TPU-native distributed launcher")
    p.add_argument("--master", default=None,
                   help="rendezvous store endpoint host:port")
    p.add_argument("--nnodes", type=str, default="1",
                   help="number of nodes (or N:M elastic range)")
    p.add_argument("--nproc_per_node", type=int, default=None)
    p.add_argument("--rank", type=int, default=-1)
    p.add_argument("--job_id", default="default")
    p.add_argument("--log_dir", default="log")
    p.add_argument("--log_level", default="INFO")
    p.add_argument("--run_mode", default="collective",
                   choices=["collective", "ps"])
    p.add_argument("--server_num", type=int, default=0)
    p.add_argument("--trainer_num", type=int, default=0)
    p.add_argument("--max_restart", type=int, default=3)
    p.add_argument("--elastic_level", type=int, default=-1)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    ns = p.parse_args(argv)
    nnodes = str(ns.nnodes).split(":")[0]  # elastic N:M → N for now
    return Args(master=ns.master, nnodes=int(nnodes),
                nproc_per_node=ns.nproc_per_node, rank=ns.rank,
                job_id=ns.job_id, log_dir=ns.log_dir,
                log_level=ns.log_level, run_mode=ns.run_mode,
                server_num=ns.server_num, trainer_num=ns.trainer_num,
                max_restart=ns.max_restart, elastic_level=ns.elastic_level,
                training_script=ns.training_script,
                training_script_args=list(ns.training_script_args))


class Context:
    """Ref ``launch/context/__init__.py:25``."""

    def __init__(self, args: Optional[Args] = None,
                 envs: Optional[dict] = None):
        self.args = args or Args()
        self.envs = dict(os.environ if envs is None else envs)
        self.node = Node.detect()
        self.status = "ready"

    def is_multi_node(self) -> bool:
        return self.args.nnodes > 1

    def nprocs(self) -> int:
        if self.args.nproc_per_node is not None:
            return self.args.nproc_per_node
        return 1  # one SPMD process per host on TPU
