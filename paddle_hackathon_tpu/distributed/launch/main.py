"""Launcher entry point (ref ``launch/main.py:18``).

Usage::

    python -m paddle_hackathon_tpu.distributed.launch \
        --nproc_per_node 4 train.py --my-arg 1

    python -m paddle_hackathon_tpu.distributed.launch \
        --master 10.0.0.1:6170 --nnodes 2 train.py
"""

from __future__ import annotations

import signal
import sys
from typing import List, Optional

from .context import Context, parse_args
from .controllers import make_controller


def launch(argv: Optional[List[str]] = None) -> int:
    ctx = Context(parse_args(argv))
    c = make_controller(ctx)

    def _sig(signum, frame):
        c.stop()
        sys.exit(128 + signum)

    prev = {}
    try:
        for s in (signal.SIGTERM, signal.SIGINT):
            prev[s] = signal.signal(s, _sig)
    except ValueError:
        pass  # not main thread (tests)
    try:
        return c.run()
    finally:
        c.stop()
        # restore the caller's handlers: leaving _sig installed after
        # this controller is stopped turns any later SIGTERM into a
        # SystemExit inside unrelated code (a programmatic launch()
        # caller — or the timed test suite, where the budget kill was
        # recorded as a failure of whatever test it interrupted)
        for s, h in prev.items():
            try:
                signal.signal(s, h)
            except ValueError:
                pass


def main() -> None:
    sys.exit(launch())


if __name__ == "__main__":
    main()
