"""Launcher entry point (ref ``launch/main.py:18``).

Usage::

    python -m paddle_hackathon_tpu.distributed.launch \
        --nproc_per_node 4 train.py --my-arg 1

    python -m paddle_hackathon_tpu.distributed.launch \
        --master 10.0.0.1:6170 --nnodes 2 train.py
"""

from __future__ import annotations

import signal
import sys
from typing import List, Optional

from .context import Context, parse_args
from .controllers import make_controller


def launch(argv: Optional[List[str]] = None) -> int:
    ctx = Context(parse_args(argv))
    c = make_controller(ctx)

    def _sig(signum, frame):
        c.stop()
        sys.exit(128 + signum)

    try:
        signal.signal(signal.SIGTERM, _sig)
        signal.signal(signal.SIGINT, _sig)
    except ValueError:
        pass  # not main thread (tests)
    try:
        return c.run()
    finally:
        c.stop()


def main() -> None:
    sys.exit(launch())


if __name__ == "__main__":
    main()
