"""paddle.distributed compatibility surface: ParallelEnv, p2p send/recv,
split, PS entry configs, QueueDataset/InMemoryDataset, gloo shims, spawn.

Ref ``python/paddle/distributed/__init__.py`` __all__. Mechanisms:
- p2p send/recv ride the rendezvous TCP store (ref ``send_v2/recv_v2`` NCCL
  p2p ops): arrays serialize through the store keyed by
  (src, dst, tag, seq). Correct across launcher-spawned processes; within a
  single process they queue locally. On-mesh tensor movement inside compiled
  programs uses ppermute (``parallel/pipeline.py``) — this API is the eager
  out-of-graph path, which is what the reference's dygraph send/recv is.
- split() builds the TP layer family (ref ``distributed/collective.py
  split``): column/row-parallel fc or vocab-parallel embedding.
"""

from __future__ import annotations

import io
import os
import queue as _queue
import threading

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..observability.sanitizers import make_lock
from ..parallel import env as _env
from ..parallel import store as _store_mod

__all__ = [
    "ParallelEnv", "send", "recv", "isend", "irecv", "wait", "get_group",
    "split", "ProbabilityEntry", "CountFilterEntry", "ShowClickEntry",
    "QueueDataset", "InMemoryDataset", "gloo_init_parallel_env",
    "gloo_barrier", "gloo_release", "spawn",
]


class ParallelEnv:
    """Env view of the launcher protocol (ref fluid/dygraph/parallel.py
    ParallelEnv)."""

    def __init__(self):
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        self._world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._endpoints = [e for e in eps.split(",") if e]
        self._device_id = int(os.environ.get("FLAGS_selected_tpus",
                                             os.environ.get("FLAGS_selected_gpus", 0)) or 0)

    @property
    def rank(self):
        return self._rank

    @property
    def local_rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    @property
    def nranks(self):
        return self._world_size

    @property
    def device_id(self):
        return self._device_id

    @property
    def current_endpoint(self):
        if self._endpoints and self._rank < len(self._endpoints):
            return self._endpoints[self._rank]
        return "127.0.0.1:0"

    @property
    def trainer_endpoints(self):
        return list(self._endpoints)


# -- p2p over the rendezvous store ------------------------------------------

_local_chan: dict = {}
# make_lock: visible to the lock-order/race sanitizers (PHT009 sweep)
_chan_lock = make_lock("dist.chan")
_p2p_seq: dict = {}
_store = None


def _get_store():
    global _store
    if _store is None and (os.environ.get("PADDLE_MASTER_PORT")
                           or os.environ.get("PADDLE_MASTER")):
        if not os.environ.get("PADDLE_MASTER_PORT"):
            host, _, port = os.environ["PADDLE_MASTER"].rpartition(":")
            os.environ.setdefault("PADDLE_MASTER_ADDR", host or "127.0.0.1")
            os.environ.setdefault("PADDLE_MASTER_PORT", port)
        _store = _store_mod.store_from_env()
    return _store


def _pack(arr) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.asarray(arr), allow_pickle=False)
    return buf.getvalue()


def _unpack(raw: bytes):
    return np.load(io.BytesIO(raw), allow_pickle=False)


class _P2PTask:
    def __init__(self, fn=None, value=None):
        self._fn = fn
        self._value = value
        self._done = fn is None

    def wait(self):
        if not self._done:
            self._value = self._fn()
            self._done = True
        return self._value

    def is_completed(self):
        return self._done


def _proc_rank():
    # launcher env rank: spawned ranks are separate jax processes whose
    # jax.process_index() is always 0, so the env var is authoritative here
    return int(os.environ.get("PADDLE_TRAINER_ID", _env.get_rank()))


def send(tensor, dst=0, group=None, use_calc_stream=True, tag=0):
    """Eager p2p send (ref distributed/collective.py send -> send_v2)."""
    src = _proc_rank()
    key = ("p2p", src, dst, tag)
    seq = _p2p_seq[key] = _p2p_seq.get(key, -1) + 1
    store = _get_store()
    payload = _pack(tensor.numpy() if isinstance(tensor, Tensor) else tensor)
    if store is not None:
        store.set(f"p2p/{src}/{dst}/{tag}/{seq}", payload)
        return
    with _chan_lock:
        _local_chan.setdefault((dst, tag), _queue.Queue()).put(payload)


def recv(tensor, src=0, group=None, use_calc_stream=True, tag=0):
    """Eager p2p recv; writes into ``tensor`` in place and returns it."""
    dst = _proc_rank()
    key = ("p2p-r", src, dst, tag)
    seq = _p2p_seq[key] = _p2p_seq.get(key, -1) + 1
    store = _get_store()
    if store is not None:
        raw = store.get(f"p2p/{src}/{dst}/{tag}/{seq}")
    else:
        with _chan_lock:
            q = _local_chan.setdefault((dst, tag), _queue.Queue())
        raw = q.get()
    arr = _unpack(raw)
    if isinstance(tensor, Tensor):
        tensor._set_value(jnp.asarray(arr))
        return tensor
    return Tensor(jnp.asarray(arr))


def isend(tensor, dst=0, group=None, tag=0):
    send(tensor, dst, group, tag=tag)
    return _P2PTask(value=None)


def irecv(tensor, src=0, group=None, tag=0):
    return _P2PTask(fn=lambda: recv(tensor, src, group, tag=tag))


def wait(tensor, group=None, use_calc_stream=True):
    """Ref distributed wait: block until the tensor's value is materialized
    (XLA async dispatch barrier)."""
    if isinstance(tensor, Tensor):
        jnp.asarray(tensor._value).block_until_ready()
    return tensor


def get_group(id=0):  # noqa: A002
    from ..parallel import collective
    return collective.new_group()


def split(x, size, operation="linear", axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Distributed fc/embedding (ref distributed/collective.py split):
    column/row-parallel Linear or vocab-parallel Embedding over the model
    axis of the current mesh."""
    from ..parallel import mp_layers
    if operation == "embedding":
        layer = mp_layers.VocabParallelEmbedding(size[0], size[1],
                                                 weight_attr=weight_attr)
        return layer(x)
    if operation != "linear":
        raise ValueError(f"unknown split operation {operation!r}")
    if axis == 1:
        layer = mp_layers.RowParallelLinear(size[0], size[1],
                                            weight_attr=weight_attr,
                                            has_bias=bias_attr is not False,
                                            input_is_parallel=not gather_out)
    else:
        layer = mp_layers.ColumnParallelLinear(size[0], size[1],
                                               weight_attr=weight_attr,
                                               has_bias=bias_attr is not False,
                                               gather_output=gather_out)
    return layer(x)


# -- PS sparse-table entry configs (ref distributed/entry_attr.py) -----------

class ProbabilityEntry:
    def __init__(self, probability):
        if not 0 < probability <= 1:
            raise ValueError("probability must be in (0, 1]")
        self.probability = probability

    def _to_attr(self):
        return f"probability_entry:{self.probability}"


class CountFilterEntry:
    def __init__(self, count_filter):
        if count_filter < 0:
            raise ValueError("count_filter must be >= 0")
        self.count_filter = int(count_filter)

    def _to_attr(self):
        return f"count_filter_entry:{self.count_filter}"


class ShowClickEntry:
    def __init__(self, show_name, click_name):
        self.show_name = show_name
        self.click_name = click_name

    def _to_attr(self):
        return f"show_click_entry:{self.show_name}:{self.click_name}"


# -- PS-era datasets (ref distributed/fleet/dataset/dataset.py) --------------

class _FileListDataset:
    """Line-oriented file-list dataset feeding ``use_var`` slots through a
    user data_generator (ref DatasetBase/QueueDataset)."""

    def __init__(self):
        self._filelist = []
        self._batch_size = 1
        self._thread_num = 1
        self._use_var = []
        self._pipe_command = None
        self._parse_fn = None

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command=None, input_type=0, fs_name="", fs_ugi="",
             **kwargs):
        self._batch_size = batch_size
        self._thread_num = thread_num
        self._use_var = use_var or []
        self._pipe_command = pipe_command

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def set_parse_fn(self, fn):
        """Non-reference helper: line -> sample tuple (replaces the
        pipe_command subprocess protocol)."""
        self._parse_fn = fn

    def _iter_lines(self):
        for path in self._filelist:
            with open(path) as f:
                for line in f:
                    line = line.rstrip("\n")
                    if self._parse_fn is not None:
                        yield self._parse_fn(line)
                    else:
                        yield tuple(float(v) for v in line.split())

    def _sample_source(self):
        return self._iter_lines()

    def __iter__(self):
        batch = []
        for sample in self._sample_source():
            batch.append(sample)
            if len(batch) == self._batch_size:
                yield _collate(batch)
                batch = []
        if batch:
            yield _collate(batch)


def _collate(batch):
    cols = list(zip(*batch))
    return tuple(Tensor(jnp.asarray(np.asarray(c))) for c in cols)


class QueueDataset(_FileListDataset):
    """Streaming file dataset (ref QueueDataset: pipe readers feed trainer
    queues; here a generator feeds the training loop)."""


class InMemoryDataset(_FileListDataset):
    """Loaded-then-shuffled dataset (ref InMemoryDataset)."""

    def __init__(self):
        super().__init__()
        self._samples = None

    def load_into_memory(self):
        self._samples = list(self._iter_lines())

    def local_shuffle(self):
        import random
        if self._samples is None:
            self.load_into_memory()
        random.shuffle(self._samples)

    def global_shuffle(self, fleet=None, thread_num=12):
        self.local_shuffle()

    def release_memory(self):
        self._samples = None

    def get_memory_data_size(self, fleet=None):
        return len(self._samples or [])

    def get_shuffle_data_size(self, fleet=None):
        return self.get_memory_data_size(fleet)

    def _sample_source(self):
        return iter(self._samples) if self._samples is not None \
            else self._iter_lines()


# -- gloo shims (CPU collectives context; ref gloo_init_parallel_env) --------

def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    _env.init_parallel_env()


def gloo_barrier():
    from ..parallel import collective
    collective.barrier()


def gloo_release():
    pass


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Launch ``func`` on N processes with the launcher env protocol
    (ref distributed/spawn.py)."""
    import multiprocessing as mp

    if nprocs <= 0:
        nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) or 1
    ctx = mp.get_context("spawn")
    procs = []
    master_port = options.get("master_port", 0)
    store = _store_mod.MasterStore(master_port) if nprocs > 1 else None
    for rank in range(nprocs):
        env = {
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nprocs),
            # NOTE: PADDLE_MASTER is deliberately NOT set — that variable
            # names the jax.distributed coordinator (env.init_parallel_env),
            # not the KV store; the store speaks ADDR/PORT below.
            "PADDLE_MASTER_ADDR": "127.0.0.1",
            "PADDLE_MASTER_PORT": str(store.port) if store else "",
            "PADDLE_STORE_HOSTED": "1",  # parent hosts the master store
        }
        p = ctx.Process(target=_spawn_main, args=(func, args, env),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        for p in procs:
            if p.exitcode:
                raise RuntimeError(f"spawned rank failed with {p.exitcode}")
        return procs
    # join=False: the caller owns the handle; it must keep the store alive
    # until the children exit (TCPStore.__del__ stops the server)
    return _SpawnContext(procs, store)


class _SpawnContext(list):
    """Process list that keeps the rendezvous store server alive."""

    def __init__(self, procs, store):
        super().__init__(procs)
        self._store = store

    def join(self):
        for p in self:
            p.join()
        for p in self:
            if p.exitcode:
                raise RuntimeError(f"spawned rank failed with {p.exitcode}")


def _spawn_main(func, args, env):
    os.environ.update(env)
    func(*args)
