"""paddle.fft equivalent (ref ``python/paddle/fft.py`` — pocketfft there;
XLA's FFT HLO here, one lowering path for CPU/TPU)."""

from __future__ import annotations

import jax.numpy as jnp

from .core.autograd import apply_op
from .core.tensor import Tensor

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2", "ifft2",
           "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _norm(norm):
    return None if norm in (None, "backward") else norm


def _mk1(name, jfn):
    def op(x, n=None, axis=-1, norm="backward", name_=None):
        return apply_op(name, lambda v: jfn(v, n=n, axis=axis,
                                            norm=_norm(norm)), [_t(x)])
    op.__name__ = name
    return op


def _mk2(name, jfn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name_=None):
        return apply_op(name, lambda v: jfn(v, s=s, axes=axes,
                                            norm=_norm(norm)), [_t(x)])
    op.__name__ = name
    return op


fft = _mk1("fft", jnp.fft.fft)
ifft = _mk1("ifft", jnp.fft.ifft)
rfft = _mk1("rfft", jnp.fft.rfft)
irfft = _mk1("irfft", jnp.fft.irfft)
hfft = _mk1("hfft", jnp.fft.hfft)
ihfft = _mk1("ihfft", jnp.fft.ihfft)
fft2 = _mk2("fft2", jnp.fft.fft2)
ifft2 = _mk2("ifft2", jnp.fft.ifft2)
rfft2 = _mk2("rfft2", jnp.fft.rfft2)
irfft2 = _mk2("irfft2", jnp.fft.irfft2)
fftn = _mk2("fftn", jnp.fft.fftn)
ifftn = _mk2("ifftn", jnp.fft.ifftn)
rfftn = _mk2("rfftn", jnp.fft.rfftn)
irfftn = _mk2("irfftn", jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype="float32", name=None):
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype))


def rfftfreq(n, d=1.0, dtype="float32", name=None):
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype))


def fftshift(x, axes=None, name=None):
    return apply_op("fftshift", lambda v: jnp.fft.fftshift(v, axes), [_t(x)])


def ifftshift(x, axes=None, name=None):
    return apply_op("ifftshift", lambda v: jnp.fft.ifftshift(v, axes), [_t(x)])


def _hermitian_nd(v, s, axes, norm, inverse):
    """n-D FFT with Hermitian symmetry on the last axis: regular (i)fft on
    the leading axes, 1-D hfft/ihfft on the last (how the reference defines
    hfft2/hfftn — fft_c2r on last axis, c2c elsewhere)."""
    if axes is None:
        axes = tuple(range(v.ndim))
    axes = tuple(a % v.ndim for a in axes)
    sizes = dict(zip(axes, s)) if s is not None else {}
    lead, last = axes[:-1], axes[-1]
    nrm = _norm(norm)
    if inverse:
        v = jnp.fft.ihfft(v, n=sizes.get(last), axis=last, norm=nrm)
        for a in lead:
            v = jnp.fft.ifft(v, n=sizes.get(a), axis=a, norm=nrm)
        return v
    for a in lead:
        v = jnp.fft.fft(v, n=sizes.get(a), axis=a, norm=nrm)
    return jnp.fft.hfft(v, n=sizes.get(last), axis=last, norm=nrm)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply_op("hfft2",
                    lambda v: _hermitian_nd(v, s, axes, norm, False), [_t(x)])


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply_op("ihfft2",
                    lambda v: _hermitian_nd(v, s, axes, norm, True), [_t(x)])


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    return apply_op("hfftn",
                    lambda v: _hermitian_nd(v, s, axes, norm, False), [_t(x)])


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    return apply_op("ihfftn",
                    lambda v: _hermitian_nd(v, s, axes, norm, True), [_t(x)])


__all__ += ["hfft2", "ihfft2", "hfftn", "ihfftn"]
