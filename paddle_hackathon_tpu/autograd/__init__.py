"""paddle.autograd — custom autograd functions + backward entry point.

Ref ``python/paddle/autograd/__init__.py`` (PyLayer/PyLayerContext from
``py_layer.py``; C++ engine hook ``fluid/eager/pylayer``). Here ``PyLayer``
records a :class:`~..core.autograd.GradNode` on the eager tape whose vjp
calls the user's ``backward`` — the same mechanism generated ops use, so
custom functions compose with hooks, ``grad()`` and higher-order ops.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core import autograd as _core_ag
from ..core.autograd import (enable_grad, grad, is_grad_enabled,  # noqa: F401
                             no_grad, run_backward, set_grad_enabled)
from ..core.tensor import Tensor

__all__ = ["PyLayer", "PyLayerContext", "backward", "grad", "no_grad",
           "enable_grad", "set_grad_enabled", "is_grad_enabled"]


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward (ref autograd/backward_mode.py)."""
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    gts = []
    for t, g in zip(tensors, grad_tensors):
        gts.append(g if g is not None
                   else Tensor(jnp.ones_like(t._value)))
    run_backward(tensors, gts, retain_graph=retain_graph)


class PyLayerContext:
    """Carries state from forward to backward (ref py_layer.py
    PyLayerContext: save_for_backward/saved_tensor + free attrs)."""

    def __init__(self):
        self._saved = ()
        self._non_differentiable = ()
        self._materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    def saved_tensor(self):
        return list(self._saved)

    def mark_non_differentiable(self, *tensors):
        self._non_differentiable = tensors

    def set_materialize_grads(self, value: bool):
        self._materialize_grads = bool(value)


class PyLayer:
    """Custom autograd function (ref py_layer.py PyLayer).

    Subclass with static ``forward(ctx, *args)`` and ``backward(ctx,
    *output_grads)``; call via ``MyLayer.apply(*args)``. ``backward`` must
    return one grad per *tensor* input of forward (None for inputs that
    don't need grad), exactly the reference contract.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError("implement PyLayer.forward")

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError("implement PyLayer.backward")

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tape_on = is_grad_enabled()

        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (tuple, list))
        out_list = [outs] if single else list(outs)

        # tensor inputs (positional and keyword); those needing grad
        # become tape parents (the reference tracks kwarg tensors too)
        all_inputs = list(args) + [kwargs[k] for k in sorted(kwargs)]
        tensor_positions = [i for i, a in enumerate(all_inputs)
                            if isinstance(a, Tensor)]
        diff_positions = [i for i in tensor_positions
                          if not all_inputs[i].stop_gradient
                          and jnp.issubdtype(
                              jnp.result_type(all_inputs[i]._value),
                              jnp.inexact)]
        if not tape_on or not diff_positions:
            return outs

        parents = []
        for i in diff_positions:
            src = all_inputs[i]
            if src._grad_node is not None:
                parents.append((src._grad_node, src._out_idx))
            else:
                parents.append(_core_ag._LeafSlot(src))

        non_diff_ids = {id(t) for t in ctx._non_differentiable}
        out_ids = [id(o) for o in out_list]
        out_avals = [(o._value.shape, o._value.dtype) for o in out_list]

        def node_vjp(cotangents):
            with no_grad():
                gouts = []
                for ct, oid, (shape, dtype) in zip(cotangents, out_ids,
                                                   out_avals):
                    if oid in non_diff_ids:
                        gouts.append(None)
                    elif ct is None and ctx._materialize_grads:
                        gouts.append(Tensor(jnp.zeros(shape, dtype)))
                    else:
                        gouts.append(Tensor(ct) if ct is not None else None)
                grads = cls.backward(ctx, *(gouts if not single
                                            else [gouts[0]]))
            if isinstance(grads, Tensor) or grads is None:
                grads = (grads,)
            grads = list(grads)
            if len(grads) == len(tensor_positions) > len(diff_positions):
                # backward returned one grad per tensor input; select the
                # differentiable ones
                by_pos = dict(zip(tensor_positions, grads))
                grads = [by_pos[i] for i in diff_positions]
            if len(grads) != len(diff_positions):
                raise ValueError(
                    f"{cls.__name__}.backward returned {len(grads)} grads "
                    f"for {len(diff_positions)} differentiable inputs")
            return tuple(g._value if isinstance(g, Tensor) else g
                         for g in grads)

        node = _core_ag.GradNode(f"pylayer.{cls.__name__}", node_vjp,
                                 parents, len(out_list), out_avals)
        wrapped = [Tensor(o._value, stop_gradient=False, _grad_node=node,
                          _out_idx=i) for i, o in enumerate(out_list)]
        out_list.clear()  # node_vjp keeps only ids/avals, not the buffers
        return wrapped[0] if single else tuple(wrapped)


# legacy alias used by some reference code paths
LegacyPyLayer = PyLayer
