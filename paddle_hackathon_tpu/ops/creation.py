"""Tensor creation ops (ref ``python/paddle/tensor/creation.py``).

Each op is a single XLA lowering via jax.numpy — the reference's per-backend
kernel forest (``paddle/phi/kernels/cpu|gpu/...``) collapses into one path.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core import autograd
from ..core.dtype import convert_dtype, default_float_dtype
from ..core.tensor import Tensor, to_tensor  # re-export to_tensor


def _dt(dtype, default=None):
    d = convert_dtype(dtype)
    if d is None:
        d = default if default is not None else default_float_dtype()
    return d


def zeros(shape, dtype=None) -> Tensor:
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None) -> Tensor:
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None) -> Tensor:
    if isinstance(fill_value, Tensor):
        fill_value = fill_value._value
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None) -> Tensor:
    return zeros(shape, dtype)


def zeros_like(x, dtype=None) -> Tensor:
    return autograd.apply_op("zeros_like", lambda v: jnp.zeros_like(v, dtype=convert_dtype(dtype)), [x])


def ones_like(x, dtype=None) -> Tensor:
    return autograd.apply_op("ones_like", lambda v: jnp.ones_like(v, dtype=convert_dtype(dtype)), [x])


def full_like(x, fill_value, dtype=None) -> Tensor:
    return autograd.apply_op(
        "full_like", lambda v: jnp.full_like(v, fill_value, dtype=convert_dtype(dtype)), [x])


def empty_like(x, dtype=None) -> Tensor:
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None) -> Tensor:
    if end is None:
        start, end = 0, start
    for v in (start, end, step):
        if isinstance(v, Tensor):
            raise TypeError("arange bounds must be python numbers (static shapes on TPU)")
    d = convert_dtype(dtype)
    if d is None:
        # Default int dtype is int32: TPU-native (int64 requires x64 mode and
        # is slow on the VPU); the reference defaults to int64 on CPU/GPU.
        d = (default_float_dtype()
             if any(isinstance(v, float) for v in (start, end, step)) else jnp.int32)
    return Tensor(jnp.arange(start, end, step, dtype=d))


def linspace(start, stop, num, dtype=None) -> Tensor:
    return Tensor(jnp.linspace(float(start), float(stop), int(num), dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None) -> Tensor:
    return Tensor(jnp.logspace(float(start), float(stop), int(num), base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None) -> Tensor:
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0) -> Tensor:
    def fn(v):
        if v.ndim == 1 and padding_value != 0:
            d = jnp.diag(v, k=offset)
            mask = jnp.eye(d.shape[0], d.shape[1], k=offset, dtype=bool)
            return jnp.where(mask, d, jnp.asarray(padding_value, d.dtype))
        return jnp.diag(v, k=offset)
    return autograd.apply_op("diag", fn, [x])


def diagflat(x, offset=0) -> Tensor:
    return autograd.apply_op("diagflat", lambda v: jnp.diagflat(v, k=offset), [x])


def tril(x, diagonal=0) -> Tensor:
    return autograd.apply_op("tril", lambda v: jnp.tril(v, k=diagonal), [x])


def triu(x, diagonal=0) -> Tensor:
    return autograd.apply_op("triu", lambda v: jnp.triu(v, k=diagonal), [x])


def meshgrid(*args):
    arrs = [a._value if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
    return [Tensor(m) for m in jnp.meshgrid(*arrs, indexing="ij")]


def assign(x, output=None) -> Tensor:
    src = x._value if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))
    if output is not None:
        output._set_value(src)
        return output
    return Tensor(src)


def clone(x) -> Tensor:
    return x.clone()


def numel(x) -> Tensor:
    return Tensor(jnp.asarray(x.size, jnp.int32))


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._value))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)
