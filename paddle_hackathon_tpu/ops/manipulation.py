"""Shape / layout manipulation ops (ref ``python/paddle/tensor/manipulation.py``).

All shapes are static — a deliberate TPU/XLA constraint: the reference permits
dynamic shapes per-op; here anything shape-like must be concrete Python ints so
jit traces stay re-usable (SURVEY §7 design stance).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import autograd
from ..core.autograd import apply_op
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor

_py_slice = slice  # the `slice` op below shadows the builtin


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _ints(seq):
    if isinstance(seq, Tensor):
        seq = np.asarray(seq._value).tolist()
    if isinstance(seq, (int, np.integer)):
        return (int(seq),)
    return tuple(int(s._value) if isinstance(s, Tensor) else int(s) for s in seq)


def reshape(x, shape, name=None):
    return apply_op("reshape", lambda v: v.reshape(_ints(shape)), [_t(x)])


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def fn(v):
        nd = v.ndim
        s, e = start_axis % nd, stop_axis % nd
        new_shape = v.shape[:s] + (-1,) + v.shape[e + 1:]
        return v.reshape(new_shape)
    return apply_op("flatten", fn, [_t(x)])


def transpose(x, perm, name=None):
    return apply_op("transpose", lambda v: jnp.transpose(v, _ints(perm)), [_t(x)])


def t(x, name=None):
    return apply_op("t", lambda v: v.T, [_t(x)])


def moveaxis(x, source, destination, name=None):
    return apply_op("moveaxis",
                    lambda v: jnp.moveaxis(v, _ints(source), _ints(destination)), [_t(x)])


def swapaxes(x, axis0, axis1, name=None):
    return apply_op("swapaxes", lambda v: jnp.swapaxes(v, int(axis0), int(axis1)), [_t(x)])


def squeeze(x, axis=None, name=None):
    def fn(v):
        if axis is None:
            return jnp.squeeze(v)
        axes = _ints(axis if isinstance(axis, (list, tuple)) else [axis])
        axes = tuple(a for a in axes if v.shape[a] == 1)
        return jnp.squeeze(v, axis=axes) if axes else v
    return apply_op("squeeze", fn, [_t(x)])


def unsqueeze(x, axis, name=None):
    axes = _ints(axis if isinstance(axis, (list, tuple, Tensor)) else [axis])
    return apply_op("unsqueeze", lambda v: jnp.expand_dims(v, axes), [_t(x)])


def concat(x, axis=0, name=None):
    tensors = [_t(v) for v in x]
    ax = int(axis._value) if isinstance(axis, Tensor) else int(axis)
    return apply_op("concat", lambda *vs: jnp.concatenate(vs, axis=ax), tensors)


def stack(x, axis=0, name=None):
    tensors = [_t(v) for v in x]
    return apply_op("stack", lambda *vs: jnp.stack(vs, axis=int(axis)), tensors)


def unstack(x, axis=0, num=None, name=None):
    x = _t(x)
    n = num if num is not None else x.shape[axis]
    outs = apply_op(
        "unstack",
        lambda v: tuple(jnp.moveaxis(v, axis, 0)[i] for i in range(n)),
        [x])
    return list(outs)


def unbind(input, axis=0):  # noqa: A002
    return unstack(input, axis=axis)


def split(x, num_or_sections, axis=0, name=None):
    x = _t(x)
    ax = int(axis._value) if isinstance(axis, Tensor) else int(axis)
    dim = x.shape[ax]
    if isinstance(num_or_sections, int):
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        if any(s == -1 for s in sizes):
            rest = dim - sum(s for s in sizes if s != -1)
            sizes = [rest if s == -1 else s for s in sizes]
    offsets = np.cumsum([0] + sizes)[:-1]

    def fn(v):
        return tuple(jax.lax.dynamic_slice_in_dim(v, int(o), int(s), axis=ax)
                     for o, s in zip(offsets, sizes))

    return list(apply_op("split", fn, [x]))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis=axis)


def tile(x, repeat_times, name=None):
    return apply_op("tile", lambda v: jnp.tile(v, _ints(repeat_times)), [_t(x)])


def expand(x, shape, name=None):
    tgt = _ints(shape)

    def fn(v):
        full = list(tgt)
        off = len(full) - v.ndim
        for i in range(v.ndim):
            if full[off + i] == -1:
                full[off + i] = v.shape[i]
        return jnp.broadcast_to(v, tuple(full))
    return apply_op("expand", fn, [_t(x)])


def expand_as(x, y, name=None):
    return apply_op("expand_as", lambda v, w: jnp.broadcast_to(v, w.shape), [_t(x), _t(y)])


def broadcast_to(x, shape, name=None):
    return apply_op("broadcast_to", lambda v: jnp.broadcast_to(v, _ints(shape)), [_t(x)])


def broadcast_tensors(inputs, name=None):
    tensors = [_t(v) for v in inputs]
    outs = apply_op("broadcast_tensors",
                    lambda *vs: tuple(jnp.broadcast_arrays(*vs)), tensors)
    return list(outs)


def flip(x, axis, name=None):
    return apply_op("flip", lambda v: jnp.flip(v, _ints(axis)), [_t(x)])


def roll(x, shifts, axis=None, name=None):
    def fn(v):
        if axis is None:
            return jnp.roll(v.reshape(-1), _ints(shifts)[0]).reshape(v.shape)
        return jnp.roll(v, _ints(shifts), _ints(axis))
    return apply_op("roll", fn, [_t(x)])


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op("rot90", lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), [_t(x)])


def cast(x, dtype):
    d = convert_dtype(dtype)
    return apply_op("cast", lambda v: v.astype(d), [_t(x)])


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    """paddle.nn.functional.pad-compatible core (ref phi PadKernel).

    ``pad`` is the flat paddle format: either len==2*ndim covering all dims
    (pairs from the last dim backward is numpy order here: we use per-dim
    pairs in order), or len==2/4 applied to the trailing spatial dims of the
    given data_format.
    """
    x = _t(x)
    nd = x.ndim
    p = _ints(pad)
    if len(p) == 2 * nd:
        width = [(p[2 * i], p[2 * i + 1]) for i in range(nd)]
    else:
        # spatial padding: paddle orders [left, right, top, bottom,...]
        # applied to W (last), H, ... of the format's spatial dims.
        width = [(0, 0)] * nd
        spatial = []
        if data_format.endswith("C"):  # NHWC / NLC / NDHWC
            spatial = list(range(1, nd - 1))
        else:  # NCHW / NCL / NCDHW
            spatial = list(range(2, nd))
        pairs = [(p[i], p[i + 1]) for i in range(0, len(p), 2)]
        # paddle lists pads from the last spatial dim backward
        for dim, pair in zip(reversed(spatial), pairs):
            width[dim] = pair
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    kwargs = {"constant_values": value} if jmode == "constant" else {}
    return apply_op("pad", lambda v: jnp.pad(v, width, mode=jmode, **kwargs), [x])


# -- gather / scatter -------------------------------------------------------
def gather(x, index, axis=0, name=None):
    ax = int(axis._value) if isinstance(axis, Tensor) else int(axis)
    return apply_op("gather",
                    lambda v, i: jnp.take(v, i.reshape(-1), axis=ax),
                    [_t(x), _t(index)])


def gather_nd(x, index, name=None):
    def fn(v, idx):
        k = idx.shape[-1]
        flat_idx = tuple(idx[..., i] for i in range(k))
        return v[flat_idx]
    return apply_op("gather_nd", fn, [_t(x), _t(index)])


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply_op("take_along_axis",
                    lambda v, i: jnp.take_along_axis(v, i, axis=axis),
                    [_t(arr), _t(indices)])


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):  # noqa: A002
    def fn(v, i, val):
        val = jnp.broadcast_to(jnp.asarray(val, v.dtype), i.shape)
        if reduce == "assign":
            return jnp.put_along_axis(v, i, val, axis=axis, inplace=False)
        mode = {"add": "add", "mul": "multiply", "multiply": "multiply"}[reduce]
        dim_idx = [jnp.arange(s).reshape([-1 if d == k else 1 for k in range(i.ndim)])
                   for d, s in enumerate(i.shape)]
        full = tuple(i if d == axis % v.ndim else jnp.broadcast_to(dim_idx[d], i.shape)
                     for d in range(v.ndim))
        at = v.at[full]
        return at.add(val) if mode == "add" else at.multiply(val)
    vt = values if isinstance(values, Tensor) else Tensor(jnp.asarray(values))
    return apply_op("put_along_axis", fn, [_t(arr), _t(indices), vt])


def scatter(x, index, updates, overwrite=True, name=None):
    def fn(v, i, u):
        i = i.reshape(-1)
        if overwrite:
            return v.at[i].set(u)
        base = v.at[i].set(jnp.zeros_like(u))
        return base.at[i].add(u)
    return apply_op("scatter", fn, [_t(x), _t(index), _t(updates)])


def scatter_nd_add(x, index, updates, name=None):
    def fn(v, i, u):
        k = i.shape[-1]
        idx = tuple(i[..., d] for d in range(k))
        return v.at[idx].add(u)
    return apply_op("scatter_nd_add", fn, [_t(x), _t(index), _t(updates)])


def scatter_nd(index, updates, shape, name=None):
    zeros_shape = _ints(shape)

    def fn(i, u):
        k = i.shape[-1]
        idx = tuple(i[..., d] for d in range(k))
        return jnp.zeros(zeros_shape, u.dtype).at[idx].add(u)
    return apply_op("scatter_nd", fn, [_t(index), _t(updates)])


def index_select(x, index, axis=0, name=None):
    return apply_op("index_select",
                    lambda v, i: jnp.take(v, i.reshape(-1), axis=axis),
                    [_t(x), _t(index)])


def index_sample(x, index):
    return apply_op("index_sample",
                    lambda v, i: jnp.take_along_axis(v, i, axis=1),
                    [_t(x), _t(index)])


def index_add(x, index, axis, value, name=None):
    def fn(v, i, u):
        idx = [_py_slice(None)] * v.ndim
        idx[axis] = i.reshape(-1)
        return v.at[tuple(idx)].add(u)
    return apply_op("index_add", fn, [_t(x), _t(index), _t(value)])


def index_put(x, indices, value, accumulate=False, name=None):
    def fn(v, u, *idx):
        at = v.at[tuple(idx)]
        return at.add(u) if accumulate else at.set(u)
    idx_t = [_t(i) for i in indices]
    return apply_op("index_put", fn, [_t(x), _t(value)] + idx_t)


def masked_select(x, mask, name=None):
    # Dynamic output shape — host-side op, not jittable (XLA static shapes).
    x, mask = _t(x), _t(mask)
    return Tensor(x._value[np.asarray(mask._value)])


def masked_fill(x, mask, value, name=None):
    v = value._value if isinstance(value, Tensor) else value
    return apply_op("masked_fill",
                    lambda a, m: jnp.where(m, jnp.asarray(v, a.dtype), a),
                    [_t(x), _t(mask)])


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply_op("where",
                    lambda c, a, b: jnp.where(c, a, b),
                    [_t(condition), _t(x), _t(y)])


def nonzero(x, as_tuple=False):
    # Dynamic output shape — host-side (ref WhereIndexKernel).
    arr = np.asarray(_t(x)._value)
    idx = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i)) for i in idx)
    return Tensor(jnp.asarray(np.stack(idx, axis=1)))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    # Dynamic output shape — host-side (ref UniqueKernel).
    arr = np.asarray(_t(x)._value)
    res = np.unique(arr, return_index=return_index,
                    return_inverse=return_inverse, return_counts=return_counts,
                    axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    arr = np.asarray(_t(x)._value)
    if axis is None:
        arr = arr.reshape(-1)
    change = np.ones(arr.shape[0], dtype=bool)
    change[1:] = np.any(
        (arr[1:] != arr[:-1]).reshape(arr.shape[0] - 1, -1), axis=1)
    starts = np.nonzero(change)[0]
    out = [Tensor(jnp.asarray(arr[starts]))]
    if return_inverse:
        out.append(Tensor(jnp.asarray(np.cumsum(change) - 1)))
    if return_counts:
        counts = np.diff(np.append(starts, arr.shape[0]))
        out.append(Tensor(jnp.asarray(counts)))
    return out[0] if len(out) == 1 else tuple(out)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        repeats = np.asarray(repeats._value).tolist()

    def fn(v):
        return jnp.repeat(v, repeats, axis=axis,
                          total_repeat_length=None if isinstance(repeats, int)
                          else int(np.sum(repeats)))
    return apply_op("repeat_interleave", fn, [_t(x)])


def strided_slice(x, axes, starts, ends, strides, name=None):
    def fn(v):
        idx = [_py_slice(None)] * v.ndim
        for ax, s, e, st in zip(_ints(axes), _ints(starts), _ints(ends), _ints(strides)):
            idx[ax] = _py_slice(s, e, st)
        return v[tuple(idx)]
    return apply_op("strided_slice", fn, [_t(x)])


def slice(x, axes, starts, ends, name=None):  # noqa: A001
    return strided_slice(x, axes, starts, ends, [1] * len(_ints(axes)))


def crop(x, shape=None, offsets=None, name=None):
    x = _t(x)
    shp = _ints(shape) if shape is not None else tuple(x.shape)
    offs = _ints(offsets) if offsets is not None else (0,) * x.ndim
    return apply_op("crop",
                    lambda v: jax.lax.dynamic_slice(v, offs, shp), [x])


def as_complex(x, name=None):
    return apply_op("as_complex", lambda v: jax.lax.complex(v[..., 0], v[..., 1]), [_t(x)])


def as_real(x, name=None):
    return apply_op("as_real",
                    lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1), [_t(x)])


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    d = convert_dtype(shape_or_dtype)
    return apply_op("view_dtype", lambda v: v.view(d), [_t(x)])


def atleast_1d(*inputs):
    outs = [apply_op("atleast_1d", jnp.atleast_1d, [_t(x)]) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs):
    outs = [apply_op("atleast_2d", jnp.atleast_2d, [_t(x)]) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs):
    outs = [apply_op("atleast_3d", jnp.atleast_3d, [_t(x)]) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):  # noqa: A002
    def fn(v):
        shard_size = (index_num + nshards - 1) // nshards
        lo, hi = shard_id * shard_size, (shard_id + 1) * shard_size
        in_shard = (v >= lo) & (v < hi)
        return jnp.where(in_shard, v - lo, ignore_value)
    with autograd.no_grad():
        return apply_op("shard_index", fn, [_t(input)])


def reverse(x, axis, name=None):
    """Legacy paddle.reverse (= flip; ref reverse_op)."""
    ax = [axis] if isinstance(axis, int) else list(axis)
    return apply_op("reverse", lambda v: jnp.flip(v, ax), [_t(x)])


def tril_indices(row, col=None, offset=0, dtype="int64", name=None):
    from ..core.dtype import convert_dtype
    from ..core import autograd as _ag
    col = row if col is None else col
    with _ag.no_grad():
        r, c = jnp.tril_indices(row, offset, col)
        return Tensor(jnp.stack([r, c]).astype(convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64", name=None):
    from ..core.dtype import convert_dtype
    from ..core import autograd as _ag
    col = row if col is None else col
    with _ag.no_grad():
        r, c = jnp.triu_indices(row, offset, col)
        return Tensor(jnp.stack([r, c]).astype(convert_dtype(dtype)))
