"""Random ops (ref ``python/paddle/tensor/random.py``).

Stateful API over JAX's functional PRNG: each call splits a subkey from the
global generator (``core.random``), or from the active :func:`rng_scope` key
when tracing (so compiled programs get fresh randomness per step via an
explicit key input — the TPU-native replacement for the reference's per-device
curand states).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import random as core_random
from ..core.autograd import apply_op
from ..core.dtype import convert_dtype, default_float_dtype
from ..core.tensor import Tensor
from .creation import _shape


def _dt(dtype):
    d = convert_dtype(dtype)
    return d if d is not None else default_float_dtype()


def rand(shape, dtype=None, name=None) -> Tensor:
    key = core_random.split_key()
    return Tensor(jax.random.uniform(key, _shape(shape), _dt(dtype)))


def randn(shape, dtype=None, name=None) -> Tensor:
    key = core_random.split_key()
    return Tensor(jax.random.normal(key, _shape(shape), _dt(dtype)))


def standard_normal(shape, dtype=None, name=None) -> Tensor:
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None) -> Tensor:
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else mean
        s = std._value if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(
            jnp.shape(m), jnp.shape(s)) if shape is None else _shape(shape)
        key = core_random.split_key()
        return Tensor(jax.random.normal(key, shp, default_float_dtype()) * s + m)
    key = core_random.split_key()
    return Tensor(
        jax.random.normal(key, _shape(shape or [1]), default_float_dtype()) * std + mean)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None) -> Tensor:  # noqa: A002
    key = jax.random.key(seed) if seed else core_random.split_key()
    return Tensor(jax.random.uniform(
        key, _shape(shape), _dt(dtype), minval=min, maxval=max))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None) -> Tensor:
    if high is None:
        low, high = 0, low
    key = core_random.split_key()
    d = convert_dtype(dtype)
    d = jnp.int32 if d == jnp.int64 else d  # int32 is the TPU-native int
    return Tensor(jax.random.randint(key, _shape(shape), low, high, dtype=d))


def randint_like(x, low=0, high=None, dtype=None, name=None) -> Tensor:
    shape = x.shape if isinstance(x, Tensor) else jnp.shape(x)
    return randint(low, high, shape, dtype or "int32")


def randperm(n, dtype="int64", name=None) -> Tensor:
    key = core_random.split_key()
    d = convert_dtype(dtype)
    d = jnp.int32 if d == jnp.int64 else d
    return Tensor(jax.random.permutation(key, n).astype(d))


def bernoulli(x, name=None) -> Tensor:
    key = core_random.split_key()
    return apply_op(
        "bernoulli",
        lambda p: jax.random.bernoulli(key, p).astype(p.dtype), [x])


def poisson(x, name=None) -> Tensor:
    key = core_random.split_key()
    return apply_op("poisson",
                    lambda lam: jax.random.poisson(key, lam).astype(lam.dtype), [x])


def multinomial(x, num_samples=1, replacement=False, name=None) -> Tensor:
    key = core_random.split_key()

    def fn(p):
        logits = jnp.log(jnp.maximum(p, 1e-30))
        if replacement:
            return jax.random.categorical(key, logits, axis=-1,
                                          shape=p.shape[:-1] + (num_samples,))
        # without replacement: Gumbel top-k trick
        g = jax.random.gumbel(key, p.shape, p.dtype)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx
    t = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    out = apply_op("multinomial", fn, [t])
    return Tensor(out._value.astype(jnp.int32))


def exponential_(x, lam=1.0, name=None) -> Tensor:
    key = core_random.split_key()
    x._set_value(jax.random.exponential(key, tuple(x.shape), x.dtype) / lam)
    return x


def normal_(x, mean=0.0, std=1.0, name=None) -> Tensor:
    key = core_random.split_key()
    x._set_value(jax.random.normal(key, tuple(x.shape), x.dtype) * std + mean)
    return x


def uniform_(x, min=-1.0, max=1.0, name=None) -> Tensor:  # noqa: A002
    key = core_random.split_key()
    x._set_value(jax.random.uniform(key, tuple(x.shape), x.dtype, min, max))
    return x
