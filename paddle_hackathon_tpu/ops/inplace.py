"""In-place op variants (``add_``, ``reshape_``, ``tanh_``...).

Ref: the reference generates ``<op>_`` inplace entry points from
``legacy_api.yaml`` (``inplace : (x -> out)`` annotations, e.g. adam_
``legacy_api.yaml:51``) and monkey-patches them onto Tensor
(``fluid/dygraph/varbase_patch_methods.py``).

Here each inplace op runs the taped out-of-place computation and rebinds the
tensor's identity to the result (value + grad node), the same tape-consistent
rebind ``Tensor.__setitem__`` uses. Gradients therefore flow exactly as for
the out-of-place op, matching paddle's inplace autograd semantics.
"""

from __future__ import annotations

from ..core.tensor import Tensor

_INPLACE_SPECS = [
    # (inplace name, out-of-place op name in the ops namespace)
    ("add_", "add"), ("subtract_", "subtract"), ("multiply_", "multiply"),
    ("divide_", "divide"), ("remainder_", "remainder"),
    ("clip_", "clip"), ("scale_", "scale"), ("lerp_", "lerp"),
    ("pow_", "pow"),
    ("exp_", "exp"), ("sqrt_", "sqrt"), ("rsqrt_", "rsqrt"),
    ("ceil_", "ceil"), ("floor_", "floor"), ("round_", "round"),
    ("reciprocal_", "reciprocal"), ("erfinv_", "erfinv"),
    ("tanh_", "tanh"), ("sigmoid_", "sigmoid"), ("abs_", "abs"),
    ("neg_", "neg"), ("sign_", "sign"), ("trunc_", "trunc"),
    ("frac_", "frac"),
    ("reshape_", "reshape"), ("squeeze_", "squeeze"),
    ("unsqueeze_", "unsqueeze"), ("flatten_", "flatten"),
    ("scatter_", "scatter"), ("put_along_axis_", "put_along_axis"),
    ("gather_", "gather"), ("cast_", "cast"),
]


def _rebind(x: Tensor, out: Tensor) -> Tensor:
    x._value = out._value
    x._grad_node = out._grad_node
    x._out_idx = out._out_idx
    x.stop_gradient = out.stop_gradient
    return x


def _make_inplace(base):
    def op(x, *args, **kwargs):
        return _rebind(x, base(x, *args, **kwargs))
    op.__name__ = base.__name__ + "_"
    op.__qualname__ = op.__name__
    op.__doc__ = (f"In-place variant of ``{base.__name__}`` (tape-consistent "
                  "rebind; ref yaml `inplace:` entries).")
    return op


def install(namespace: dict) -> dict:
    """Build every inplace op from ``namespace`` (the ops module dict) and
    patch them onto Tensor. Returns {name: fn} for re-export."""
    built = {}
    for iname, oname in _INPLACE_SPECS:
        base = namespace.get(oname)
        if base is None:
            continue
        fn = _make_inplace(base)
        fn.__name__ = iname
        fn.__qualname__ = iname
        built[iname] = fn
        setattr(Tensor, iname, fn)
    return built
