"""Search / sort ops (ref ``python/paddle/tensor/search.py``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import autograd
from ..core.autograd import apply_op
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    with autograd.no_grad():
        def fn(v):
            out = jnp.argmax(v if axis is not None else v.reshape(-1),
                             axis=axis if axis is not None else 0)
            if keepdim and axis is not None:
                out = jnp.expand_dims(out, axis)
            return out.astype(jnp.int32)
        return apply_op("argmax", fn, [_t(x)])


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    with autograd.no_grad():
        def fn(v):
            out = jnp.argmin(v if axis is not None else v.reshape(-1),
                             axis=axis if axis is not None else 0)
            if keepdim and axis is not None:
                out = jnp.expand_dims(out, axis)
            return out.astype(jnp.int32)
        return apply_op("argmin", fn, [_t(x)])


def argsort(x, axis=-1, descending=False, stable=True, name=None):
    with autograd.no_grad():
        def fn(v):
            idx = jnp.argsort(v, axis=axis, stable=stable,
                              descending=descending)
            return idx.astype(jnp.int32)
        return apply_op("argsort", fn, [_t(x)])


def sort(x, axis=-1, descending=False, stable=True, name=None):
    def fn(v):
        out = jnp.sort(v, axis=axis, stable=stable, descending=descending)
        return out
    return apply_op("sort", fn, [_t(x)])


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):  # noqa: A002
    """Top-k (ref phi TopkKernel) — lowered to lax.top_k on the last axis."""
    if isinstance(k, Tensor):
        k = int(k.item())

    def fn(v):
        ax = axis % v.ndim
        moved = jnp.moveaxis(v, ax, -1)
        vals, idx = jax.lax.top_k(moved if largest else -moved, k)
        if not largest:
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax),
                jnp.moveaxis(idx, -1, ax).astype(jnp.int32))
    vals, idx = apply_op("topk", fn, [_t(x)])
    idx.stop_gradient = True
    return vals, idx


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def fn(v):
        ax = axis % v.ndim
        moved = jnp.moveaxis(v, ax, -1)
        sorted_v = jnp.sort(moved, axis=-1)
        sorted_i = jnp.argsort(moved, axis=-1)
        vals = sorted_v[..., k - 1]
        idx = sorted_i[..., k - 1]
        if keepdim:
            vals, idx = jnp.expand_dims(vals, ax), jnp.expand_dims(idx, ax)
        return vals, idx.astype(jnp.int32)
    vals, idx = apply_op("kthvalue", fn, [_t(x)])
    idx.stop_gradient = True
    return vals, idx


def mode(x, axis=-1, keepdim=False, name=None):
    def fn(v):
        ax = axis % v.ndim
        moved = jnp.moveaxis(v, ax, -1)
        sorted_v = jnp.sort(moved, axis=-1)
        n = sorted_v.shape[-1]
        runs = jnp.sum(sorted_v[..., :, None] == sorted_v[..., None, :], axis=-1)
        best = jnp.argmax(runs, axis=-1)
        vals = jnp.take_along_axis(sorted_v, best[..., None], axis=-1)[..., 0]
        idx = jnp.argmax(moved == vals[..., None], axis=-1)
        if keepdim:
            vals, idx = jnp.expand_dims(vals, ax), jnp.expand_dims(idx, ax)
        return vals, idx.astype(jnp.int32)
    vals, idx = apply_op("mode", fn, [_t(x)])
    idx.stop_gradient = True
    return vals, idx


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    with autograd.no_grad():
        def fn(seq, v):
            side = "right" if right else "left"
            if seq.ndim == 1:
                out = jnp.searchsorted(seq, v, side=side)
            else:
                out = jax.vmap(lambda s, q: jnp.searchsorted(s, q, side=side))(
                    seq.reshape(-1, seq.shape[-1]), v.reshape(-1, v.shape[-1])
                ).reshape(v.shape)
            return out.astype(jnp.int32 if out_int32 else jnp.int32)
        return apply_op("searchsorted", fn, [_t(sorted_sequence), _t(values)])


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def histogram(input, bins=100, min=0, max=0, name=None):  # noqa: A002
    with autograd.no_grad():
        def fn(v):
            lo, hi = (min, max) if (min != 0 or max != 0) else (v.min(), v.max())
            h, _ = jnp.histogram(v, bins=bins, range=(lo, hi))
            return h.astype(jnp.int32)
        return apply_op("histogram", fn, [_t(input)])


def bincount(x, weights=None, minlength=0, name=None):
    with autograd.no_grad():
        arr = _t(x)
        n = int(max(int(jnp.max(arr._value)) + 1 if arr.size else 1, minlength))

        def fn(v, *w):
            return jnp.bincount(v.reshape(-1),
                                weights=w[0].reshape(-1) if w else None,
                                minlength=n, length=n)
        args = [arr] + ([_t(weights)] if weights is not None else [])
        return apply_op("bincount", fn, args)
