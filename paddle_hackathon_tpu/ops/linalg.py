"""Linear algebra ops (ref ``python/paddle/tensor/linalg.py``; kernels ref
``paddle/phi/kernels/matmul_kernel.h:24`` and ``phi/kernels/*/cholesky_*`` etc.).

matmul is THE op on TPU — it is lowered straight to an MXU dot_general. All
other decompositions ride jax.numpy.linalg (XLA custom calls on TPU).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.autograd import apply_op
from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    """MXU matmul (ref ``phi::MatmulKernel`` ``matmul_kernel.h:24``).

    bf16/f32 inputs hit the systolic array directly; the transpose flags fold
    into dot_general dimension numbers (no materialised transpose).
    """
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return apply_op("matmul", fn, [_t(x), _t(y)])


def mm(input, mat2, name=None):  # noqa: A002
    return matmul(input, mat2)


def bmm(x, y, name=None):
    return matmul(x, y)


def dot(x, y, name=None):
    def fn(a, b):
        return jnp.sum(a * b, axis=-1)
    return apply_op("dot", fn, [_t(x), _t(y)])


def mv(x, vec, name=None):
    return apply_op("mv", lambda a, v: a @ v, [_t(x), _t(vec)])


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def fn(v):
        if axis is None and p in ("fro", 2):
            return jnp.sqrt(jnp.sum(jnp.square(v)))
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if p == "fro":
            return jnp.sqrt(jnp.sum(jnp.square(v), axis=ax, keepdims=keepdim))
        if p == float("inf"):
            return jnp.max(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((v != 0).astype(v.dtype), axis=ax, keepdims=keepdim)
        return jnp.sum(jnp.abs(v) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)
    return apply_op("p_norm", fn, [_t(x)])


def dist(x, y, p=2, name=None):
    return norm(x - y, p=float(p) if p not in ("fro",) else p)


def cross(x, y, axis=9, name=None):
    def fn(a, b):
        ax = axis if axis != 9 else next(
            (i for i, s in enumerate(a.shape) if s == 3), -1)
        return jnp.cross(a, b, axis=ax)
    return apply_op("cross", fn, [_t(x), _t(y)])


def einsum(equation, *operands):
    tensors = [_t(o) for o in operands]
    return apply_op("einsum",
                    lambda *vs: jnp.einsum(equation, *vs), tensors)


def cholesky(x, upper=False, name=None):
    def fn(v):
        c = jnp.linalg.cholesky(v)
        return jnp.swapaxes(c, -1, -2) if upper else c
    return apply_op("cholesky", fn, [_t(x)])


def cholesky_solve(x, y, upper=False, name=None):
    def fn(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)
    return apply_op("cholesky_solve", fn, [_t(x), _t(y)])


def qr(x, mode="reduced", name=None):
    outs = apply_op("qr", lambda v: tuple(jnp.linalg.qr(v, mode=mode)), [_t(x)])
    return outs


def svd(x, full_matrices=False, name=None):
    return apply_op("svd",
                    lambda v: tuple(jnp.linalg.svd(v, full_matrices=full_matrices)),
                    [_t(x)])


def eig(x, name=None):
    # TPU lacks a nonsymmetric eig custom call; route through host CPU.
    import numpy as np
    w, v = np.linalg.eig(np.asarray(_t(x)._value))
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigh(x, UPLO="L", name=None):
    return apply_op("eigh", lambda v: tuple(jnp.linalg.eigh(v, UPLO=UPLO)), [_t(x)])


def eigvals(x, name=None):
    import numpy as np
    return Tensor(jnp.asarray(np.linalg.eigvals(np.asarray(_t(x)._value))))


def eigvalsh(x, UPLO="L", name=None):
    return apply_op("eigvalsh", lambda v: jnp.linalg.eigvalsh(v, UPLO=UPLO), [_t(x)])


def inverse(x, name=None):
    return apply_op("inverse", jnp.linalg.inv, [_t(x)])


inv = inverse


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op("pinv",
                    lambda v: jnp.linalg.pinv(v, rtol=rcond, hermitian=hermitian), [_t(x)])


def solve(x, y, name=None):
    return apply_op("solve", jnp.linalg.solve, [_t(x), _t(y)])


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply_op("triangular_solve", fn, [_t(x), _t(y)])


def lstsq(x, y, rcond=None, driver=None, name=None):
    outs = apply_op("lstsq",
                    lambda a, b: tuple(jnp.linalg.lstsq(a, b, rcond=rcond)),
                    [_t(x), _t(y)])
    return outs


def matrix_power(x, n, name=None):
    return apply_op("matrix_power", lambda v: jnp.linalg.matrix_power(v, n), [_t(x)])


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply_op("matrix_rank",
                    lambda v: jnp.linalg.matrix_rank(v, rtol=tol), [_t(x)])


def det(x, name=None):
    return apply_op("determinant", jnp.linalg.det, [_t(x)])


def slogdet(x, name=None):
    return apply_op("slogdet", lambda v: tuple(jnp.linalg.slogdet(v)), [_t(x)])


def multi_dot(x, name=None):
    tensors = [_t(v) for v in x]
    return apply_op("multi_dot", lambda *vs: jnp.linalg.multi_dot(vs), tensors)


def householder_product(x, tau, name=None):
    def fn(a, t):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)
        q = jnp.broadcast_to(eye, a.shape[:-2] + (m, m))
        for i in range(t.shape[-1]):
            v = jnp.concatenate(
                [jnp.zeros(a.shape[:-2] + (i,), a.dtype),
                 jnp.ones(a.shape[:-2] + (1,), a.dtype),
                 a[..., i + 1:, i]], axis=-1)
            ti = t[..., i:i + 1, None]
            h = jnp.eye(m, dtype=a.dtype) - ti * v[..., :, None] * v[..., None, :]
            q = q @ h
        return q[..., :, :n]
    return apply_op("householder_product", fn, [_t(x), _t(tau)])


def corrcoef(x, rowvar=True, name=None):
    return apply_op("corrcoef", lambda v: jnp.corrcoef(v, rowvar=rowvar), [_t(x)])


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply_op("cov",
                    lambda v: jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0), [_t(x)])


def cond(x, p=None, name=None):
    """Condition number (ref phi CondKernel). p in {None,'fro','nuc',1,-1,2,-2,inf,-inf}."""
    def fn(a):
        pp = 2 if p is None else p
        if pp in ("fro", "nuc") or isinstance(pp, (int, float)):
            if pp == "fro":
                return (jnp.linalg.norm(a, "fro", axis=(-2, -1))
                        * jnp.linalg.norm(jnp.linalg.inv(a), "fro", axis=(-2, -1)))
            if pp == "nuc":
                s = jnp.linalg.svd(a, compute_uv=False)
                si = jnp.linalg.svd(jnp.linalg.inv(a), compute_uv=False)
                return s.sum(-1) * si.sum(-1)
            if pp in (2, -2):
                s = jnp.linalg.svd(a, compute_uv=False)
                r = s[..., 0] / s[..., -1]
                return r if pp == 2 else 1.0 / r
            return (jnp.linalg.norm(a, pp, axis=(-2, -1))
                    * jnp.linalg.norm(jnp.linalg.inv(a), pp, axis=(-2, -1)))
        raise ValueError(f"unsupported p={p!r}")
    return apply_op("cond", fn, [_t(x)])


def lu(x, pivot=True, get_infos=False, name=None):
    """LU factorization packed as the reference returns it
    (ref phi LuKernel): (LU, pivots[, infos])."""
    import jax.scipy.linalg as jsl

    def fn(a):
        lu_, piv = jsl.lu_factor(a)
        return lu_, (piv + 1).astype(jnp.int32)  # 1-based like the reference
    out, piv = apply_op("lu", fn, [_t(x)], n_outputs=2)
    if get_infos:
        from ..core import autograd as _ag
        with _ag.no_grad():
            infos = Tensor(jnp.zeros(x._value.shape[:-2] or (1,), jnp.int32))
        return out, piv, infos
    return out, piv


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack lu()'s outputs into P, L, U (ref phi LuUnpackKernel)."""
    def fn(lu_, piv):
        m, n = lu_.shape[-2], lu_.shape[-1]
        k = min(m, n)
        L = jnp.tril(lu_[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_.dtype)
        U = jnp.triu(lu_[..., :k, :])

        # pivots (1-based sequential row swaps) -> permutation matrix,
        # vmapped over any batch dims
        def perm_mat(pv):
            perm = jnp.arange(m)
            for i in range(pv.shape[-1]):
                j = pv[i] - 1
                pi, pj = perm[i], perm[j]
                perm = perm.at[i].set(pj).at[j].set(pi)
            return jnp.eye(m, dtype=lu_.dtype)[perm].T

        pm = perm_mat
        for _ in range(piv.ndim - 1):
            pm = jax.vmap(pm)
        return pm(piv), L, U
    return apply_op("lu_unpack", fn, [_t(x), _t(y)], n_outputs=3)
