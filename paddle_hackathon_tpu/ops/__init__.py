"""Functional op library.

Equivalent of ``python/paddle/tensor/*`` plus the yaml-generated C++ API of the
reference (``paddle/phi/api/yaml/legacy_api.yaml`` → generated
``paddle::experimental::*``): here each op is a Python function that lowers to
a single jax/XLA composition, and a registry (``OP_TABLE``) records the op
surface the way the yaml does.

This module also monkey-patches the math methods onto ``Tensor``, mirroring
``fluid/dygraph/math_op_patch.py:66``.
"""

from . import creation, linalg, manipulation, math, random, search
from .creation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403

from ..core.tensor import Tensor

# Registry of every public op — the analog of the yaml op table
# (``legacy_api.yaml``), used by tests to assert surface coverage.
OP_TABLE = {}
for _mod in (creation, math, manipulation, linalg, random, search):
    for _name in dir(_mod):
        if _name.startswith("_"):
            continue
        _fn = getattr(_mod, _name)
        if callable(_fn) and getattr(_fn, "__module__", "").startswith(
                "paddle_hackathon_tpu.ops"):
            OP_TABLE.setdefault(_name, _fn)


def _patch_tensor_methods():
    """Attach op methods to Tensor (ref math_op_patch.py monkey-patching)."""
    methods = [
        # math
        "exp", "log", "log2", "log10", "log1p", "sqrt", "rsqrt", "abs",
        "sign", "floor", "ceil", "round", "trunc", "sin", "cos", "tan",
        "tanh", "sinh", "cosh", "asin", "acos", "atan", "reciprocal",
        "square", "erf", "erfinv", "add", "subtract", "multiply", "divide",
        "pow", "maximum", "minimum", "remainder", "mod", "floor_divide",
        "scale", "clip", "lerp", "isnan", "isinf", "isfinite", "isclose",
        "allclose", "equal_all", "logical_and", "logical_or", "logical_xor",
        "logical_not", "bitwise_and", "bitwise_or", "bitwise_xor",
        "bitwise_not", "equal", "not_equal", "less_than", "less_equal",
        "greater_than", "greater_equal", "nan_to_num",
        # reductions
        "sum", "mean", "prod", "max", "min", "amax", "amin", "all", "any",
        "std", "var", "median", "cumsum", "cumprod", "logsumexp", "trace",
        "count_nonzero",
        # manipulation
        "reshape", "flatten", "transpose", "t", "squeeze", "unsqueeze",
        "tile", "expand", "expand_as", "broadcast_to", "flip", "roll",
        "cast", "gather", "gather_nd", "take_along_axis", "put_along_axis",
        "scatter", "scatter_nd_add", "index_select", "index_sample",
        "index_add", "masked_select", "masked_fill", "where", "nonzero",
        "unique", "split", "chunk", "unbind", "repeat_interleave",
        "moveaxis", "swapaxes", "tril", "triu", "diag",
        "unstack", "strided_slice",
        # linalg
        "matmul", "mm", "bmm", "dot", "norm", "dist", "cross", "cholesky",
        "inverse", "solve", "matrix_power", "det", "qr", "svd",
        # search
        "argmax", "argmin", "argsort", "sort", "topk", "kthvalue", "mode",
        "bincount", "histogram",
        # random in-place
        "exponential_", "normal_", "uniform_",
    ]
    ns = {}
    for mod in (math, manipulation, linalg, search, creation, random):
        for name in dir(mod):
            if not name.startswith("_"):
                ns.setdefault(name, getattr(mod, name))
    for m in methods:
        fn = ns.get(m)
        if fn is not None and not hasattr(Tensor, m):
            setattr(Tensor, m, fn)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    import jax.numpy as jnp
    from ..core.autograd import apply_op
    return apply_op(
        "diagonal",
        lambda v: jnp.diagonal(v, offset=offset, axis1=axis1, axis2=axis2), [x])


OP_TABLE["diagonal"] = diagonal
_patch_tensor_methods()
Tensor.diagonal = diagonal

# In-place variants (<op>_) — built from the out-of-place table and patched
# onto Tensor (ref yaml `inplace:` annotations; varbase_patch_methods.py).
from . import inplace as _inplace_mod  # noqa: E402

_ns = {}
for _mod in (math, manipulation, linalg, search, creation, random):
    for _name in dir(_mod):
        if not _name.startswith("_"):
            _ns.setdefault(_name, getattr(_mod, _name))
for _name, _fn in _inplace_mod.install(_ns).items():
    globals()[_name] = _fn
    OP_TABLE.setdefault(_name, _fn)

for _name in ("cond", "lu", "lu_unpack", "tensordot", "logit", "stanh",
              "rad2deg", "deg2rad", "logcumsumexp", "renorm", "nanmedian",
              "nanquantile", "tolist", "is_complex", "is_integer",
              "is_floating_point", "is_empty", "rank", "increment"):
    _fn = globals().get(_name) or OP_TABLE.get(_name)
    if _fn is not None and not hasattr(Tensor, _name):
        setattr(Tensor, _name, _fn)
        OP_TABLE.setdefault(_name, _fn)
