"""Elementwise math + reduction ops (ref ``python/paddle/tensor/math.py``,
``python/paddle/tensor/stat.py``; kernels ref ``paddle/phi/kernels/*``).

Every op is a taped jax.numpy composition — XLA fuses chains of these into
single HBM-bandwidth-bound kernels, which is what the reference's
``ir/fusion_group`` NVRTC JIT pass does by hand.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import autograd
from ..core.autograd import apply_op
from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _unary(name, fn):
    def op(x, name=None):
        return apply_op(name_, fn, [_t(x)])
    name_ = name
    op.__name__ = name
    op.__qualname__ = name
    op.__doc__ = f"Elementwise {name} (ref phi::{name.capitalize()}Kernel)."
    return op


def _binary(name, fn):
    def op(x, y, name=None):
        return apply_op(name_, fn, [_t(x), _t(y)])
    name_ = name
    op.__name__ = name
    op.__qualname__ = name
    op.__doc__ = f"Elementwise {name} with numpy broadcasting."
    return op


# -- unary ------------------------------------------------------------------
exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", jax.lax.rsqrt)
abs = _unary("abs", jnp.abs)  # noqa: A001 - matches paddle.abs
sign = _unary("sign", jnp.sign)
floor = _unary("floor", jnp.floor)
ceil = _unary("ceil", jnp.ceil)
round = _unary("round", jnp.round)  # noqa: A001
trunc = _unary("trunc", jnp.trunc)
frac = _unary("frac", lambda x: x - jnp.trunc(x))
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
reciprocal = _unary("reciprocal", lambda x: 1.0 / x)
square = _unary("square", jnp.square)
neg = _unary("neg", jnp.negative)
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
digamma = _unary("digamma", jax.scipy.special.digamma)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
angle = _unary("angle", jnp.angle)
conj = _unary("conj", jnp.conj)
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)

# -- binary -----------------------------------------------------------------
add = _binary("add", jnp.add)
subtract = _binary("subtract", jnp.subtract)
multiply = _binary("multiply", jnp.multiply)
divide = _binary("divide", jnp.divide)
floor_divide = _binary("floor_divide", jnp.floor_divide)
remainder = _binary("remainder", jnp.remainder)
mod = remainder
floor_mod = remainder
pow = _binary("pow", jnp.power)  # noqa: A001
maximum = _binary("maximum", jnp.maximum)
minimum = _binary("minimum", jnp.minimum)
fmax = _binary("fmax", jnp.fmax)
fmin = _binary("fmin", jnp.fmin)
atan2 = _binary("atan2", jnp.arctan2)
logaddexp = _binary("logaddexp", jnp.logaddexp)
heaviside = _binary("heaviside", jnp.heaviside)
hypot = _binary("hypot", jnp.hypot)
gcd = _binary("gcd", jnp.gcd)
lcm = _binary("lcm", jnp.lcm)
kron = _binary("kron", jnp.kron)
inner = _binary("inner", jnp.inner)
outer = _binary("outer", jnp.outer)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """paddle.scale (ref phi ScaleKernel)."""
    def fn(v, s, b):
        return v * s + b if bias_after_scale else (v + b) * s
    out = apply_op("scale", lambda v: fn(v, scale, bias), [_t(x)])
    if act == "relu":
        return apply_op("relu", jax.nn.relu, [out])
    return out


def clip(x, min=None, max=None, name=None):  # noqa: A002
    lo = min._value if isinstance(min, Tensor) else min
    hi = max._value if isinstance(max, Tensor) else max
    return apply_op("clip", lambda v: jnp.clip(v, lo, hi), [_t(x)])


def lerp(x, y, weight, name=None):
    w = weight if isinstance(weight, Tensor) else Tensor(jnp.asarray(weight))
    return apply_op("lerp", lambda a, b, t: a + t * (b - a), [_t(x), _t(y), w])


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    return apply_op("addmm", lambda i, a, b: beta * i + alpha * (a @ b),
                    [_t(input), _t(x), _t(y)])


def multiplex(inputs, index, name=None):
    stacked = stack(inputs, axis=0)
    idx = index._value.reshape(-1)
    return apply_op("multiplex",
                    lambda s: s[idx, jnp.arange(s.shape[1])], [stacked])


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply_op("nan_to_num",
                    lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf, neginf=neginf),
                    [_t(x)])


def isnan(x, name=None):
    with autograd.no_grad():
        return apply_op("isnan", jnp.isnan, [_t(x)])


def isinf(x, name=None):
    with autograd.no_grad():
        return apply_op("isinf", jnp.isinf, [_t(x)])


def isfinite(x, name=None):
    with autograd.no_grad():
        return apply_op("isfinite", jnp.isfinite, [_t(x)])


def isclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False, name=None):
    with autograd.no_grad():
        return apply_op("isclose",
                        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                        [_t(x), _t(y)])


def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False, name=None):
    with autograd.no_grad():
        return apply_op("allclose",
                        lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                        [_t(x), _t(y)])


def equal_all(x, y, name=None):
    with autograd.no_grad():
        return apply_op("equal_all", lambda a, b: jnp.array_equal(a, b), [_t(x), _t(y)])


# -- logical ----------------------------------------------------------------
def _logical(name, fn):
    def op(x, y=None, out=None, name=None):
        with autograd.no_grad():
            if y is None:
                return apply_op(name_, fn, [_t(x)])
            return apply_op(name_, fn, [_t(x), _t(y)])
    name_ = name
    op.__name__ = name
    return op


logical_and = _logical("logical_and", jnp.logical_and)
logical_or = _logical("logical_or", jnp.logical_or)
logical_xor = _logical("logical_xor", jnp.logical_xor)
logical_not = _logical("logical_not", jnp.logical_not)
bitwise_and = _logical("bitwise_and", jnp.bitwise_and)
bitwise_or = _logical("bitwise_or", jnp.bitwise_or)
bitwise_xor = _logical("bitwise_xor", jnp.bitwise_xor)
bitwise_not = _logical("bitwise_not", jnp.bitwise_not)

equal = _logical("equal", jnp.equal)
not_equal = _logical("not_equal", jnp.not_equal)
less_than = _logical("less_than", jnp.less)
less_equal = _logical("less_equal", jnp.less_equal)
greater_than = _logical("greater_than", jnp.greater)
greater_equal = _logical("greater_equal", jnp.greater_equal)


# -- reductions -------------------------------------------------------------
def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _reduce(name, fn):
    def op(x, axis=None, keepdim=False, name=None):
        ax = _axis(axis)
        return apply_op(name_, lambda v: fn(v, axis=ax, keepdims=keepdim), [_t(x)])
    name_ = name
    op.__name__ = name
    op.__doc__ = f"Reduce-{name} (ref phi Reduce{name.capitalize()}Kernel)."
    return op


sum = _reduce("sum", jnp.sum)  # noqa: A001
mean = _reduce("mean", jnp.mean)
prod = _reduce("prod", jnp.prod)
max = _reduce("max", jnp.max)  # noqa: A001
min = _reduce("min", jnp.min)  # noqa: A001
amax = _reduce("amax", jnp.max)
amin = _reduce("amin", jnp.min)
nansum = _reduce("nansum", jnp.nansum)
nanmean = _reduce("nanmean", jnp.nanmean)
logsumexp = _reduce("logsumexp", jax.scipy.special.logsumexp)


def all(x, axis=None, keepdim=False, name=None):  # noqa: A001
    with autograd.no_grad():
        return apply_op("all", lambda v: jnp.all(v, axis=_axis(axis), keepdims=keepdim), [_t(x)])


def any(x, axis=None, keepdim=False, name=None):  # noqa: A001
    with autograd.no_grad():
        return apply_op("any", lambda v: jnp.any(v, axis=_axis(axis), keepdims=keepdim), [_t(x)])


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ddof = 1 if unbiased else 0
    return apply_op("std", lambda v: jnp.std(v, axis=_axis(axis), ddof=ddof, keepdims=keepdim), [_t(x)])


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ddof = 1 if unbiased else 0
    return apply_op("var", lambda v: jnp.var(v, axis=_axis(axis), ddof=ddof, keepdims=keepdim), [_t(x)])


def median(x, axis=None, keepdim=False, name=None):
    return apply_op("median", lambda v: jnp.median(v, axis=_axis(axis), keepdims=keepdim), [_t(x)])


def quantile(x, q, axis=None, keepdim=False, name=None):
    return apply_op("quantile",
                    lambda v: jnp.quantile(v, jnp.asarray(q), axis=_axis(axis), keepdims=keepdim),
                    [_t(x)])


def cumsum(x, axis=None, dtype=None, name=None):
    def fn(v):
        if axis is None:
            v = v.reshape(-1)
            return jnp.cumsum(v)
        return jnp.cumsum(v, axis=int(axis))
    return apply_op("cumsum", fn, [_t(x)])


def cumprod(x, dim=None, dtype=None, name=None):
    def fn(v):
        if dim is None:
            return jnp.cumprod(v.reshape(-1))
        return jnp.cumprod(v, axis=int(dim))
    return apply_op("cumprod", fn, [_t(x)])


def cummax(x, axis=None, name=None):
    def fn(v):
        a = 0 if axis is None else int(axis)
        vv = v.reshape(-1) if axis is None else v
        return jax.lax.associative_scan(jnp.maximum, vv, axis=a)
    return apply_op("cummax", fn, [_t(x)])


def cummin(x, axis=None, name=None):
    def fn(v):
        a = 0 if axis is None else int(axis)
        vv = v.reshape(-1) if axis is None else v
        return jax.lax.associative_scan(jnp.minimum, vv, axis=a)
    return apply_op("cummin", fn, [_t(x)])


def diff(x, n=1, axis=-1, name=None):
    return apply_op("diff", lambda v: jnp.diff(v, n=n, axis=axis), [_t(x)])


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("trace",
                    lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2), [_t(x)])


def count_nonzero(x, axis=None, keepdim=False, name=None):
    with autograd.no_grad():
        return apply_op("count_nonzero",
                        lambda v: jnp.count_nonzero(v, axis=_axis(axis), keepdims=keepdim), [_t(x)])


# needed by multiplex; full version lives in manipulation.py
def stack(x, axis=0, name=None):
    tensors = [_t(v) for v in x]
    return apply_op("stack", lambda *vs: jnp.stack(vs, axis=axis), tensors)


# -- round-out ops (reference top-level exports python/paddle/__init__.py) ---
def logit(x, eps=None, name=None):
    """log(x / (1-x)); inputs clamped to [eps, 1-eps] when eps given
    (ref phi LogitKernel)."""
    def fn(v):
        vv = jnp.clip(v, eps, 1.0 - eps) if eps is not None else v
        return jax.scipy.special.logit(vv)
    return apply_op("logit", fn, [_t(x)])



def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    """paddle.stanh: scale_b * tanh(scale_a * x) (ref phi StanhKernel)."""
    return apply_op("stanh", lambda v: scale_b * jnp.tanh(scale_a * v), [_t(x)])


rad2deg = _unary("rad2deg", jnp.rad2deg)
deg2rad = _unary("deg2rad", jnp.deg2rad)


def logcumsumexp(x, axis=None, dtype=None, name=None):
    def fn(v):
        vv = v.reshape(-1) if axis is None else v
        a = 0 if axis is None else int(axis)
        out = jax.lax.associative_scan(jnp.logaddexp, vv, axis=a)
        return out.astype(dtype) if dtype else out
    return apply_op("logcumsumexp", fn, [_t(x)])


def renorm(x, p, axis, max_norm, name=None):
    """Renormalize slices along ``axis`` to at most ``max_norm`` in p-norm
    (ref phi RenormKernel)."""
    def fn(v):
        red = tuple(i for i in range(v.ndim) if i != axis % v.ndim)
        norms = jnp.sum(jnp.abs(v) ** p, axis=red, keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return v * factor
    return apply_op("renorm", fn, [_t(x)])


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply_op("nanmedian",
                    lambda v: jnp.nanmedian(v, axis=_axis(axis), keepdims=keepdim),
                    [_t(x)])


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return apply_op("nanquantile",
                    lambda v: jnp.nanquantile(v, q, axis=_axis(axis), keepdims=keepdim),
                    [_t(x)])


def complex(real, imag, name=None):  # noqa: A001
    """Build a complex tensor from real/imaginary parts (ref phi ComplexKernel)."""
    return apply_op("complex", jax.lax.complex, [_t(real), _t(imag)])


def add_n(inputs, name=None):
    """Sum a list of tensors (ref sum_op / add_n)."""
    if isinstance(inputs, Tensor):
        return inputs
    tensors = [_t(v) for v in inputs]
    import functools
    return apply_op("add_n",
                    lambda *vs: functools.reduce(jnp.add, vs), tensors)


def increment(x, value=1.0, name=None):
    """In-place add a scalar (ref increment_op); returns ``x``."""
    x._set_value(x._value + value)
    return x


def tensordot(x, y, axes=2, name=None):
    def fn(a, b):
        ax = axes
        if isinstance(ax, Tensor):
            ax = ax.tolist()
        if isinstance(ax, (list, tuple)):
            ax = tuple(tuple(int(i) for i in (a_ if isinstance(a_, (list, tuple)) else [a_]))
                       for a_ in ax)
            if len(ax) == 1:
                ax = (ax[0], ax[0])
        return jnp.tensordot(a, b, axes=ax)
    return apply_op("tensordot", fn, [_t(x), _t(y)])


def broadcast_shape(x_shape, y_shape):
    import numpy as _np
    return list(_np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def rank(input, name=None):  # noqa: A002
    with autograd.no_grad():
        return Tensor(jnp.asarray(_t(input).ndim, jnp.int32))


def shape(input, name=None):  # noqa: A002
    with autograd.no_grad():
        return Tensor(jnp.asarray(_t(input).shape, jnp.int32))


def is_tensor(x):
    return isinstance(x, Tensor)


def is_complex(x):
    return jnp.issubdtype(_t(x)._value.dtype, jnp.complexfloating)


def is_integer(x):
    return jnp.issubdtype(_t(x)._value.dtype, jnp.integer)


def is_floating_point(x):
    return jnp.issubdtype(_t(x)._value.dtype, jnp.floating)


def is_empty(x, name=None):
    with autograd.no_grad():
        return Tensor(jnp.asarray(_t(x)._value.size == 0))


def tolist(x):
    return _t(x).tolist()
