"""paddle.onnx — ONNX export wrapper.

Ref ``python/paddle/onnx/export.py``: the reference delegates to the
external ``paddle2onnx`` converter. Here export goes StableHLO-first: the
model is traced and serialized with ``paddle.jit.save`` (the portable
deployment artifact of this framework); when the optional ``onnx`` package
is installed the traced program is additionally converted via jax's ONNX
bridge if available. Without it, a clear error explains the path.
"""

from .export import export  # noqa: F401

__all__ = ["export"]
