"""ONNX export (ref ``python/paddle/onnx/export.py``)."""

from __future__ import annotations

import importlib.util
import os


def _onnx_available() -> bool:
    return importlib.util.find_spec("onnx") is not None


def export(layer, path, input_spec=None, opset_version: int = 9,
           **configs):
    """Export ``layer`` for deployment (ref ``paddle.onnx.export``).

    Always writes the portable StableHLO jit artifact ``<path>.pdmodel``
    (loadable by ``paddle.inference`` anywhere, incl. non-TPU hosts). When
    the ``onnx`` package is importable, also writes ``<path>.onnx``;
    otherwise raises with instructions if the caller explicitly demanded
    onnx output via ``enable_onnx_checker``/``output_spec`` style configs.
    """
    from .. import jit

    saved = jit.save(layer, path, input_spec=input_spec, **{
        k: v for k, v in configs.items() if k in ("input_names",)})

    if configs.get("enable_onnx_checker"):
        # the caller demanded a checked .onnx file; conversion of the traced
        # program is not wired yet, so fail loudly rather than silently
        # returning only the StableHLO artifact
        raise RuntimeError(
            "onnx output is not supported yet; the portable StableHLO "
            f"artifact was written to {saved} and runs via "
            "paddle_hackathon_tpu.inference on any host")
    if _onnx_available():
        import warnings
        warnings.warn(
            "the 'onnx' package is installed but program->onnx conversion "
            f"is not wired yet; wrote the StableHLO artifact {saved} only")
    return saved
