"""paddle.incubate.distributed namespace (ref ``python/paddle/incubate/
distributed/``): MoE lives under models.moe, implemented in parallel.moe."""

from . import models  # noqa: F401
