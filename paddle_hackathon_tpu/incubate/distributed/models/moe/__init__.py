"""Re-export of the MoE stack (ref ``python/paddle/incubate/distributed/
models/moe/moe_layer.py:244``); implementation in ``parallel.moe``."""

from paddle_hackathon_tpu.parallel import moe as _impl
from paddle_hackathon_tpu.parallel.moe import *  # noqa: F401,F403

__all__ = getattr(_impl, "__all__", [n for n in dir(_impl)
                                     if not n.startswith("_")])
