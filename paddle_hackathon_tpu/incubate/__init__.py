"""paddle.incubate equivalent — staging surface.

Ref ``python/paddle/incubate/``: fused transformer layers + functionals
(Pallas flash attention on TPU), ASP n:m sparsity, functional autograd
(jvp/vjp/Jacobian/Hessian), LookAhead/ModelAverage optimizers,
``incubate.distributed.models.moe`` (the MoE layer, shared with
``parallel.moe``).
"""

from . import asp, autograd, distributed, nn, operators, optimizer  # noqa: F401
from .operators import (graph_khop_sampler, graph_reindex,  # noqa: F401
                        graph_sample_neighbors, graph_send_recv,
                        identity_loss, segment_max, segment_mean,
                        segment_min, segment_sum, softmax_mask_fuse,
                        softmax_mask_fuse_upper_triangle)
from .optimizer import DistributedFusedLamb, LookAhead, ModelAverage  # noqa: F401
from .. import sparse  # noqa: F401 — paddle.incubate.sparse surface


def autotune(config=None):
    """paddle.incubate.autotune stub — on TPU, kernel autotuning is XLA's
    job (autotuner runs inside the compiler); layout autotune is subsumed by
    XLA layout assignment. Accepts and ignores the reference's config dict
    (ref incubate/autotune.py)."""
    return None
