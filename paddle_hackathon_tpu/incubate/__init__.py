"""paddle.incubate equivalent — staging surface.

Ref ``python/paddle/incubate/``: fused transformer layers + functionals
(Pallas flash attention on TPU), ASP n:m sparsity, functional autograd
(jvp/vjp/Jacobian/Hessian), LookAhead/ModelAverage optimizers,
``incubate.distributed.models.moe`` (the MoE layer, shared with
``parallel.moe``).
"""

from . import asp, autograd, distributed, nn, operators, optimizer  # noqa: F401
from .operators import (graph_khop_sampler, graph_reindex,  # noqa: F401
                        graph_sample_neighbors, graph_send_recv,
                        identity_loss, segment_max, segment_mean,
                        segment_min, segment_sum, softmax_mask_fuse,
                        softmax_mask_fuse_upper_triangle)
from .optimizer import DistributedFusedLamb, LookAhead, ModelAverage  # noqa: F401
from .. import sparse  # noqa: F401 — paddle.incubate.sparse surface


def autotune(config=None):
    """paddle.incubate.autotune (ref ``incubate/autotune.py`` set_config).

    kernel: enables the runtime Pallas-kernel autotuner
    (``core.autotune``) — flash-attention block shapes are measured per
    signature during the configured eager tuning window and cached.
    layout: subsumed by XLA layout assignment. dataloader: accepted for
    parity. XLA additionally autotunes its own fusions in-compiler."""
    from ..core import autotune as _at
    _at.set_config(config)
    # return None for parity: the reference's set_config returns None; the
    # status dict is available via paddle.incubate.autotune_status()


def autotune_status():
    """Autotuner status (config + cache hit/miss counters) — the dict the
    pre-parity ``autotune()`` used to return."""
    from ..core import autotune as _at
    return _at.status()
