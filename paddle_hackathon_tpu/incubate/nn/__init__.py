from . import functional, kernels  # noqa: F401
from .layer.fused_transformer import (  # noqa: F401
    FusedBiasDropoutResidualLayerNorm, FusedFeedForward, FusedLinear,
    FusedMultiHeadAttention, FusedMultiTransformer,
    FusedTransformerEncoderLayer)
