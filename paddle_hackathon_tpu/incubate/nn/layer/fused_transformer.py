"""Fused transformer layers.

Ref ``python/paddle/incubate/nn/layer/fused_transformer.py`` —
``FusedMultiHeadAttention`` (:176), ``FusedFeedForward`` (:437),
``FusedTransformerEncoderLayer`` (:641), ``FusedMultiTransformer`` (:914).
The reference dispatches to monolithic CUDA kernels; here each layer calls
the incubate fused functionals (Pallas flash attention + XLA-fused chains).
"""

from __future__ import annotations

import math

from ....nn import initializer as I
from ....nn.layer import Layer
from ....ops import manipulation as M
from .. import functional as FF


class FusedMultiHeadAttention(Layer):
    """Pre/post-LN multi-head self-attention with fused residual+dropout."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.normalize_before = normalize_before
        self.epsilon = epsilon
        self.qkv_weight = self.create_parameter(
            [embed_dim, 3 * embed_dim], attr=qkv_weight_attr)
        self.qkv_bias = self.create_parameter(
            [3 * embed_dim], attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter(
            [embed_dim, embed_dim], attr=linear_weight_attr)
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=linear_bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=ln_scale_attr, default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter(
            [embed_dim], attr=ln_bias_attr, is_bias=True)

    def gen_cache(self, key, value=None):
        """Empty growing Cache for incremental decoding (same protocol as
        ``nn.MultiHeadAttention.gen_cache``; the fused qkv computes k/v
        from the query, so only the growing-Cache type applies)."""
        from ....nn.layers.transformer import MultiHeadAttention as _MHA
        from ....ops import creation
        b = key.shape[0]
        z = creation.zeros([b, 0, self.num_heads, self.head_dim], key.dtype)
        return _MHA.Cache(z, z)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        x = query
        residual = x
        if self.normalize_before:
            x, _ = FF.fused_layer_norm(x, self.ln_scale, self.ln_bias,
                                       epsilon=self.epsilon,
                                       training=self.training)
        qkv = FF.fused_linear(x, self.qkv_weight, self.qkv_bias)
        b, s = qkv.shape[0], qkv.shape[1]
        qkv = M.reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
        q = M.squeeze(M.slice(qkv, [2], [0], [1]), axis=[2])
        k = M.squeeze(M.slice(qkv, [2], [1], [2]), axis=[2])
        v = M.squeeze(M.slice(qkv, [2], [2], [3]), axis=[2])
        new_cache = None
        if cache is not None:
            from ....nn.layers.transformer import MultiHeadAttention as _MHA
            if isinstance(cache, _MHA.StaticCache):
                k, v = cache.k, cache.v
            else:
                k = M.concat([cache.k, k], axis=1)
                v = M.concat([cache.v, v], axis=1)
                new_cache = _MHA.Cache(k, v)
        from ....nn import functional as F
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate if self.training else 0.0,
            training=self.training)
        out = M.reshape(out, [b, s, self.embed_dim])
        if self.normalize_before:
            out = FF.fused_linear(out, self.linear_weight, self.linear_bias)
            out = FF.fused_dropout_add(out, residual, p=self.dropout_rate,
                                       training=self.training)
        else:
            out = FF.fused_linear(out, self.linear_weight)
            out, _ = FF.fused_layer_norm(
                out, self.ln_scale, self.ln_bias, epsilon=self.epsilon,
                residual=residual, bias=self.linear_bias,
                dropout_rate=self.dropout_rate, training=self.training)
        if new_cache is not None:
            return out, new_cache
        return out

    def extra_repr(self):
        return (f"embed_dim={self.embed_dim}, num_heads={self.num_heads}, "
                f"normalize_before={self.normalize_before}")


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.d_model = d_model
        self.dim_feedforward = dim_feedforward
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (act_dropout_rate if act_dropout_rate
                                 is not None else dropout_rate)
        self.activation = activation
        self.epsilon = epsilon
        self.normalize_before = normalize_before
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=linear1_weight_attr)
        self.linear1_bias = self.create_parameter(
            [dim_feedforward], attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=linear2_weight_attr)
        self.linear2_bias = self.create_parameter(
            [d_model], attr=linear2_bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            [d_model], attr=ln1_scale_attr, default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter(
            [d_model], attr=ln1_bias_attr, is_bias=True)

    def forward(self, src, cache=None):
        return FF.fused_feedforward(
            src, self.linear1_weight, self.linear1_bias, self.linear2_weight,
            self.linear2_bias, ln1_scale=self.ln_scale, ln1_bias=self.ln_bias,
            dropout1_rate=self.act_dropout_rate,
            dropout2_rate=self.dropout_rate, activation=self.activation,
            ln1_epsilon=self.epsilon, pre_layer_norm=self.normalize_before,
            training=self.training)

    def extra_repr(self):
        return (f"d_model={self.d_model}, "
                f"dim_feedforward={self.dim_feedforward}")


class FusedTransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout_rate = (attn_dropout_rate if attn_dropout_rate
                             is not None else dropout_rate)
        act_dropout_rate = (act_dropout_rate if act_dropout_rate
                            is not None else dropout_rate)
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        if cache is not None:
            res = self.fused_attn(src, attn_mask=src_mask, cache=cache)
            if isinstance(res, tuple):           # growing Cache: updated
                out, new_cache = res
                return self.ffn(out), new_cache
            return self.ffn(res)                 # StaticCache: no update
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)

    def gen_cache(self, src):
        return self.fused_attn.gen_cache(src)


class FusedMultiTransformer(Layer):
    """Stack of fused decoder blocks (ref :914 — the inference-serving path
    of ERNIE/GPT; here the same layers drive the Pallas attention)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 num_layers=1, epsilon=1e-5):
        super().__init__()
        from ....nn.container import LayerList
        self.layers = LayerList([
            FusedTransformerEncoderLayer(
                embed_dim, num_heads, dim_feedforward,
                dropout_rate=dropout_rate, activation=activation,
                normalize_before=normalize_before)
            for _ in range(num_layers)])

    def forward(self, src, attn_mask=None, caches=None):
        out = src
        if caches is not None:
            new_caches = []
            for layer, c in zip(self.layers, caches):
                res = layer(out, src_mask=attn_mask, cache=c)
                if isinstance(res, tuple):
                    out, nc = res
                else:                            # StaticCache layer
                    out, nc = res, c
                new_caches.append(nc)
            return out, new_caches
        for layer in self.layers:
            out = layer(out, src_mask=attn_mask)
        return out

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "FusedMultiTransformer"]


class FusedLinear(Layer):
    """Linear with the gemm-epilogue fused op (ref incubate FusedLinear)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        shape = ([out_features, in_features] if transpose_weight
                 else [in_features, out_features])
        self.weight = self.create_parameter(shape, attr=weight_attr,
                                            dtype="float32")
        self.bias = (None if bias_attr is False else self.create_parameter(
            [out_features], attr=bias_attr, dtype="float32", is_bias=True))
        self.transpose_weight = transpose_weight

    def forward(self, x):
        from .. import functional as F
        return F.fused_linear(x, self.weight, self.bias,
                              transpose_weight=self.transpose_weight)


class FusedBiasDropoutResidualLayerNorm(Layer):
    """LN(residual + dropout(x + bias)) (ref incubate layer)."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        from ....nn import initializer as I
        self.linear_bias = self.create_parameter([embed_dim],
                                                 dtype="float32", is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=weight_attr, dtype="float32",
            default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], attr=bias_attr,
                                             dtype="float32", is_bias=True)

    def forward(self, x, residual):
        from .. import functional as F
        return F.fused_bias_dropout_residual_layer_norm(
            x, residual, self.linear_bias, self.ln_scale, self.ln_bias,
            dropout_rate=self.dropout_rate, ln_epsilon=self.epsilon,
            training=self.training)
