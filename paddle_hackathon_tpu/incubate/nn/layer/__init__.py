from .fused_transformer import (  # noqa: F401
    FusedFeedForward, FusedMultiHeadAttention, FusedMultiTransformer,
    FusedTransformerEncoderLayer)
