"""Packed-heads Pallas flash attention: consumes the qkv projection output
directly.

The (bh, s, d) kernels in ``flash_attention.py`` require the model to
reorganize activations (b, s, H*D) -> (b, H, s, d) around every attention
call; XLA materializes those as layout-change copies (measured ~10% of the
gpt2-small train step, plus the (3,b,s,H,d) gradient re-assembly fusions).
The reference pays the same cost on GPU inside
``fused_attention_op.cu``'s transpose stage (``fmha_ref.h``).

This kernel family keeps everything in the projection-native layout:

- input is the fused qkv projection output ``(b, s, 3*H*D)`` — q/k/v are
  *lane-offset BlockSpecs into the same array*, so no split, reshape, or
  transpose ever exists in HBM;
- heads are processed in *groups* of G per grid cell (one extra grid
  dimension indexes the group): per head the kernel lane-slices
  (block, D) tiles out of its (block, G*D) VMEM blocks, runs the online
  softmax recurrence, and writes packed (b, s, H*D) outputs that feed
  out_proj directly.  Grouping keeps VMEM per cell bounded for any H, so
  gpt2-small (H*D=768) runs whole rows per cell while a 2048-hidden model
  splits into G=4-head groups without shrinking the 512-edge blocks;
- backward mirrors it (dq kernel + dkdv kernel); the only XLA-side work
  left is one lane concat of (dq, dk, dv) into the qkv cotangent.

Stats (lse) live transposed as (b, H, 8, s) sublane-broadcast rows — the
running max/sum also live transposed in VMEM ((G, 8, block) instead of
(G, block, 128)), which is what lets 512-edge blocks fit.  Causal masking
uses diagonal-clamped index maps (masked cells skip compute AND their
DMA).  Dropout reuses the positional-hash mask, keyed by the global head
index so each head draws an independent mask.

``supported()`` gates callers: bf16/f16 only (f32 blocks blow the VMEM
budget — those callers take the bhd path), D a sublane multiple, G*D a
lane multiple.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .flash_attention import (_NEG_INF, _SUB, _dropout_keep, _interpret,
                              _prec, _smem_spec)

_LANES = 128
# The estimator under-counts the compiler's score/prob temporaries; 13 MB
# keeps the worst (dkdv) kernel clear of the 16 MB scoped-vmem limit
# (G=12 at 512^2 blocks estimated 14.6 MB but compiled to 16.56 MB).
_VMEM_BUDGET = 13 * 2**20


def _tune_key(sq, skv, heads, dtype):
    return ("flash_packed_blocks", sq, skv, heads, jnp.dtype(dtype).itemsize)


def _plan(sq, skv, heads, head_dim, dtype=jnp.bfloat16):
    """Pick (block_q, block_kv, group) — block edges and heads-per-cell.

    Largest block edge wins (512 beat 256 by ~12% e2e on gpt2-small), then
    the largest head group that keeps the worst-case (dkdv) cell inside
    the scoped-VMEM budget: 4 double-buffered (b, G*D) input streams, two
    (b, G*D) outputs, two (G, b, D) f32 accumulators, ~2 (b, b) f32
    score/prob temporaries.  The autotune cache can override per shape."""
    from ....core import autotune as _at
    cached = (_at.kernel_cache.get(_tune_key(sq, skv, heads, dtype))
              if _at.enabled() else None)
    if cached is not None:
        return cached
    isz = jnp.dtype(dtype).itemsize

    def est(b, g):
        gd = g * head_dim
        return (2 * 4 * b * gd * isz + 2 * 2 * b * gd * isz
                + 2 * g * b * head_dim * 4 + 2 * b * b * 4)

    groups = [g for g in range(heads, 0, -1) if heads % g == 0
              and (g * head_dim) % _LANES == 0]
    for b in (512, 256, 128, 64, 32, 16, 8):
        if sq % b or skv % b or b > sq or b > skv:
            continue
        for g in groups:
            if est(b, g) <= _VMEM_BUDGET:
                return (b, b, g)
    return None


def _block_sizes(sq, skv, heads, head_dim, dtype=jnp.bfloat16):
    plan = _plan(sq, skv, heads, head_dim, dtype)
    return None if plan is None else (plan[0], plan[1])


def supported(sq, skv, heads, head_dim, dtype) -> bool:
    if head_dim % 8 != 0:
        return False
    if jnp.dtype(dtype).itemsize > 2:
        return False  # f32 blocks blow the VMEM budget; use the bhd path
    return _plan(sq, skv, heads, head_dim, dtype) is not None


def _causal_positions(qi, ki, bq, bkv, transposed=False):
    if transposed:  # (block_kv, block_q) layouts (the dkdv kernel)
        k_pos = ki * bkv + jax.lax.broadcasted_iota(
            jnp.int32, (bkv, bq), 0)
        q_pos = qi * bq + jax.lax.broadcasted_iota(
            jnp.int32, (bkv, bq), 1)
    else:
        q_pos = qi * bq + jax.lax.broadcasted_iota(
            jnp.int32, (bq, bkv), 0)
        k_pos = ki * bkv + jax.lax.broadcasted_iota(
            jnp.int32, (bq, bkv), 1)
    return q_pos, k_pos


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, seed_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, sm_scale, causal, block_q,
                block_kv, n_kv, group, heads, head_dim, dropout_p):
    bi = pl.program_id(0)
    gi = pl.program_id(1)
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    D = head_dim

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _body(masked):
        # VPU passes over the (block_q, block_kv) tile are the kernel's
        # critical path (the d=64 dots leave the MXU mostly idle), so the
        # softmax touches the full tile as few times as possible:
        # sm_scale is folded into the small (block, D) q slice (exact for
        # power-of-two 1/sqrt(D)), and the causal mask + iotas exist only
        # on diagonal-crossing cells (``masked``) — strictly-lower cells
        # skip them entirely.  Diag cells mask BEFORE the running max (a
        # raw-block max could be inflated by a masked outlier logit,
        # underflowing every valid probability in the row).
        qb = q_ref[0]                            # (block_q, G*D)
        kb = k_ref[0]                            # (block_kv, G*D)
        vb = v_ref[0]
        if masked or dropout_p > 0.0:
            q_pos, k_pos = _causal_positions(qi, ki, block_q, block_kv)
        if masked:
            causal_keep = q_pos >= k_pos         # bool; the i32 iotas die here
        for h in range(group):
            q = (qb[:, h * D:(h + 1) * D] *
                 jnp.asarray(sm_scale, qb.dtype))
            k = kb[:, h * D:(h + 1) * D]
            v = vb[:, h * D:(h + 1) * D]
            # contract over d of BOTH operands directly — current Mosaic
            # takes (1,1) bf16 contractions natively, no register transpose
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32,
                                    precision=_prec(q.dtype))
            if masked:
                s = jnp.where(causal_keep, s, _NEG_INF)
            # stats live transposed (8, block_q); work in (block_q, 1)
            m_prev = jnp.swapaxes(m_ref[h], 0, 1)[:, :1]
            l_prev = jnp.swapaxes(l_ref[h], 0, 1)[:, :1]
            m_cur = jnp.max(s, axis=1, keepdims=True)
            m_next = jnp.maximum(m_prev, m_cur)          # (block_q, 1)
            alpha = jnp.exp(m_prev - m_next)
            p = jnp.exp(s - m_next)
            l_next = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
            if dropout_p > 0.0:
                keep = _dropout_keep(seed_ref[0],
                                     bi * heads + gi * group + h,
                                     q_pos, k_pos, 1.0 - dropout_p)
                p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
            pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                     (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32,
                                     precision=_prec(v.dtype))
            acc_ref[h] = acc_ref[h] * alpha + pv
            m_ref[h] = jnp.swapaxes(
                jnp.broadcast_to(m_next, (block_q, _SUB)), 0, 1)
            l_ref[h] = jnp.swapaxes(
                jnp.broadcast_to(l_next, (block_q, _SUB)), 0, 1)

    if causal:
        last_q = qi * block_q + block_q - 1
        diag = (ki * block_kv <= last_q) & \
            (ki * block_kv + block_kv - 1 > last_q - block_q)

        @pl.when(diag)
        def _run_diag():
            _body(True)

        @pl.when(ki * block_kv + block_kv - 1 <= last_q - block_q)
        def _run_full():
            _body(False)
    else:
        _body(False)

    @pl.when(ki == n_kv - 1)
    def _finish():
        for h in range(group):
            lt = l_ref[h]                        # (8, block_q)
            lt = jnp.where(lt == 0.0, 1.0, lt)
            l_col = jnp.swapaxes(lt, 0, 1)[:, :1]
            o_ref[0, :, h * D:(h + 1) * D] = (
                acc_ref[h] / l_col).astype(o_ref.dtype)
            lse_ref[0, h] = m_ref[h] + jnp.log(jnp.maximum(lt, 1e-30))


def _kv_idx_packed(causal, bq, bkv, n_kv, part, n_groups):
    """kv index map into the packed (b, s, 3*H*D) qkv array, in G*D-lane
    block units: ``part`` selects q (0), k (1) or v (2); the group grid
    index picks the lane block within the part; causal clamps to the
    diagonal so masked cells elide their DMA."""
    if not causal:
        return lambda b, g, i, j: (b, j, part * n_groups + g)

    def idx(b, g, i, j):
        diag = jnp.minimum((i * bq + bq - 1) // bkv, n_kv - 1)
        return (b, jnp.minimum(j, diag), part * n_groups + g)
    return idx


def _fwd(qkv, heads, causal, sm_scale, dropout_p=0.0, seed=None,
         _blocks=None):
    from jax.experimental.pallas import tpu as pltpu
    b, sq, hd3 = qkv.shape
    hd = hd3 // 3
    D = hd // heads
    skv = sq
    bq, bkv, G = _blocks or _plan(sq, skv, heads, D, qkv.dtype)
    n_q, n_kv = sq // bq, skv // bkv
    n_g = heads // G
    gd = G * D

    if seed is None:
        seed = jnp.zeros((1,), jnp.int32)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=bq,
        block_kv=bkv, n_kv=n_kv, group=G, heads=heads, head_dim=D,
        dropout_p=dropout_p)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, n_g, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, bq, gd), lambda bb, g, i, j: (bb, i, g)),
            pl.BlockSpec((1, bkv, gd),
                         _kv_idx_packed(causal, bq, bkv, n_kv, 1, n_g)),
            pl.BlockSpec((1, bkv, gd),
                         _kv_idx_packed(causal, bq, bkv, n_kv, 2, n_g)),
            _smem_spec(),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, gd), lambda bb, g, i, j: (bb, i, g)),
            pl.BlockSpec((1, G, _SUB, bq),
                         lambda bb, g, i, j: (bb, g, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, sq, hd), qkv.dtype),
            jax.ShapeDtypeStruct((b, heads, _SUB, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((G, bq, D), jnp.float32),       # acc
            pltpu.VMEM((G, _SUB, bq), jnp.float32),    # m (transposed)
            pltpu.VMEM((G, _SUB, bq), jnp.float32),    # l (transposed)
        ],
        interpret=_interpret(),
    )(qkv, qkv, qkv, seed)
    return out, lse


def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                     delta_ref, seed_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                     *, sm_scale, causal, block_q, block_kv, n_q, group,
                     heads, head_dim, dropout_p):
    bi = pl.program_id(0)
    gi = pl.program_id(1)
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    D = head_dim

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _body(masked):
        # VPU economy (see _fwd_kernel): sm_scale folded into the q slice
        # (st lands in lse space; the same scaled q also serves the dk dot,
        # since dk = pt*(dpt-delta) . q*scale), causal select after the
        # exp, diagonal-crossing cells only
        qb = q_ref[0]                            # (block_q, G*D)
        kb = k_ref[0]                            # (block_kv, G*D)
        vb = v_ref[0]
        dob = do_ref[0]
        if masked or dropout_p > 0.0:
            q_pos_t, k_pos_t = _causal_positions(
                qi, ki, block_q, block_kv, transposed=True)
        if masked:
            causal_keep = q_pos_t >= k_pos_t
        for h in range(group):
            q = (qb[:, h * D:(h + 1) * D] *
                 jnp.asarray(sm_scale, qb.dtype))
            k = kb[:, h * D:(h + 1) * D]
            v = vb[:, h * D:(h + 1) * D]
            do = dob[:, h * D:(h + 1) * D]
            lse = lse_ref[0, h][:1, :]           # (1, block_q)
            delta = delta_ref[0, h][:1, :]
            st = jax.lax.dot_general(k, q, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32,
                                     precision=_prec(k.dtype))
            pt = jnp.exp(st - lse)
            if masked:
                pt = jnp.where(causal_keep, pt, 0.0)
            pt_v = pt
            if dropout_p > 0.0:
                keep = _dropout_keep(seed_ref[0],
                                     bi * heads + gi * group + h,
                                     q_pos_t, k_pos_t, 1.0 - dropout_p)
                pt_v = jnp.where(keep, pt / (1.0 - dropout_p), 0.0)
            dv_acc[h] += jax.lax.dot_general(
                pt_v.astype(v.dtype), do, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_prec(v.dtype))
            dpt = jax.lax.dot_general(v, do, (((1,), (1,)), ((), ())),
                                      preferred_element_type=jnp.float32,
                                      precision=_prec(v.dtype))
            if dropout_p > 0.0:
                dpt = jnp.where(keep, dpt / (1.0 - dropout_p), 0.0)
            dst = pt * (dpt - delta)
            dk_acc[h] += jax.lax.dot_general(
                dst.astype(k.dtype), q, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_prec(k.dtype))

    if causal:
        first_k = ki * block_kv
        diag = (qi * block_q + block_q - 1 >= first_k) & \
            (qi * block_q < first_k + block_kv)

        @pl.when(diag)
        def _run_diag():
            _body(True)

        @pl.when(qi * block_q >= first_k + block_kv)
        def _run_full():
            _body(False)
    else:
        _body(False)

    @pl.when(qi == n_q - 1)
    def _finish():
        for h in range(group):
            dk_ref[0, :, h * D:(h + 1) * D] = dk_acc[h].astype(dk_ref.dtype)
            dv_ref[0, :, h * D:(h + 1) * D] = dv_acc[h].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   seed_ref, dq_ref, dq_acc, *, sm_scale, causal, block_q,
                   block_kv, n_kv, group, heads, head_dim, dropout_p):
    bi = pl.program_id(0)
    gi = pl.program_id(1)
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    D = head_dim

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def _body(masked):
        # same VPU economy as the forward: sm_scale folded into the small
        # q slice (s lands in lse space directly) and into the k slice of
        # the final dot (dq = p*(dp-delta) . k*scale); the causal select
        # runs on p AFTER the exp and only on diagonal-crossing cells
        qb = q_ref[0]
        kb = k_ref[0]
        vb = v_ref[0]
        dob = do_ref[0]
        if masked or dropout_p > 0.0:
            q_pos, k_pos = _causal_positions(qi, ki, block_q, block_kv)
        if masked:
            causal_keep = q_pos >= k_pos
        for h in range(group):
            scale = jnp.asarray(sm_scale, qb.dtype)
            q = qb[:, h * D:(h + 1) * D] * scale
            k = kb[:, h * D:(h + 1) * D]
            v = vb[:, h * D:(h + 1) * D]
            do = dob[:, h * D:(h + 1) * D]
            lse = jnp.swapaxes(lse_ref[0, h], 0, 1)[:, :1]   # (block_q, 1)
            delta = jnp.swapaxes(delta_ref[0, h], 0, 1)[:, :1]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32,
                                    precision=_prec(q.dtype))
            p = jnp.exp(s - lse)
            if masked:
                p = jnp.where(causal_keep, p, 0.0)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32,
                                     precision=_prec(do.dtype))
            if dropout_p > 0.0:
                keep = _dropout_keep(seed_ref[0],
                                     bi * heads + gi * group + h,
                                     q_pos, k_pos, 1.0 - dropout_p)
                dp = jnp.where(keep, dp / (1.0 - dropout_p), 0.0)
            ds = p * (dp - delta)
            dq_acc[h] += jax.lax.dot_general(
                ds.astype(k.dtype), k * scale, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_prec(k.dtype))

    if causal:
        last_q = qi * block_q + block_q - 1
        diag = (ki * block_kv <= last_q) & \
            (ki * block_kv + block_kv - 1 > last_q - block_q)

        @pl.when(diag)
        def _run_diag():
            _body(True)

        @pl.when(ki * block_kv + block_kv - 1 <= last_q - block_q)
        def _run_full():
            _body(False)
    else:
        _body(False)

    @pl.when(ki == n_kv - 1)
    def _finish():
        for h in range(group):
            dq_ref[0, :, h * D:(h + 1) * D] = dq_acc[h].astype(dq_ref.dtype)


def _bwd(heads, causal, sm_scale, dropout_p, res, do):
    from jax.experimental.pallas import tpu as pltpu
    qkv, out, lse, seed = res
    if seed is None:
        seed = jnp.zeros((1,), jnp.int32)
    b, sq, hd3 = qkv.shape
    hd = hd3 // 3
    D = hd // heads
    skv = sq
    bq, bkv, G = _plan(sq, skv, heads, D, qkv.dtype)
    n_q, n_kv = sq // bq, skv // bkv
    n_g = heads // G
    gd = G * D

    # delta = rowsum(dO * O) per head, in the (b, H, 8, s) stats layout
    do_h = do.reshape(b, sq, heads, D).astype(jnp.float32)
    out_h = out.reshape(b, sq, heads, D).astype(jnp.float32)
    delta_row = jnp.sum(do_h * out_h, axis=-1)            # (b, sq, H)
    delta_t = jnp.broadcast_to(
        jnp.swapaxes(delta_row, 1, 2)[:, :, None, :], (b, heads, _SUB, sq))

    if causal:
        def q_idx(bb, g, j, i):
            first = jnp.minimum((j * bkv) // bq, n_q - 1)
            return (bb, jnp.maximum(i, first), g)

        def stat_idx(bb, g, j, i):
            first = jnp.minimum((j * bkv) // bq, n_q - 1)
            return (bb, g, 0, jnp.maximum(i, first))
    else:
        def q_idx(bb, g, j, i):
            return (bb, i, g)

        def stat_idx(bb, g, j, i):
            return (bb, g, 0, i)

    dkdv = functools.partial(
        _bwd_dkdv_kernel, sm_scale=sm_scale, causal=causal, block_q=bq,
        block_kv=bkv, n_q=n_q, group=G, heads=heads, head_dim=D,
        dropout_p=dropout_p)
    dk, dv = pl.pallas_call(
        dkdv,
        grid=(b, n_g, n_kv, n_q),
        in_specs=[
            pl.BlockSpec((1, bq, gd), q_idx),                       # q rows
            pl.BlockSpec((1, bkv, gd),
                         lambda bb, g, j, i: (bb, j, n_g + g)),     # k
            pl.BlockSpec((1, bkv, gd),
                         lambda bb, g, j, i: (bb, j, 2 * n_g + g)),  # v
            pl.BlockSpec((1, bq, gd), q_idx),                       # dO rows
            pl.BlockSpec((1, G, _SUB, bq), stat_idx),               # lse
            pl.BlockSpec((1, G, _SUB, bq), stat_idx),               # delta
            _smem_spec(),
        ],
        out_specs=[
            pl.BlockSpec((1, bkv, gd), lambda bb, g, j, i: (bb, j, g)),
            pl.BlockSpec((1, bkv, gd), lambda bb, g, j, i: (bb, j, g)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, skv, hd), qkv.dtype),
            jax.ShapeDtypeStruct((b, skv, hd), qkv.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((G, bkv, D), jnp.float32),
            pltpu.VMEM((G, bkv, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(qkv, qkv, qkv, do, lse, delta_t, seed)

    dqk = functools.partial(
        _bwd_dq_kernel, sm_scale=sm_scale, causal=causal, block_q=bq,
        block_kv=bkv, n_kv=n_kv, group=G, heads=heads, head_dim=D,
        dropout_p=dropout_p)
    dq = pl.pallas_call(
        dqk,
        grid=(b, n_g, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, bq, gd), lambda bb, g, i, j: (bb, i, g)),
            pl.BlockSpec((1, bkv, gd),
                         _kv_idx_packed(causal, bq, bkv, n_kv, 1, n_g)),
            pl.BlockSpec((1, bkv, gd),
                         _kv_idx_packed(causal, bq, bkv, n_kv, 2, n_g)),
            pl.BlockSpec((1, bq, gd), lambda bb, g, i, j: (bb, i, g)),
            pl.BlockSpec((1, G, _SUB, bq),
                         lambda bb, g, i, j: (bb, g, 0, i)),
            pl.BlockSpec((1, G, _SUB, bq),
                         lambda bb, g, i, j: (bb, g, 0, i)),
            _smem_spec(),
        ],
        out_specs=pl.BlockSpec((1, bq, gd), lambda bb, g, i, j: (bb, i, g)),
        out_shape=jax.ShapeDtypeStruct((b, sq, hd), qkv.dtype),
        scratch_shapes=[pltpu.VMEM((G, bq, D), jnp.float32)],
        interpret=_interpret(),
    )(qkv, qkv, qkv, do, lse, delta_t, seed)

    dqkv = jnp.concatenate([dq, dk, dv], axis=-1)   # (b, s, 3*H*D)
    return (dqkv, None)                             # None: the int seed array


# ---------------------------------------------------------------------------
# custom_vjp wrapper
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def flash_attention_packed(qkv, heads, causal, sm_scale, dropout_p=0.0,
                           seed=None):
    """Flash attention over a packed ``(b, s, 3*H*D)`` qkv projection.

    Returns the packed attention output ``(b, s, H*D)`` ready for the
    output projection. ``seed`` is a (1,) int32 array, required when
    ``dropout_p > 0``.
    """
    out, _ = _fwd(qkv, heads, causal, sm_scale, dropout_p, seed)
    return out


def _vjp_fwd(qkv, heads, causal, sm_scale, dropout_p=0.0, seed=None):
    out, lse = _fwd(qkv, heads, causal, sm_scale, dropout_p, seed)
    return out, (qkv, out, lse, seed)


flash_attention_packed.defvjp(_vjp_fwd, _bwd)
