"""Pallas TPU flash attention — forward and backward kernels.

The reference has no flash attention; its fused attention CUDA ops
(``paddle/fluid/operators/fused/fused_attention_op.cu``, ``fmha_ref.h``)
materialise the full (s, s) probability matrix. On TPU the memory-bound
classic attention wastes HBM bandwidth and caps sequence length, so the
framework's fused-attention slot is filled with an online-softmax tiled
kernel instead: O(s) memory, MXU-shaped (block_q x d) @ (d x block_kv)
tiles, f32 accumulators in VMEM scratch.

Layout contract: (batch*heads, seq, head_dim) arrays; head_dim needs no
explicit lane padding (Mosaic pads sub-128 lanes in VMEM; explicit padding
would cost real HBM copies). Gradients follow the standard two-kernel
split (dk/dv accumulate over q blocks; dq accumulates over kv blocks) with
the log-sum-exp saved from the forward pass and ``delta = rowsum(dO * O)``
precomputed in XLA.

All operands arrive in natural (s, d) block layout; where a contraction
needs a (d, s) operand it is transposed *in VMEM* inside the kernel (a
register shuffle) rather than pre-transposed by XLA — the XLA transposes
cost a full HBM read+write per tensor per pass and doubled the kernels'
input DMA streams (measured ~10% of the gpt2 train step as pure `copy`
ops).

Causal masking skips work at the *grid* level: the kv-block index map
clamps to the diagonal, so cells entirely above it re-request the previous
block index — Pallas elides the DMA — and a ``pl.when`` skips the compute.
This makes causal attention cost ~(n+1)/2n of full instead of always-full
(the old kernels only skipped compute, and only between whole blocks).

On non-TPU backends the same kernels run under the Pallas interpreter so
numerics are testable on the virtual CPU mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30  # large-but-finite: keeps exp()=0 without inf-inf NaNs
_LANES = 128
_SUB = 8  # sublane count of the (8, seq) stats (lse/delta) layout


def _prec(dtype):
    # f32 operands: keep full precision (DEFAULT would run them at bf16
    # MXU rate and lose bits). bf16 operands: DEFAULT — the global
    # 'highest' default would request an fp32 contract Mosaic rejects.
    return (jax.lax.Precision.HIGHEST if dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


def _dropout_keep(seed, b, q_pos, k_pos, keep_prob):
    """Layout-independent dropout mask: a murmur-style integer hash of
    (seed, batch*head, q position, k position) so the forward kernel and
    both backward kernels — which see the score matrix in different
    layouts — regenerate the identical mask without storing it (the
    reference's fused attention stores the O(s^2) mask; fmha_ref.h).
    int32 ops wrap, which is fine for mixing."""
    # avalanche the (seed, b) word BEFORE mixing positions, with distinct
    # odd constants per coordinate — otherwise masks are shifted copies
    # across batch*head (h would depend on b + q_pos only)
    h = (seed ^ (b * jnp.int32(-2048144789))).astype(jnp.int32)  # 0x85EBCA6B
    h = (h ^ (h >> 16)) * jnp.int32(-1640531527)    # 0x9E3779B9
    h = h + q_pos * jnp.int32(-1028477387)          # 0xC2B2AE35
    h = (h ^ (h >> 13)) * jnp.int32(668265261)      # 0x27D4EB2F
    h = h + k_pos * jnp.int32(461845907)            # 0x1B873593
    h = (h ^ (h >> 16)) * jnp.int32(-2048144789)
    h = h ^ (h >> 13)
    bits23 = h & jnp.int32(0x7FFFFF)
    thresh = jnp.int32(int(keep_prob * float(0x800000)))
    return bits23 < thresh


def _smem_spec():
    """(1,) int32 scalar input block (seed) — SMEM on TPU, plain block
    under the CPU interpreter."""
    from jax.experimental.pallas import tpu as pltpu
    if _interpret():
        return pl.BlockSpec((1,), lambda *_: (0,))
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _block_sizes(sq: int, skv: int, dtype=jnp.bfloat16):
    """Pick (block_q, block_kv). Swept on v5e (fwd+bwd, bf16, d=64,
    B*H=288): square 1024x1024 blocks win at every seq length that admits
    them — 12.9 ms vs 19.5 for (1024,512) at S=1024, 23.7 vs 25.8 at
    S=4096. Wider blocks blow the 16 MB scoped-VMEM budget (the s/p
    temporaries are f32 (bq, bkv): 4 MB at 1024^2); with f32 *operands*
    the backward's doubled input blocks push a 1024^2 grid cell past the
    budget too, so f32 caps at 512.

    ``paddle.incubate.autotune`` overrides this default per shape: a
    measured winner in the autotune cache (keyed like _tune_key) wins."""
    from ....core import autotune as _at
    cached = _at.kernel_cache.get(_tune_key(sq, skv, dtype))         if _at.enabled() else None
    if cached is not None:
        return cached
    cap = _vmem_cap(dtype)
    bq = next((b for b in _BLOCK_CANDIDATES
               if b <= min(sq, cap) and sq % b == 0), None)
    bkv = next((b for b in _BLOCK_CANDIDATES
                if b <= min(skv, cap) and skv % b == 0), None)
    if bq is None or bkv is None:
        return None
    return bq, bkv


_BLOCK_CANDIDATES = (1024, 512, 256, 128, 64, 32, 16, 8)


def _vmem_cap(dtype):
    """Largest admissible block edge under the 16 MB scoped-VMEM budget
    (single source for the default chooser AND the autotuner's candidate
    set — they must agree on what is safe)."""
    return 1024 if jnp.dtype(dtype).itemsize <= 2 else 512


def _tune_key(sq, skv, dtype):
    return ("flash_attention_blocks", sq, skv, jnp.dtype(dtype).itemsize)


def _candidate_blocks(sq, skv, dtype):
    cap = _vmem_cap(dtype)
    cands = []
    for bq in (1024, 512, 256, 128):
        for bkv in (1024, 512, 256, 128):
            if bq <= min(sq, cap) and bkv <= min(skv, cap)                     and sq % bq == 0 and skv % bkv == 0:
                cands.append((bq, bkv))
    return cands


def maybe_autotune(q, k, v, causal, sm_scale):
    """Eager-mode block-shape autotune (ref ``auto_tune_base.h``): when
    ``incubate.autotune`` enabled kernel tuning and we are inside the
    tuning step window, measure the fwd kernel across candidate block
    shapes for this (sq, skv, dtype) signature and cache the winner.
    No-op under a jit trace (nothing can be measured) — the cache filled
    during eager warmup steps then serves compiled calls too. Measurement
    covers the forward kernel only (the backward shares the cached block
    choice); the static default remains the bwd-swept optimum when tuning
    is off."""
    from ....core import autotune as _at
    if not (_at.enabled() and _at.in_tuning_window()):
        return
    if isinstance(q, jax.core.Tracer) or _interpret():
        return
    sq, skv = q.shape[1], k.shape[1]
    key = _tune_key(sq, skv, q.dtype)
    if _at.kernel_cache.get(key) is not None:
        return
    default = _block_sizes(sq, skv, q.dtype)
    cands = _candidate_blocks(sq, skv, q.dtype)

    def measure(blocks):
        def run():
            out, _ = _fwd(q, k, v, causal, sm_scale, _blocks=blocks)
            jax.block_until_ready(out)
            float(jnp.sum(out[..., :1].astype(jnp.float32)))  # hard sync
        run()  # compile outside the timed reps
        return _at.measure_wall(run)

    _at.tune(key, cands, measure, default=default)


def supported(sq: int, skv: int) -> bool:
    """Whether the kernel handles these sequence lengths (else XLA path)."""
    return _block_sizes(sq, skv) is not None


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, seed_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, sm_scale, causal, block_q,
                block_kv, n_kv, dropout_p):
    bi = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _body():
        q = q_ref[0]
        kt = jnp.swapaxes(k_ref[0], 0, 1)        # (d, block_kv) in-VMEM
        v = v_ref[0]
        # standard (1),(0) contraction — the only dot shape Mosaic's bf16
        # matmul supports; the k transpose is a VMEM register shuffle
        s = jax.lax.dot_general(q, kt, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=_prec(q.dtype))
        s = s * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            k_pos = ki * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)

        m_prev = m_ref[...]                      # (block_q, LANES)
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=1, keepdims=True)        # (block_q, 1)
        m_next = jnp.maximum(m_prev, m_cur)              # (block_q, LANES)
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next[:, :1])                   # (block_q, block_kv)
        l_ref[...] = l_prev * alpha + jnp.sum(
            p, axis=1, keepdims=True) * jnp.ones_like(l_prev)
        if dropout_p > 0.0:
            # drop the unnormalised p only in the PV accumulation: the
            # final /l then equals dropout(softmax(s)) @ v, and lse stays
            # the exact (undropped) statistic the backward needs
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            k_pos = ki * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            keep = _dropout_keep(seed_ref[0], bi, q_pos, k_pos,
                                 1.0 - dropout_p)
            p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
        pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=_prec(v.dtype))
        acc_ref[...] = acc_ref[...] * alpha[:, :1] + pv
        m_ref[...] = m_next

    if causal:
        @pl.when(ki * block_kv <= qi * block_q + block_q - 1)
        def _run():
            _body()
    else:
        _body()

    @pl.when(ki == n_kv - 1)
    def _finish():
        l = l_ref[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)  # fully-masked row -> zeros out
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        # store lse transposed as (8, block_q) sublane-broadcast rows: a
        # (bh, 8, sq) stats array costs 8 f32 lanes per token in HBM where
        # the old lane-broadcast (bh, sq, 128) layout cost 128 — at
        # B*H=288, S=1024 that is 9.4 MB vs 151 MB of residual per layer
        lse2d = m_ref[...] + jnp.log(jnp.maximum(l_ref[...], 1e-30))
        lse_ref[0] = jnp.swapaxes(lse2d[:, :_SUB], 0, 1)


def _kv_index(causal, bq, bkv, n_kv):
    """kv-block index map: clamp to the causal diagonal so fully-masked
    cells repeat the previous block index (Pallas elides the DMA).  The
    diagonal position is additionally clamped into [0, n_kv) — with
    sq != skv it can land past the last kv block."""
    if not causal:
        return lambda b, i, j: (b, j, 0)

    def idx(b, i, j):
        diag = jnp.minimum((i * bq + bq - 1) // bkv, n_kv - 1)
        return (b, jnp.minimum(j, diag), 0)
    return idx


def _fwd(q, k, v, causal, sm_scale, dropout_p=0.0, seed=None,
         _blocks=None):
    bh, sq, d = q.shape
    skv = k.shape[1]
    bq, bkv = _blocks or _block_sizes(sq, skv, q.dtype)
    n_q, n_kv = sq // bq, skv // bkv

    if seed is None:
        seed = jnp.zeros((1,), jnp.int32)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=bq,
        block_kv=bkv, n_kv=n_kv, dropout_p=dropout_p)
    kv_idx = _kv_index(causal, bq, bkv, n_kv)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bkv, d), kv_idx),
            pl.BlockSpec((1, bkv, d), kv_idx),
            _smem_spec(),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, _SUB, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, _SUB, sq), jnp.float32),
        ],
        scratch_shapes=_fwd_scratch(bq, d),
        interpret=_interpret(),
    )(q, k, v, seed)
    return out, lse


def _fwd_scratch(bq, d):
    from jax.experimental.pallas import tpu as pltpu
    return [
        pltpu.VMEM((bq, d), jnp.float32),       # acc
        pltpu.VMEM((bq, _LANES), jnp.float32),  # m
        pltpu.VMEM((bq, _LANES), jnp.float32),  # l
    ]


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------

def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref,
                     lse_ref, delta_ref, seed_ref,
                     dk_ref, dv_ref, dk_acc, dv_acc, *, sm_scale, causal,
                     block_q, block_kv, n_q, dropout_p):
    """dk/dv in transposed (kv, q) layout.

    Every contraction is a standard (1),(0) dot — the only shape Mosaic's
    native bf16 matmul supports — by computing s^T = k @ q^T with the
    (d, block_q) operands produced by in-VMEM transposes (register
    shuffles; the old XLA pre-transposes cost an HBM pass per tensor and
    doubled the kernel's input DMA streams).
    lse/delta arrive as (8, block_q) sublane-broadcast rows. bf16 operands
    stay bf16 on the MXU (f32 accumulate); only softmax/elementwise math
    is f32.
    """
    bi = pl.program_id(0)
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _body():
        q = q_ref[0]                            # (block_q, d)
        qt = jnp.swapaxes(q, 0, 1)              # (d, block_q) in-VMEM
        k = k_ref[0]                            # (block_kv, d)
        v = v_ref[0]
        do = do_ref[0]                          # (block_q, d)
        dot_ = jnp.swapaxes(do, 0, 1)           # (d, block_q) = dO^T
        lse = lse_ref[0][:1, :]                 # (1, block_q)
        delta = delta_ref[0][:1, :]
        # s^T = (k @ q^T) * scale                 (block_kv, block_q)
        st = jax.lax.dot_general(k, qt, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=_prec(k.dtype))
        st = st * sm_scale
        if causal:
            k_pos = ki * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, (block_kv, block_q), 0)
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_kv, block_q), 1)
            st = jnp.where(q_pos >= k_pos, st, _NEG_INF)
        pt = jnp.exp(st - lse)                  # (block_kv, block_q)
        pt_v = pt
        if dropout_p > 0.0:
            # same positional-hash mask as the forward (transposed layout:
            # k along rows, q along columns)
            k_pos_t = ki * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, (block_kv, block_q), 0)
            q_pos_t = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_kv, block_q), 1)
            keep = _dropout_keep(seed_ref[0], bi, q_pos_t, k_pos_t,
                                 1.0 - dropout_p)
            pt_v = jnp.where(keep, pt / (1.0 - dropout_p), 0.0)
        # dv += dropout(p)^T @ dO                 (block_kv, d)
        dv_acc[...] += jax.lax.dot_general(
            pt_v.astype(v.dtype), do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_prec(v.dtype))
        # dp^T = v @ dO^T                         (block_kv, block_q)
        dpt = jax.lax.dot_general(v, dot_, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32,
                                  precision=_prec(v.dtype))
        if dropout_p > 0.0:
            dpt = jnp.where(keep, dpt / (1.0 - dropout_p), 0.0)
        dst = pt * (dpt - delta) * sm_scale
        # dk += ds^T @ q                          (block_kv, d)
        dk_acc[...] += jax.lax.dot_general(
            dst.astype(k.dtype), q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_prec(k.dtype))

    if causal:
        @pl.when(qi * block_q + block_q - 1 >= ki * block_kv)
        def _run():
            _body()
    else:
        _body()

    @pl.when(qi == n_q - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   seed_ref,
                   dq_ref, dq_acc, *, sm_scale, causal, block_q, block_kv,
                   n_kv, dropout_p):
    """dq in natural (q, kv) layout; the (d, block_kv) operands are in-VMEM
    transposes of the natural k/v blocks so every dot is a standard (1),(0)
    bf16 contraction (see dkdv kernel).
    lse/delta arrive in the (8, block_q) stats layout and are transposed to
    a (block_q, 1) column in-VMEM (a cheap sublane/lane swap)."""
    bi = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def _body():
        q = q_ref[0]                            # (block_q, d)
        k = k_ref[0]                            # (block_kv, d)
        kt = jnp.swapaxes(k, 0, 1)              # (d, block_kv) in-VMEM
        vt = jnp.swapaxes(v_ref[0], 0, 1)       # (d, block_kv)
        do = do_ref[0]                          # (block_q, d)
        lse = jnp.swapaxes(lse_ref[0], 0, 1)[:, :1]     # (block_q, 1)
        delta = jnp.swapaxes(delta_ref[0], 0, 1)[:, :1]
        s = jax.lax.dot_general(q, kt, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=_prec(q.dtype))
        s = s * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            k_pos = ki * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        # dp = dO @ v^T                           (block_q, block_kv)
        dp = jax.lax.dot_general(do, vt, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=_prec(do.dtype))
        if dropout_p > 0.0:
            q_pos2 = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            k_pos2 = ki * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            keep = _dropout_keep(seed_ref[0], bi, q_pos2, k_pos2,
                                 1.0 - dropout_p)
            dp = jnp.where(keep, dp / (1.0 - dropout_p), 0.0)
        ds = p * (dp - delta) * sm_scale
        # dq += ds @ k                            (block_q, d)
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_prec(k.dtype))

    if causal:
        @pl.when(ki * block_kv <= qi * block_q + block_q - 1)
        def _run():
            _body()
    else:
        _body()

    @pl.when(ki == n_kv - 1)
    def _finish():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd(causal, sm_scale, dropout_p, res, do):
    q, k, v, out, lse, seed = res
    delta_row = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1)                          # (bh, sq)
    dq, dk, dv = _bwd_pair(q, k, v, do, lse, delta_row, causal, sm_scale,
                           dropout_p, seed)
    return dq, dk, dv, None


def _bwd_pair(q, k, v, do, lse, delta_row, causal, sm_scale,
              dropout_p=0.0, seed=None):
    """(dq, dk, dv) for one q-chunk x kv-chunk pair, given the *global*
    softmax statistics of the q rows: ``lse`` in the (bh, 8, sq) stats
    layout and ``delta_row = rowsum(dO * O_final)`` as (bh, sq).

    This is the whole-sequence backward when the pair covers the full
    sequence — and the per-step building block of ring attention, where
    the same q rows pair with a rotating kv chunk (Liu et al. 2023): with
    global lse/delta the per-pair grads sum exactly to the full-attention
    gradient."""
    if seed is None:
        seed = jnp.zeros((1,), jnp.int32)
    bh, sq, d = q.shape
    skv = k.shape[1]
    bq, bkv = _block_sizes(sq, skv, q.dtype)
    n_q, n_kv = sq // bq, skv // bkv
    from jax.experimental.pallas import tpu as pltpu

    delta_t = jnp.broadcast_to(delta_row[:, None, :], (bh, _SUB, sq))
    lse_t = lse                                           # (bh, 8, sq)

    # causal: q-block index map clamped to the diagonal from the other side
    # (the first q block that attends to kv block j) — skipped cells repeat
    # the previous q index so their DMA is elided.  Clamped into [0, n_q)
    # for the skv > sq case where the diagonal falls past the last q block.
    if causal:
        def q_idx(b, j, i):
            first = jnp.minimum((j * bkv) // bq, n_q - 1)
            return (b, jnp.maximum(i, first), 0)

        def stat_idx(b, j, i):
            first = jnp.minimum((j * bkv) // bq, n_q - 1)
            return (b, 0, jnp.maximum(i, first))
    else:
        def q_idx(b, j, i):
            return (b, i, 0)

        def stat_idx(b, j, i):
            return (b, 0, i)

    dkdv = functools.partial(
        _bwd_dkdv_kernel, sm_scale=sm_scale, causal=causal, block_q=bq,
        block_kv=bkv, n_q=n_q, dropout_p=dropout_p)
    dk, dv = pl.pallas_call(
        dkdv,
        grid=(bh, n_kv, n_q),
        in_specs=[
            pl.BlockSpec((1, bq, d), q_idx),                        # q
            pl.BlockSpec((1, bkv, d), lambda b, j, i: (b, j, 0)),   # k
            pl.BlockSpec((1, bkv, d), lambda b, j, i: (b, j, 0)),   # v
            pl.BlockSpec((1, bq, d), q_idx),                        # do
            pl.BlockSpec((1, _SUB, bq), stat_idx),                  # lse^T
            pl.BlockSpec((1, _SUB, bq), stat_idx),                  # delta^T
            _smem_spec(),
        ],
        out_specs=[
            pl.BlockSpec((1, bkv, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bkv, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, skv, d), k.dtype),
            jax.ShapeDtypeStruct((bh, skv, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bkv, d), jnp.float32),
            pltpu.VMEM((bkv, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse_t, delta_t, seed)

    kv_idx = _kv_index(causal, bq, bkv, n_kv)
    dqk = functools.partial(
        _bwd_dq_kernel, sm_scale=sm_scale, causal=causal, block_q=bq,
        block_kv=bkv, n_kv=n_kv, dropout_p=dropout_p)
    dq = pl.pallas_call(
        dqk,
        grid=(bh, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),    # q
            pl.BlockSpec((1, bkv, d), kv_idx),                      # k
            pl.BlockSpec((1, bkv, d), kv_idx),                      # v
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),    # do
            pl.BlockSpec((1, _SUB, bq), lambda b, i, j: (b, 0, i)),  # lse
            pl.BlockSpec((1, _SUB, bq), lambda b, i, j: (b, 0, i)),  # delta
            _smem_spec(),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse_t, delta_t, seed)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention_bhd(q, k, v, causal, sm_scale, dropout_p=0.0,
                        seed=None):
    """Flash attention over (batch*heads, seq, head_dim) arrays.

    ``dropout_p`` drops attention probabilities inside the kernel (the
    mask is a positional hash of ``seed``, regenerated — never stored —
    in the backward kernels). ``seed`` is a (1,) int32 array; required
    when ``dropout_p > 0``.
    """
    out, _ = _fwd(q, k, v, causal, sm_scale, dropout_p, seed)
    return out


def _vjp_fwd(q, k, v, causal, sm_scale, dropout_p=0.0, seed=None):
    out, lse = _fwd(q, k, v, causal, sm_scale, dropout_p, seed)
    return out, (q, k, v, out, lse, seed)


flash_attention_bhd.defvjp(_vjp_fwd, _bwd)
