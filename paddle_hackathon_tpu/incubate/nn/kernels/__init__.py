from . import flash_attention  # noqa: F401
