"""Pallas TPU paged-attention decode kernel + pure-jnp reference path.

The serving engine's paged KV layout stores each layer's cache as a
global page pool ``(num_pages, page_size, heads, head_dim)`` plus a
per-slot page table ``(B, pages_per_slot)`` of physical page ids
(``inference/paged.py`` owns the host-side allocator).  Attention then
needs a gather through the table.  Two implementations share this
module:

- :func:`paged_attention_ref` — pure jnp, any query width: gather the
  slot's pages into a contiguous ``(B, T, H, D)`` view and run exactly
  the dense static-cache composition from ``models/gpt.py`` (same einsum
  strings, same ``-1e30`` mask, same softmax), so paged greedy decode is
  token-exact against the dense engine.  This is the CPU/tier-1 path and
  the chunk-prefill path.
- :func:`paged_attention_decode` — the Pallas kernel for width-1 decode
  (the steady-state hot path).  The page gather happens at the GRID
  level: the kv block index map reads the scalar-prefetched page table,
  so each grid cell DMAs exactly one physical page from the pool —
  no materialized ``(B, T, H, D)`` gather in HBM.  Pages past a slot's
  length clamp to the previous index (Pallas elides the repeat DMA) and
  a ``pl.when`` skips their compute, mirroring the causal-grid trick in
  the in-tree ``flash_attention.py``.  Softmax is online (f32 VMEM
  scratch); the per-page score/context products are VPU element-wise
  contractions — at decode shapes (one query row) kernel time is
  DMA-bound, which is the point: the kernel reads ``length`` rows where
  the dense tick reads ``max_len``.

On non-TPU backends the kernel runs under the Pallas interpreter for
numerics tests; the engine dispatches the reference path there.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30  # large-but-finite, matching the dense composition
_LANES = 128

# test hook: None = auto (kernel on TPU, reference elsewhere);
# True/False force the choice (CPU tests force True to run the kernel
# under the Pallas interpreter)
FORCE_KERNEL = None


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


def supported(page_size: int, head_dim: int) -> bool:
    """Whether the decode kernel handles this pool geometry (else the
    reference path runs).  Sub-128 lanes are padded by Mosaic in VMEM
    (same contract as flash_attention.py's head_dim handling)."""
    return page_size % 8 == 0 and head_dim % 8 == 0


def use_kernel(page_size: int, head_dim: int) -> bool:
    if FORCE_KERNEL is not None:
        return bool(FORCE_KERNEL)
    return (not _interpret()) and supported(page_size, head_dim)


def paged_write(pool, vals, page_table, pos):
    """Write ``vals`` (B, s, H, D) at logical rows ``[pos, pos+s)`` of
    each slot through the page table: row ``r`` of slot ``b`` lives at
    physical row ``page_table[b, r // P] * P + r % P`` of the flattened
    pool.  One scatter covers every slot (page-boundary straddles just
    split a slot's rows across two physical pages).  Inactive slots'
    table rows are NULL (page 0), so their garbage writes land in the
    reserved scratch page instead of live KV."""
    N, P, H, D = pool.shape
    B, s = vals.shape[:2]
    positions = pos[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    page_idx = positions // P
    # take_along_axis clips out-of-range page indices; active slots are
    # guarded by the engine's page-granular capacity check, inactive
    # slots only ever index page_idx < pages_per_slot (reserve <= max_len)
    phys = jnp.take_along_axis(page_table, page_idx, axis=1) * P \
        + positions % P
    flat = pool.reshape(N * P, H, D)
    flat = flat.at[phys.reshape(-1)].set(
        vals.astype(pool.dtype).reshape(B * s, H, D))
    return flat.reshape(N, P, H, D)


def paged_attention_ref(q, k_pool, v_pool, page_table, lengths):
    """Reference paged attention, any query width: gather + the exact
    dense static-cache composition (``models/gpt.py``).  ``lengths`` is
    each slot's write offset this call (the query at width index ``i``
    sits at global position ``lengths[b] + i`` and attends
    ``kpos <= qpos``); the current tokens' K/V are already in the pool
    (write-before-read, like the dense path)."""
    N, P, H, D = k_pool.shape
    B, s = q.shape[:2]
    rows = (page_table[:, :, None] * P
            + jnp.arange(P, dtype=jnp.int32)[None, None, :]).reshape(B, -1)
    kb = k_pool.reshape(N * P, H, D)[rows]        # (B, T, H, D)
    vb = v_pool.reshape(N * P, H, D)[rows]
    qpos = lengths[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    kpos = jnp.arange(rows.shape[1], dtype=jnp.int32)
    mask = (kpos[None, None, :] <= qpos[..., None])[:, None]   # (B,1,s,T)
    scale = 1.0 / math.sqrt(D)
    logits = jnp.einsum("bshe,bthe->bhst", q, kb.astype(q.dtype)) * scale
    logits = jnp.where(mask, logits, jnp.asarray(_NEG_INF, logits.dtype))
    probs = jax.nn.softmax(logits, -1)
    return jnp.einsum("bhst,bthe->bshe", probs, vb.astype(probs.dtype))


def _decode_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, page_size, n_pages, sm_scale):
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = len_ref[b]

    # pages past the one holding row `length` are clamped by the index
    # map (DMA elided) and skipped here
    @pl.when(j <= length // page_size)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)            # (H, D)
        kt = jnp.swapaxes(k_ref[0], 0, 1)              # (H, P, D) in-VMEM
        s = jnp.sum(kt.astype(jnp.float32) * q[:, None, :], axis=-1)
        s = s * sm_scale                               # (H, P)
        kpos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(kpos <= length, s, _NEG_INF)
        m_prev = m_ref[...]                            # (H, LANES)
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=1, keepdims=True)      # (H, 1)
        m_next = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next[:, :1])                 # (H, P)
        l_ref[...] = l_prev * alpha + jnp.sum(
            p, axis=1, keepdims=True) * jnp.ones_like(l_prev)
        vt = jnp.swapaxes(v_ref[0], 0, 1)              # (H, P, D)
        pv = jnp.sum(vt.astype(jnp.float32) * p[:, :, None], axis=1)
        acc_ref[...] = acc_ref[...] * alpha[:, :1] + pv
        m_ref[...] = m_next

    @pl.when(j == n_pages - 1)
    def _finish():
        l = l_ref[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def paged_attention_decode(q, k_pool, v_pool, page_table, lengths):
    """Width-1 paged decode attention via the Pallas kernel.  ``q`` is
    (B, 1, H, D); returns (B, 1, H, D)."""
    from jax.experimental.pallas import tpu as pltpu

    N, P, H, D = k_pool.shape
    B, s = q.shape[:2]
    assert s == 1, "the decode kernel is width-1; wider goes via ref"
    maxp = page_table.shape[1]
    sm_scale = 1.0 / math.sqrt(D)

    def kv_idx(b, j, pt_ref, len_ref):
        jj = jnp.minimum(j, len_ref[b] // P)
        return (pt_ref[b * maxp + jj], 0, 0, 0)

    kernel = functools.partial(_decode_kernel, page_size=P, n_pages=maxp,
                               sm_scale=sm_scale)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, maxp),
            in_specs=[
                pl.BlockSpec((1, 1, H, D), lambda b, j, *_: (b, 0, 0, 0)),
                pl.BlockSpec((1, P, H, D), kv_idx),
                pl.BlockSpec((1, P, H, D), kv_idx),
            ],
            out_specs=pl.BlockSpec((1, 1, H, D),
                                   lambda b, j, *_: (b, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((H, D), jnp.float32),       # acc
                pltpu.VMEM((H, _LANES), jnp.float32),  # m
                pltpu.VMEM((H, _LANES), jnp.float32),  # l
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, 1, H, D), q.dtype),
        interpret=_interpret(),
    )(page_table.reshape(-1).astype(jnp.int32),
      lengths.astype(jnp.int32), q, k_pool, v_pool)
    return out


def paged_attention(q, k_pool, v_pool, page_table, lengths):
    """Dispatch: the Pallas kernel on TPU for width-1 decode, the jnp
    reference otherwise (CPU/tier-1, chunk prefill, spec verify widths).
    ``FORCE_KERNEL`` overrides for interpreter-mode kernel tests."""
    P, D = k_pool.shape[1], k_pool.shape[3]
    if q.shape[1] == 1 and use_kernel(P, D):
        return paged_attention_decode(q, k_pool, v_pool, page_table,
                                      lengths)
    return paged_attention_ref(q, k_pool, v_pool, page_table, lengths)
