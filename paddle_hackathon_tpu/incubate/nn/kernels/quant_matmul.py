"""Pallas TPU weight-only quantized matmul + pure-jnp reference path.

Decode throughput is HBM-bandwidth-bound: every tick re-streams the full
weight matrices, and at decode batch sizes the MXU is idle waiting on
those loads.  Weight-only quantization (LLM.int8 / AWQ lineage) stores
each Linear weight as int8 (or fp8-e4m3) with one f32 scale per OUTPUT
channel and keeps activations bf16 — halving weight HBM traffic roughly
doubles effective GEMM bandwidth while the bf16 activation path
preserves quality.  Two implementations share this module:

- :func:`quant_matmul_ref` — pure jnp, any backend: widen the quantized
  weight to the activation dtype, one f32-accumulated dot, scale the
  columns.  Because the per-output-channel scale is constant over the
  contraction, ``(x @ (w_q * s)) == (x @ w_q) * s`` — dequant commutes
  out of the GEMM, so the reference IS the fused kernel's math.  This is
  the CPU/tier-1 path and the numerics oracle.
- :func:`quant_matmul_kernel` — the Pallas kernel: int8 tiles stream
  HBM→VMEM at half the bf16 bytes, widen to the activation dtype in
  VMEM registers (no dequantized copy ever exists in HBM), MXU dot with
  f32 accumulation, and the per-channel scale applied once on the f32
  accumulator in the epilogue.  The grid is (M tiles, N tiles) with the
  FULL contraction per cell — N innermost, so the activation tile stays
  resident in VMEM while weight tiles stream past it (the weight is the
  array whose bandwidth the quantization bought back).  Blocking only M
  and N keeps every output element's full contraction inside one dot, so
  kernel-vs-ref agreement is at the dot level: interpreter-mode runs
  match the reference to within dot reassociation (CPU XLA picks a
  K-tiling per output shape — observed <= 1 output-ulp on bf16
  activations, the serving dtype) — tests pin the tolerance.

Dispatch mirrors ``paged_attention``: the kernel on TPU for supported
geometry, the reference elsewhere; ``FORCE_KERNEL`` runs the kernel
under the Pallas interpreter for numerics tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_LANES = 128

# test hook: None = auto (kernel on TPU, reference elsewhere);
# True/False force the choice (CPU tests force True to run the kernel
# under the Pallas interpreter)
FORCE_KERNEL = None


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


def _is_quant_dtype(dtype) -> bool:
    if dtype == jnp.int8:
        return True
    fp8 = getattr(jnp, "float8_e4m3fn", None)
    return fp8 is not None and dtype == fp8


def supported(k: int, n: int, w_dtype) -> bool:
    """Whether the kernel handles this GEMM geometry (else the reference
    runs).  Lane-aligned K and N keep the int8 tiles on the (32, 128)
    native tiling; M is padded by the wrapper."""
    return k % _LANES == 0 and n % _LANES == 0 and _is_quant_dtype(w_dtype)


def use_kernel(k: int, n: int, w_dtype) -> bool:
    if FORCE_KERNEL is not None:
        return bool(FORCE_KERNEL)
    return (not _interpret()) and supported(k, n, w_dtype)


def quant_matmul_ref(x, w_q, scale):
    """Reference weight-only matmul: ``(x @ widen(w_q)) * scale`` with
    f32 accumulation, result in ``x.dtype``.  ``x`` (..., K) activation,
    ``w_q`` (K, N) int8/fp8, ``scale`` (N,) f32 per-output-channel."""
    acc = jnp.dot(x, w_q.astype(x.dtype),
                  preferred_element_type=jnp.float32)
    return (acc * scale).astype(x.dtype)


def _qmm_kernel(x_ref, w_ref, s_ref, o_ref):
    # widen int8→activation dtype in VMEM (the only dequantized form of
    # the weight anywhere), f32-accumulated MXU dot, scale the columns
    # of the f32 accumulator once in the epilogue
    acc = jnp.dot(x_ref[...], w_ref[...].astype(x_ref.dtype),
                  preferred_element_type=jnp.float32)
    o_ref[...] = (acc * s_ref[...]).astype(o_ref.dtype)


def quant_matmul_kernel(x2d, w_q, scale, block_m=128, block_n=256):
    """Fused dequant GEMM via the Pallas kernel.  ``x2d`` is (M, K);
    returns (M, N) in ``x2d.dtype``.  M is padded to the block size (the
    zero rows fall out of the slice); K and N must be lane-aligned
    (:func:`supported`)."""
    m, k = x2d.shape
    n = w_q.shape[1]
    if not supported(k, n, w_q.dtype):
        # a non-dividing N would leave tail output columns unwritten by
        # any grid cell (silent garbage); fail loudly — dispatch sends
        # unsupported geometry to the reference, and FORCE_KERNEL tests
        # must use supported shapes
        raise ValueError(
            f"quant_matmul_kernel requires lane-aligned K/N and an "
            f"int8/fp8 weight; got K={k}, N={n}, dtype={w_q.dtype}")
    bm = block_m if m >= block_m else -(-m // 8) * 8
    m_pad = -(-m // bm) * bm
    if m_pad != m:
        x2d = jnp.pad(x2d, ((0, m_pad - m), (0, 0)))
    bn = block_n if n % block_n == 0 else _LANES  # must divide lane-aligned N
    out = pl.pallas_call(
        _qmm_kernel,
        grid=(m_pad // bm, n // bn),
        in_specs=[
            # N innermost: the x tile's index map is constant over j, so
            # it stays in VMEM while the weight tiles stream
            pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m_pad, n), x2d.dtype),
        interpret=_interpret(),
    )(x2d, w_q, scale.astype(jnp.float32).reshape(1, n))
    return out[:m] if m_pad != m else out


# pht-lint: hot-root (decode-path GEMM entry)
def quant_matmul(x, w_q, scale, bias=None):
    """Dispatch: the Pallas fused-dequant kernel on TPU for supported
    geometry, the jnp reference otherwise (CPU/tier-1).  ``x`` (..., K)
    activations in bf16/f32, ``w_q`` (K, N) int8 or fp8-e4m3, ``scale``
    (N,) f32; optional ``bias`` (N,) added in the activation dtype on
    both paths (outside the kernel — XLA fuses it into the epilogue)."""
    k, n = w_q.shape
    lead = x.shape[:-1]
    x2d = x.reshape(-1, k)
    if use_kernel(k, n, w_q.dtype):
        out = quant_matmul_kernel(x2d, w_q, scale)
    else:
        out = quant_matmul_ref(x2d, w_q, scale)
    out = out.reshape(*lead, n)
    if bias is not None:
        out = out + bias.astype(out.dtype)
    return out
