"""Fused functionals (ref ``python/paddle/incubate/nn/functional/``).

The reference backs these with hand-written fused CUDA kernels
(``paddle/fluid/operators/fused/fused_attention_op.cu``,
``fused_feedforward_op.cu``, ``fused_gemm_epilogue_op.cu``,
``fused_layernorm_residual_dropout_bias.h``). Here attention is a Pallas
TPU kernel; the elementwise chains (layernorm+residual+dropout,
gemm+bias+activation) are expressed as single taped ops whose bodies XLA
fuses into one HBM pass — the TPU-correct way to get what the CUDA fusions
buy, without hand-scheduling.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ....core.autograd import apply_op
from ....core.tensor import Tensor
from ..kernels import flash_attention as _fa


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _dropout(h, rate, key, mode="upscale_in_train"):
    """Shared dropout body for the fused chains. key=None -> identity."""
    if key is None:
        return h
    keep = jax.random.bernoulli(key, 1.0 - rate, h.shape)
    if mode == "upscale_in_train":
        return jnp.where(keep, h / (1.0 - rate), 0.0).astype(h.dtype)
    return jnp.where(keep, h, 0.0).astype(h.dtype)


def _pad_lanes(x, d):
    pad = (-d) % 128
    if pad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, pad)))
    return x


def flash_attention_bshd(query, key, value, causal=False, sm_scale=None):
    """Flash attention over paddle-layout (batch, seq, heads, head_dim).

    Falls back to the caller's XLA path by raising if shapes don't qualify.
    """
    b, sq, h, d = query.shape
    skv = key.shape[1]
    if not _fa.supported(sq, skv):
        raise ValueError(f"flash kernel unsupported for seq ({sq},{skv})")
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)

    def fn(q, k, v):
        def to_bhd(x, s):
            x = jnp.swapaxes(x, 1, 2)           # b h s d
            x = x.reshape(b * h, s, d)
            return _pad_lanes(x, d)

        out = _fa.flash_attention_bhd(
            to_bhd(q, sq), to_bhd(k, skv), to_bhd(v, skv), causal, scale)
        out = out[:, :, :d].reshape(b, h, sq, d)
        return jnp.swapaxes(out, 1, 2)          # b s h d

    return apply_op("flash_attention", fn, [_t(query), _t(key), _t(value)])


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, name=None):
    """paddle.incubate flash_attention-style API: returns (out, softmax)."""
    assert not return_softmax, "flash kernel never materialises softmax"
    if dropout:
        raise NotImplementedError(
            "attention-probability dropout inside the flash kernel is not "
            "implemented; use nn.functional.scaled_dot_product_attention "
            "(XLA path) when dropout_p > 0")
    out = flash_attention_bshd(query, key, value, causal=causal)
    return out, None


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     residual=None, bias=None, dropout_rate=0.0,
                     training=True, rng_key=None):
    """layernorm(residual + dropout(x + bias)) in one taped op.

    Ref ``fused_layernorm_residual_dropout_bias.h`` — one HBM pass; XLA
    fuses this body into a single loop the same way.
    Returns (out, residual_out).
    """
    args = [_t(x)]
    names = ["x"]
    for nm, v in (("norm_weight", norm_weight), ("norm_bias", norm_bias),
                  ("residual", residual), ("bias", bias)):
        if v is not None:
            args.append(_t(v))
            names.append(nm)

    drop_key = None
    if dropout_rate > 0.0 and training:
        if rng_key is None:
            from ....core import random as core_random
            drop_key = core_random.split_key()
        else:
            drop_key = rng_key

    def fn(*vals):
        d = dict(zip(names, vals))
        h = d["x"]
        if "bias" in d:
            h = h + d["bias"]
        h = _dropout(h, dropout_rate, drop_key)
        if "residual" in d:
            h = h + d["residual"]
        res_out = h
        mu = jnp.mean(h, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(h - mu), axis=-1, keepdims=True)
        y = (h - mu) * jax.lax.rsqrt(var + epsilon)
        if "norm_weight" in d:
            y = y * d["norm_weight"]
        if "norm_bias" in d:
            y = y + d["norm_bias"]
        return y.astype(h.dtype), res_out

    return apply_op("fused_layer_norm", fn, args, n_outputs=2)


def fused_dropout_add(x, y, p=0.0, training=True, mode="upscale_in_train"):
    """dropout(x) + y as one op (ref fused_dropout_add in incubate)."""
    drop_key = None
    if p > 0.0 and training:
        from ....core import random as core_random
        drop_key = core_random.split_key()

    def fn(a, b):
        if drop_key is None:
            # upscale_in_train: eval is identity (train already rescaled);
            # downscale_in_infer: eval scales by the keep probability.
            if not training and p > 0.0 and mode != "upscale_in_train":
                a = a * (1.0 - p)
            return a + b
        return _dropout(a, p, drop_key, mode) + b

    return apply_op("fused_dropout_add", fn, [_t(x), _t(y)])


def fused_linear(x, weight, bias=None, transpose_weight=False,
                 activation=None, name=None):
    """matmul + bias + activation epilogue (ref fused_gemm_epilogue_op.cu,
    cublasLt epilogue). XLA fuses the epilogue into the MXU matmul."""
    args = [_t(x), _t(weight)] + ([_t(bias)] if bias is not None else [])

    def fn(xv, wv, *rest):
        if transpose_weight:
            wv = wv.T
        out = jnp.matmul(xv, wv)
        if rest:
            out = out + rest[0]
        if activation in ("gelu",):
            out = jax.nn.gelu(out)
        elif activation in ("relu",):
            out = jax.nn.relu(out)
        return out

    return apply_op("fused_linear", fn, args)


def fused_feedforward(x, linear1_weight, linear1_bias, linear2_weight,
                      linear2_bias, ln1_scale=None, ln1_bias=None,
                      dropout1_rate=0.5, dropout2_rate=0.5,
                      activation="relu", ln1_epsilon=1e-5,
                      pre_layer_norm=False, training=True):
    """Transformer FFN block as one taped op (ref fused_feedforward_op.cu).

    out = residual + dropout2(linear2(dropout1(act(linear1(ln(x))))))
    (post-LN applies layer_norm at the end instead).
    """
    args = [_t(x), _t(linear1_weight), _t(linear1_bias), _t(linear2_weight),
            _t(linear2_bias)]
    names = ["x", "w1", "b1", "w2", "b2"]
    for nm, v in (("ln_scale", ln1_scale), ("ln_bias", ln1_bias)):
        if v is not None:
            args.append(_t(v))
            names.append(nm)

    keys = [None, None]
    if training:
        from ....core import random as core_random
        if dropout1_rate > 0.0:
            keys[0] = core_random.split_key()
        if dropout2_rate > 0.0:
            keys[1] = core_random.split_key()

    def _ln(h, d, eps):
        mu = jnp.mean(h, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(h - mu), axis=-1, keepdims=True)
        y = (h - mu) * jax.lax.rsqrt(var + eps)
        if "ln_scale" in d:
            y = y * d["ln_scale"]
        if "ln_bias" in d:
            y = y + d["ln_bias"]
        return y.astype(h.dtype)

    def fn(*vals):
        d = dict(zip(names, vals))
        residual = d["x"]
        h = _ln(d["x"], d, ln1_epsilon) if pre_layer_norm else d["x"]
        h = jnp.matmul(h, d["w1"]) + d["b1"]
        h = jax.nn.gelu(h) if activation == "gelu" else jax.nn.relu(h)
        h = _dropout(h, dropout1_rate, keys[0])
        h = jnp.matmul(h, d["w2"]) + d["b2"]
        h = _dropout(h, dropout2_rate, keys[1])
        out = residual + h
        if not pre_layer_norm:
            out = _ln(out, d, ln1_epsilon)
        return out

    return apply_op("fused_feedforward", fn, args)


__all__ = [
    "flash_attention", "flash_attention_bshd", "fused_layer_norm",
    "fused_dropout_add", "fused_linear", "fused_feedforward",
]
