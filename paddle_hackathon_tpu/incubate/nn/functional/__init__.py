"""Fused functionals (ref ``python/paddle/incubate/nn/functional/``).

The reference backs these with hand-written fused CUDA kernels
(``paddle/fluid/operators/fused/fused_attention_op.cu``,
``fused_feedforward_op.cu``, ``fused_gemm_epilogue_op.cu``,
``fused_layernorm_residual_dropout_bias.h``). Here attention is a Pallas
TPU kernel; the elementwise chains (layernorm+residual+dropout,
gemm+bias+activation) are expressed as single taped ops whose bodies XLA
fuses into one HBM pass — the TPU-correct way to get what the CUDA fusions
buy, without hand-scheduling.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ....core.autograd import apply_op
from ....core.tensor import Tensor
from ..kernels import flash_attention as _fa


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _dropout(h, rate, key, mode="upscale_in_train"):
    """Shared dropout body for the fused chains. key=None -> identity."""
    if key is None:
        return h
    keep = jax.random.bernoulli(key, 1.0 - rate, h.shape)
    if mode == "upscale_in_train":
        return jnp.where(keep, h / (1.0 - rate), 0.0).astype(h.dtype)
    return jnp.where(keep, h, 0.0).astype(h.dtype)


def flash_attention_bshd(query, key, value, causal=False, sm_scale=None,
                         dropout_p=0.0, seed=None):
    """Flash attention over paddle-layout (batch, seq, heads, head_dim).

    ``dropout_p`` drops attention probabilities inside the kernel (ref
    ``fused_attention_op.cu`` attn_dropout); the mask is regenerated from
    ``seed`` in the backward, never materialised. Falls back to the
    caller's XLA path by raising if shapes don't qualify.
    """
    b, sq, h, d = query.shape
    skv = key.shape[1]
    if not _fa.supported(sq, skv):
        raise ValueError(f"flash kernel unsupported for seq ({sq},{skv})")
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    if dropout_p and seed is None:
        from ....core import random as core_random
        key_arr = core_random.split_key()
        seed = jax.random.randint(key_arr, (1,), -2**31, 2**31 - 1,
                                  dtype=jnp.int32)

    def fn(q, k, v):
        def to_bhd(x, s):
            # no explicit lane padding: Mosaic pads d<128 in-register, and an
            # explicit pad materialises 2x HBM copies of q/k/v (measured -8%
            # e2e on gpt2-small); odd head dims (80/96/256) verified native
            x = jnp.swapaxes(x, 1, 2)           # b h s d
            return x.reshape(b * h, s, d)

        qb, kb, vb = to_bhd(q, sq), to_bhd(k, skv), to_bhd(v, skv)
        _fa.maybe_autotune(qb, kb, vb, causal, scale)
        out = _fa.flash_attention_bhd(qb, kb, vb, causal, scale,
                                      float(dropout_p), seed)
        out = out.reshape(b, h, sq, d)
        return jnp.swapaxes(out, 1, 2)          # b s h d

    return apply_op("flash_attention", fn, [_t(query), _t(key), _t(value)])


def flash_attention_qkv_packed(qkv, num_heads, causal=True, sm_scale=None,
                               dropout_p=0.0, seed=None):
    """Flash attention directly on the fused qkv projection output
    ``(b, s, 3*num_heads*head_dim)`` — no head split/merge ever touches
    HBM (the (b,s,h,d) reorganization around the bhd kernel costs ~10% of
    a gpt2-class train step in layout copies). Returns ``(b, s, h*d)``
    ready for the output projection. Raises ValueError when shapes don't
    qualify so callers can fall back.
    """
    from ..kernels import flash_attention_packed as _fap

    qkv = _t(qkv)
    b, s, hd3 = qkv.shape
    head_dim = hd3 // 3 // num_heads
    if not _fap.supported(s, s, num_heads, head_dim, qkv.dtype):
        raise ValueError(
            f"packed flash kernel unsupported for seq {s}, heads {num_heads}, "
            f"head_dim {head_dim}, dtype {qkv.dtype}")
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(head_dim)
    if dropout_p and seed is None:
        from ....core import random as core_random
        key_arr = core_random.split_key()
        seed = jax.random.randint(key_arr, (1,), -2**31, 2**31 - 1,
                                  dtype=jnp.int32)

    def fn(qkv_val):
        return _fap.flash_attention_packed(qkv_val, num_heads, causal,
                                           scale, float(dropout_p), seed)

    return apply_op("flash_attention_packed", fn, [qkv])


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, name=None):
    """paddle.incubate flash_attention-style API: returns (out, softmax)."""
    assert not return_softmax, "flash kernel never materialises softmax"
    out = flash_attention_bshd(query, key, value, causal=causal,
                               dropout_p=dropout)
    return out, None


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     residual=None, bias=None, dropout_rate=0.0,
                     training=True, rng_key=None):
    """layernorm(residual + dropout(x + bias)) in one taped op.

    Ref ``fused_layernorm_residual_dropout_bias.h`` — one HBM pass; XLA
    fuses this body into a single loop the same way.
    Returns (out, residual_out).
    """
    args = [_t(x)]
    names = ["x"]
    for nm, v in (("norm_weight", norm_weight), ("norm_bias", norm_bias),
                  ("residual", residual), ("bias", bias)):
        if v is not None:
            args.append(_t(v))
            names.append(nm)

    drop_key = None
    if dropout_rate > 0.0 and training:
        if rng_key is None:
            from ....core import random as core_random
            drop_key = core_random.split_key()
        else:
            drop_key = rng_key

    def fn(*vals):
        d = dict(zip(names, vals))
        h = d["x"]
        if "bias" in d:
            h = h + d["bias"]
        h = _dropout(h, dropout_rate, drop_key)
        if "residual" in d:
            h = h + d["residual"]
        res_out = h
        mu = jnp.mean(h, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(h - mu), axis=-1, keepdims=True)
        y = (h - mu) * jax.lax.rsqrt(var + epsilon)
        if "norm_weight" in d:
            y = y * d["norm_weight"]
        if "norm_bias" in d:
            y = y + d["norm_bias"]
        return y.astype(h.dtype), res_out

    return apply_op("fused_layer_norm", fn, args, n_outputs=2)


def fused_dropout_add(x, y, p=0.0, training=True, mode="upscale_in_train"):
    """dropout(x) + y as one op (ref fused_dropout_add in incubate)."""
    drop_key = None
    if p > 0.0 and training:
        from ....core import random as core_random
        drop_key = core_random.split_key()

    def fn(a, b):
        if drop_key is None:
            # upscale_in_train: eval is identity (train already rescaled);
            # downscale_in_infer: eval scales by the keep probability.
            if not training and p > 0.0 and mode != "upscale_in_train":
                a = a * (1.0 - p)
            return a + b
        return _dropout(a, p, drop_key, mode) + b

    return apply_op("fused_dropout_add", fn, [_t(x), _t(y)])


def fused_linear(x, weight, bias=None, transpose_weight=False,
                 activation=None, name=None):
    """matmul + bias + activation epilogue (ref fused_gemm_epilogue_op.cu,
    cublasLt epilogue). XLA fuses the epilogue into the MXU matmul."""
    args = [_t(x), _t(weight)] + ([_t(bias)] if bias is not None else [])

    def fn(xv, wv, *rest):
        if transpose_weight:
            wv = wv.T
        out = jnp.matmul(xv, wv)
        if rest:
            out = out + rest[0]
        if activation in ("gelu",):
            out = jax.nn.gelu(out)
        elif activation in ("relu",):
            out = jax.nn.relu(out)
        return out

    return apply_op("fused_linear", fn, args)


def fused_feedforward(x, linear1_weight, linear1_bias, linear2_weight,
                      linear2_bias, ln1_scale=None, ln1_bias=None,
                      dropout1_rate=0.5, dropout2_rate=0.5,
                      activation="relu", ln1_epsilon=1e-5,
                      pre_layer_norm=False, training=True):
    """Transformer FFN block as one taped op (ref fused_feedforward_op.cu).

    out = residual + dropout2(linear2(dropout1(act(linear1(ln(x))))))
    (post-LN applies layer_norm at the end instead).
    """
    args = [_t(x), _t(linear1_weight), _t(linear2_weight)]
    names = ["x", "w1", "w2"]
    for nm, v in (("b1", linear1_bias), ("b2", linear2_bias)):
        if v is not None:
            args.append(_t(v))
            names.append(nm)
    for nm, v in (("ln_scale", ln1_scale), ("ln_bias", ln1_bias)):
        if v is not None:
            args.append(_t(v))
            names.append(nm)

    keys = [None, None]
    if training:
        from ....core import random as core_random
        if dropout1_rate > 0.0:
            keys[0] = core_random.split_key()
        if dropout2_rate > 0.0:
            keys[1] = core_random.split_key()

    def _ln(h, d, eps):
        mu = jnp.mean(h, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(h - mu), axis=-1, keepdims=True)
        y = (h - mu) * jax.lax.rsqrt(var + eps)
        if "ln_scale" in d:
            y = y * d["ln_scale"]
        if "ln_bias" in d:
            y = y + d["ln_bias"]
        return y.astype(h.dtype)

    def fn(*vals):
        d = dict(zip(names, vals))
        residual = d["x"]
        h = _ln(d["x"], d, ln1_epsilon) if pre_layer_norm else d["x"]
        h = jnp.matmul(h, d["w1"])
        if "b1" in d:
            h = h + d["b1"]
        h = jax.nn.gelu(h) if activation == "gelu" else jax.nn.relu(h)
        h = _dropout(h, dropout1_rate, keys[0])
        h = jnp.matmul(h, d["w2"])
        if "b2" in d:
            h = h + d["b2"]
        h = _dropout(h, dropout2_rate, keys[1])
        out = residual + h
        if not pre_layer_norm:
            out = _ln(out, d, ln1_epsilon)
        return out

    return apply_op("fused_feedforward", fn, args)


__all__ = [
    "flash_attention", "flash_attention_bshd", "fused_layer_norm",
    "fused_dropout_add", "fused_linear", "fused_feedforward",
]


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """matmul + bias epilogue (ref fused_gemm_epilogue via cublasLt)."""
    args = [_t(x), _t(y)] + ([_t(bias)] if bias is not None else [])

    def fn(xv, yv, *rest):
        if transpose_x:
            xv = jnp.swapaxes(xv, -1, -2)
        if transpose_y:
            yv = jnp.swapaxes(yv, -1, -2)
        out = jnp.matmul(xv, yv)
        if rest:
            out = out + rest[0]
        return out
    return apply_op("fused_matmul_bias", fn, args)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", name=None):
    """LN(residual + dropout(x + bias)) in one fused op (ref
    fused_bias_dropout_residual_layer_norm op)."""
    out, _ = fused_layer_norm(x, ln_scale, ln_bias, epsilon=ln_epsilon,
                              residual=residual, bias=bias,
                              dropout_rate=dropout_rate, training=training)
    return out


def fused_multi_head_attention(
        x, qkv_weight, linear_weight, pre_layer_norm=False,
        pre_ln_scale=None, pre_ln_bias=None, ln_scale=None, ln_bias=None,
        pre_ln_epsilon=1e-5, qkv_bias=None, linear_bias=None,
        cache_kv=None, attn_mask=None, dropout_rate=0.5,
        attn_dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", ring_id=-1, add_residual=True,
        time_step=None, name=None):
    """Functional form of the fused attention block (ref
    fused_attention_op.cu): optional pre-LN -> qkv -> MHA -> out proj ->
    bias+dropout+residual(+post-LN).

    With ``cache_kv`` (shape (2, batch, heads, max_seq, head_dim), the
    reference's CacheKV layout) the call runs incremental decoding: this
    step's k/v are written at ``time_step`` (scalar, default 0 = prefill)
    and queries attend over every cached position ≤ their global position.
    Since arrays are immutable here, the updated cache is RETURNED:
    ``(out, cache_kv_out)`` instead of the reference's in-place write.
    """
    import math as _math
    h = _t(x)
    residual = h
    if pre_layer_norm:
        h, _ = fused_layer_norm(h, pre_ln_scale, pre_ln_bias,
                                epsilon=pre_ln_epsilon)
    qkvw = _t(qkv_weight)  # (3, num_heads, head_dim, embed)
    _, n_heads, head_dim, embed = qkvw.shape
    has_bias = qkv_bias is not None
    has_mask = attn_mask is not None

    drop_key = None
    if attn_dropout_rate > 0.0 and training:
        from ....core import random as core_random
        drop_key = core_random.split_key()

    def qkv_fn(hv, wv, *rest):
        it = iter(rest)
        b = next(it) if has_bias else None
        mask = next(it) if has_mask else None
        q, k, v = (jnp.einsum("bsd,hed->bshe", hv, wv[i])
                   for i in range(3))
        if b is not None:
            q = q + b[0][None, None]
            k = k + b[1][None, None]
            v = v + b[2][None, None]
        logits = jnp.einsum("bshe,bthe->bhst", q, k) / _math.sqrt(head_dim)
        if mask is not None:
            logits = logits + mask
        probs = jax.nn.softmax(logits, -1)
        probs = _dropout(probs, attn_dropout_rate, drop_key)
        ctx = jnp.einsum("bhst,bthe->bshe", probs, v)
        return ctx.reshape(ctx.shape[0], ctx.shape[1], -1)

    def qkv_cached_fn(hv, wv, cachev, tstep, *rest):
        """Incremental decoding against a static (2, B, H, Tmax, D) cache
        (ref fused_multi_transformer_op.cu decode phase): write this call's
        k/v at [time_step, time_step+s), attend each query i over key
        positions j <= time_step + i.  Functional: returns the new cache."""
        it = iter(rest)
        b = next(it) if has_bias else None
        mask = next(it) if has_mask else None
        q, k, v = (jnp.einsum("bsd,hed->bshe", hv, wv[i])
                   for i in range(3))
        if b is not None:
            q = q + b[0][None, None]
            k = k + b[1][None, None]
            v = v + b[2][None, None]
        t0 = tstep.astype(jnp.int32)
        kc, vc = cachev[0], cachev[1]                    # (B, H, Tmax, D)
        k_bh = jnp.swapaxes(k, 1, 2).astype(kc.dtype)    # (B, H, s, D)
        v_bh = jnp.swapaxes(v, 1, 2).astype(vc.dtype)
        zero = jnp.zeros((), jnp.int32)
        kc = jax.lax.dynamic_update_slice(kc, k_bh, (zero, zero, t0, zero))
        vc = jax.lax.dynamic_update_slice(vc, v_bh, (zero, zero, t0, zero))
        logits = jnp.einsum("bshe,bhte->bhst", q,
                            kc.astype(q.dtype)) / _math.sqrt(head_dim)
        s, t_max = q.shape[1], kc.shape[2]
        qpos = t0 + jnp.arange(s)[:, None]               # (s, 1) global pos
        kpos = jnp.arange(t_max)[None, :]
        logits = jnp.where((kpos <= qpos)[None, None], logits,
                           jnp.asarray(-1e30, logits.dtype))
        if mask is not None:
            logits = logits + mask
        probs = jax.nn.softmax(logits, -1)
        probs = _dropout(probs, attn_dropout_rate, drop_key)
        ctx = jnp.einsum("bhst,bhte->bshe", probs, vc.astype(probs.dtype))
        return (ctx.reshape(ctx.shape[0], ctx.shape[1], -1),
                jnp.stack([kc, vc]))

    new_cache = None
    args = [h, qkvw]
    if cache_kv is not None:
        ts = time_step if time_step is not None else jnp.asarray(
            0, jnp.int32)
        args += [_t(cache_kv), _t(ts)]
    if has_bias:
        args.append(_t(qkv_bias))
    if has_mask:
        args.append(_t(attn_mask))
    if cache_kv is not None:
        ctx, new_cache = apply_op("fused_mha_core_cached", qkv_cached_fn,
                                  args, n_outputs=2)
    else:
        ctx = apply_op("fused_mha_core", qkv_fn, args)
    out = fused_linear(ctx, linear_weight, linear_bias)
    if add_residual:
        out = fused_dropout_add(out, residual, p=dropout_rate,
                                training=training, mode=mode)
    if not pre_layer_norm:
        out, _ = fused_layer_norm(out, ln_scale, ln_bias, epsilon=ln_epsilon)
    if new_cache is not None:
        return out, new_cache
    return out


def fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, pre_layer_norm=True,
        epsilon=1e-5, cache_kvs=None, time_step=None, attn_mask=None,
        dropout_rate=0.0, activation="gelu", training=False,
        mode="upscale_in_train", trans_qkvw=True, ring_id=-1, name=None):
    """Stacked fused transformer decoder blocks (ref
    fused_multi_transformer_op.cu). Returns (out, cache_kvs)."""
    h = _t(x)
    n_layers = len(qkv_weights)
    if not trans_qkvw:
        # weights arrive (embed, 3, heads, head_dim): move embed last to the
        # (3, heads, head_dim, embed) layout the attention core consumes
        from ....ops import manipulation as _M
        qkv_weights = [_M.transpose(_t(w), [1, 2, 3, 0])
                       for w in qkv_weights]
    new_caches = [] if cache_kvs is not None else None
    for i in range(n_layers):
        att = fused_multi_head_attention(
            h, qkv_weights[i], linear_weights[i],
            pre_layer_norm=pre_layer_norm,
            pre_ln_scale=ln_scales[i] if ln_scales else None,
            pre_ln_bias=ln_biases[i] if ln_biases else None,
            qkv_bias=qkv_biases[i] if qkv_biases else None,
            linear_bias=linear_biases[i] if linear_biases else None,
            cache_kv=cache_kvs[i] if cache_kvs is not None else None,
            time_step=time_step,
            attn_mask=attn_mask, dropout_rate=dropout_rate,
            attn_dropout_rate=dropout_rate, training=training, mode=mode)
        if cache_kvs is not None:
            h, cache_i = att
            new_caches.append(cache_i)
        else:
            h = att
        h = fused_feedforward(
            h, ffn1_weights[i], ffn1_biases[i] if ffn1_biases else None,
            ffn2_weights[i], ffn2_biases[i] if ffn2_biases else None,
            ln1_scale=ffn_ln_scales[i] if ffn_ln_scales else None,
            ln1_bias=ffn_ln_biases[i] if ffn_ln_biases else None,
            dropout1_rate=dropout_rate, dropout2_rate=dropout_rate,
            activation=activation, pre_layer_norm=pre_layer_norm,
            training=training)
    return h, (new_caches if new_caches is not None else cache_kvs)


__all__ += ["fused_matmul_bias", "fused_bias_dropout_residual_layer_norm",
            "fused_multi_head_attention", "fused_multi_transformer"]
