from .auto_checkpoint import (TrainEpochRange, train_epoch_range)  # noqa: F401
