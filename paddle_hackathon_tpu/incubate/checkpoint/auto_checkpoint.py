"""Auto-checkpoint: transparent epoch-loop checkpoint/resume.

Ref ``fluid/incubate/checkpoint/auto_checkpoint.py`` — ``TrainEpochRange``
(``:267``) wraps the epoch loop, periodically snapshots training state keyed
by job id (env ``PADDLE_JOB_ID``), and transparently resumes from the last
snapshot after a relaunch (``train_epoch_range:597``) — the recovery half of
elastic training (SURVEY §5.3).

Eager-mode design: the reference snapshots the static Executor+Program;
here the user registers any objects exposing ``state_dict``/
``set_state_dict`` (Layer, Optimizer, LRScheduler) and the range snapshots
them atomically (write-tmp + rename through the FS abstraction) after each
epoch, at most once per ``save_checkpoint_inter`` seconds.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from ...framework.io import load as _load
from ...framework.io import save as _save
from ...utils.fs import FS, LocalFS

_CKPT_FILE = "auto_ckpt.pdparams"
_META_FILE = "auto_ckpt_meta.pdparams"


class TrainEpochRange:
    """Iterate epochs with transparent resume (ref ``:267``)."""

    def __init__(self, max_epoch_num: int, name: Optional[str] = None,
                 checkpoint_inter: Optional[float] = None,
                 fs: Optional[FS] = None,
                 checkpoint_dir: Optional[str] = None):
        self.max_epoch_num = int(max_epoch_num)
        job = os.environ.get("PADDLE_JOB_ID", "default")
        self.name = name or "main"
        self._inter = (float(checkpoint_inter) if checkpoint_inter is not None
                       else float(os.environ.get(
                           "PADDLE_CHECKPOINT_INTER", 0.0)))
        self._fs = fs or LocalFS()
        root = checkpoint_dir or os.environ.get("PADDLE_CHECKPOINT_DIR",
                                                "./auto_checkpoint")
        self._dir = os.path.join(root, job, self.name)
        self._objs = {}
        self._last_save = 0.0
        self._restored_epoch = -1
        self._maybe_restore_meta()

    # -- registration --------------------------------------------------------
    def register(self, **objs) -> "TrainEpochRange":
        """Register named stateful objects (state_dict/set_state_dict)."""
        for k, o in objs.items():
            if not hasattr(o, "state_dict") or not hasattr(o, "set_state_dict"):
                raise TypeError(f"{k!r} lacks state_dict/set_state_dict")
            self._objs[k] = o
        if self._restored_epoch >= 0:
            self._restore_states()
        return self

    # -- persistence ---------------------------------------------------------
    def _meta_path(self):
        return os.path.join(self._dir, _META_FILE)

    def _ckpt_path(self):
        return os.path.join(self._dir, _CKPT_FILE)

    def _fs_load(self, path):
        """Read a snapshot file through the FS abstraction: remote stores
        are downloaded to a local temp file first (framework.io itself only
        reads local paths)."""
        if isinstance(self._fs, LocalFS):
            return _load(path)
        import tempfile
        with tempfile.NamedTemporaryFile(suffix=".pdparams") as tf:
            self._fs.download(path, tf.name)
            return _load(tf.name)

    def _fs_save(self, obj, path):
        """Atomic write through the FS: serialize locally, then upload/
        rename into place."""
        import tempfile
        if isinstance(self._fs, LocalFS):
            tmp = path + ".tmp"
            _save(obj, tmp)
            self._fs.mv(tmp, path, overwrite=True)
            return
        with tempfile.NamedTemporaryFile(suffix=".pdparams",
                                         delete=False) as tf:
            local_tmp = tf.name
        try:
            _save(obj, local_tmp)
            self._fs.upload(local_tmp, path)
        finally:
            os.unlink(local_tmp)

    def _maybe_restore_meta(self):
        if self._fs.is_exist(self._meta_path()):
            meta = self._fs_load(self._meta_path())
            self._restored_epoch = int(meta["epoch"])

    def _restore_states(self):
        if not self._fs.is_exist(self._ckpt_path()):
            return
        states = self._fs_load(self._ckpt_path())
        for k, obj in self._objs.items():
            if k in states:
                obj.set_state_dict(states[k])

    def save_checkpoint(self, epoch: int) -> None:
        self._fs.mkdirs(self._dir)
        states = {k: o.state_dict() for k, o in self._objs.items()}
        self._fs_save(states, self._ckpt_path())
        self._fs_save({"epoch": epoch, "max_epoch_num": self.max_epoch_num},
                      self._meta_path())
        self._last_save = time.monotonic()

    # -- iteration -----------------------------------------------------------
    @property
    def restored_from(self) -> int:
        """Last completed epoch restored from disk (-1 if fresh)."""
        return self._restored_epoch

    def get(self):  # reference spelling: `for i in tr.get():`
        return iter(self)

    def __iter__(self):
        start = self._restored_epoch + 1
        for epoch in range(start, self.max_epoch_num):
            yield epoch
            due = (time.monotonic() - self._last_save) >= self._inter
            if due or epoch == self.max_epoch_num - 1:
                self.save_checkpoint(epoch)


def train_epoch_range(max_epoch_num: int, save_checkpoint_inter=None
                      ) -> TrainEpochRange:
    """Ref module-level ``train_epoch_range`` (``auto_checkpoint.py:597``)."""
    return TrainEpochRange(max_epoch_num,
                           checkpoint_inter=save_checkpoint_inter)
