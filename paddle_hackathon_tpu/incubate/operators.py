"""incubate functional ops: segment reductions, graph message passing,
fused-softmax masks, identity_loss.

Ref ``python/paddle/incubate/__init__.py`` exports; kernels ref
``paddle/phi/kernels/{segment_pool,graph_send_recv,graph_reindex,
graph_khop_sampler,graph_sample_neighbors}_kernel.*`` and
``operators/fused/fused_softmax_mask{,_upper_triangle}_op.cu``.

TPU notes: segment/send-recv reductions lower to XLA scatter-reduce
(``jax.ops.segment_*``) which the compiler vectorizes; the sampling ops
(khop/reindex/neighbors) are host-side (data-dependent output shapes can't
live under jit — the reference runs them outside the compiled region too,
in its dataloader-side graph pipeline).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply_op, no_grad
from ..core.tensor import Tensor

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "graph_send_recv", "graph_reindex", "graph_khop_sampler",
    "graph_sample_neighbors", "softmax_mask_fuse",
    "softmax_mask_fuse_upper_triangle", "identity_loss",
]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _nseg(segment_ids):
    ids = segment_ids._value if isinstance(segment_ids, Tensor) else segment_ids
    try:
        arr = np.asarray(ids)
    except Exception as e:  # jit tracer: num_segments is data-dependent
        raise NotImplementedError(
            "segment_* ops need concrete segment_ids (num_segments = "
            "max(ids)+1 is data-dependent, which XLA cannot shape); call "
            "them eagerly, outside jit.to_static") from e
    return int(arr.max()) + 1 if arr.size else 0


def _segment(name, reducer, empty_fill):
    def op(data, segment_ids, name_=None):
        n = _nseg(segment_ids)

        def fn(d, ids):
            out = reducer(d, ids.astype(jnp.int32), num_segments=n)
            if empty_fill is not None:
                # empty segments produce +-inf for max/min; the reference
                # writes 0 there (segment_pool_kernel)
                counts = jax.ops.segment_sum(jnp.ones_like(ids, jnp.int32),
                                             ids.astype(jnp.int32),
                                             num_segments=n)
                shape = (n,) + (1,) * (d.ndim - 1)
                out = jnp.where(counts.reshape(shape) > 0, out, empty_fill)
            return out
        return apply_op(name, fn, [_t(data), _t(segment_ids)])
    op.__name__ = name
    return op


segment_sum = _segment("segment_sum", jax.ops.segment_sum, None)
segment_mean = _segment(
    "segment_mean",
    lambda d, ids, num_segments: jax.ops.segment_sum(d, ids, num_segments)
    / jnp.maximum(jax.ops.segment_sum(
        jnp.ones(d.shape[:1], d.dtype), ids, num_segments), 1.0
    ).reshape((num_segments,) + (1,) * (d.ndim - 1)),
    None)
segment_max = _segment("segment_max", jax.ops.segment_max, 0.0)
segment_min = _segment("segment_min", jax.ops.segment_min, 0.0)


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """Gather x[src], reduce onto dst (ref phi GraphSendRecvKernel)."""
    pool = pool_type.lower()
    red = {"sum": jax.ops.segment_sum, "mean": None,
           "max": jax.ops.segment_max, "min": jax.ops.segment_min}[pool]
    n = (int(out_size) if out_size
         else int(np.asarray(_t(x)._value.shape[0])))

    def fn(v, src, dst):
        msgs = v[src.astype(jnp.int32)]
        dsti = dst.astype(jnp.int32)
        if pool == "mean":
            s = jax.ops.segment_sum(msgs, dsti, num_segments=n)
            c = jax.ops.segment_sum(jnp.ones_like(dsti, v.dtype), dsti,
                                    num_segments=n)
            return s / jnp.maximum(c, 1.0).reshape((n,) + (1,) * (v.ndim - 1))
        out = red(msgs, dsti, num_segments=n)
        if pool in ("max", "min"):
            c = jax.ops.segment_sum(jnp.ones_like(dsti, jnp.int32), dsti,
                                    num_segments=n)
            out = jnp.where(c.reshape((n,) + (1,) * (v.ndim - 1)) > 0, out, 0)
        return out
    return apply_op("graph_send_recv", fn,
                    [_t(x), _t(src_index), _t(dst_index)])


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """Reindex a sampled subgraph to local ids (ref phi GraphReindexKernel).
    Host-side: output shape depends on the unique node set."""
    with no_grad():
        xs = np.asarray(_t(x)._value)
        nb = np.asarray(_t(neighbors)._value)
        cnt = np.asarray(_t(count)._value)
        uniq, inv = np.unique(np.concatenate([xs, nb]), return_inverse=True)
        # reference keeps input-x ids first in the local numbering
        order = {int(v): i for i, v in enumerate(xs)}
        for v in uniq:
            if int(v) not in order:
                order[int(v)] = len(order)
        remap = np.array([order[int(v)] for v in np.concatenate([xs, nb])])
        reindex_src = remap[len(xs):]
        # dst: each x[i] repeated count[i] times
        reindex_dst = np.repeat(np.arange(len(xs)), cnt)
        out_nodes = np.array(sorted(order, key=order.get))
        return (Tensor(jnp.asarray(reindex_src, jnp.int64)),
                Tensor(jnp.asarray(reindex_dst, jnp.int64)),
                Tensor(jnp.asarray(out_nodes, jnp.int64)))


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    """Sample up to ``sample_size`` neighbors per input node from CSC
    (ref phi GraphSampleNeighborsKernel). Host-side sampling."""
    with no_grad():
        r = np.asarray(_t(row)._value)
        cp = np.asarray(_t(colptr)._value)
        nodes = np.asarray(_t(input_nodes)._value)
        from ..core import random as _core_random
        rng = np.random.default_rng(
            int(jax.random.key_data(_core_random.split_key())[-1]))
        out_nb, out_cnt, out_eids = [], [], []
        for nval in nodes:
            lo, hi = int(cp[nval]), int(cp[nval + 1])
            neigh = r[lo:hi]
            idx = np.arange(lo, hi)
            if sample_size > 0 and len(neigh) > sample_size:
                sel = rng.choice(len(neigh), sample_size, replace=False)
                neigh, idx = neigh[sel], idx[sel]
            out_nb.append(neigh)
            out_cnt.append(len(neigh))
            out_eids.append(idx)
        nb = Tensor(jnp.asarray(np.concatenate(out_nb) if out_nb else
                                np.zeros(0, r.dtype)))
        cnt = Tensor(jnp.asarray(np.asarray(out_cnt, np.int32)))
        if return_eids:
            ev = (np.asarray(_t(eids)._value)[np.concatenate(out_eids)]
                  if eids is not None else np.concatenate(out_eids))
            return nb, cnt, Tensor(jnp.asarray(ev))
        return nb, cnt


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop neighbor sampling + reindex (ref phi
    GraphKhopSamplerKernel). Host-side."""
    with no_grad():
        frontier = np.asarray(_t(input_nodes)._value)
        all_src, all_dst = [], []
        seen = list(frontier)
        for size in sample_sizes:
            nb, cnt = graph_sample_neighbors(row, colptr, Tensor(jnp.asarray(frontier)),
                                             sample_size=size)
            nbv = np.asarray(nb._value)
            cntv = np.asarray(cnt._value)
            all_src.append(nbv)
            all_dst.append(np.repeat(frontier, cntv))
            new = np.setdiff1d(nbv, np.asarray(seen))
            seen.extend(new.tolist())
            frontier = new
        src = np.concatenate(all_src) if all_src else np.zeros(0, np.int64)
        dst = np.concatenate(all_dst) if all_dst else np.zeros(0, np.int64)
        order = {int(v): i for i, v in enumerate(dict.fromkeys(seen))}
        remap_src = np.array([order[int(v)] for v in src], np.int64)
        remap_dst = np.array([order[int(v)] for v in dst], np.int64)
        nodes = np.array(list(order.keys()), np.int64)
        inputs0 = np.asarray(_t(input_nodes)._value)
        # reindex_x: positions of the query nodes inside `nodes`
        # (reference contract: edge_src, edge_dst, sample_index, reindex_x)
        reindex_x = np.array([order[int(v)] for v in inputs0], np.int64)
        outs = (Tensor(jnp.asarray(remap_src)), Tensor(jnp.asarray(remap_dst)),
                Tensor(jnp.asarray(nodes)), Tensor(jnp.asarray(reindex_x)))
        if return_eids:
            eids = np.arange(len(src), dtype=np.int64)
            if sorted_eids is not None:
                se = np.asarray(_t(sorted_eids)._value)
                eids = se[eids % max(len(se), 1)]
            return outs + (Tensor(jnp.asarray(eids)),)
        return outs


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) in one fused pass (ref
    fused_softmax_mask_op.cu) — XLA fuses the add into the softmax."""
    return apply_op("softmax_mask_fuse",
                    lambda v, m: jax.nn.softmax(v + m, -1), [_t(x), _t(mask)])


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Causal-masked softmax (ref fused_softmax_mask_upper_triangle_op.cu):
    positions above the diagonal get -inf."""
    def fn(v):
        s, t = v.shape[-2], v.shape[-1]
        mask = jnp.tril(jnp.ones((s, t), bool))
        return jax.nn.softmax(jnp.where(mask, v, -1e4 if v.dtype == jnp.float16
                                        else -1e30), -1)
    return apply_op("softmax_mask_fuse_upper_triangle", fn, [_t(x)])


def identity_loss(x, reduction="none"):
    """Mark a loss for IPU-style pipelining (ref identity_loss op); on TPU
    it is just the reduction."""
    red = {0: "sum", 1: "mean", 2: "none", "sum": "sum", "mean": "mean",
           "none": "none"}[reduction]
    if red == "sum":
        return apply_op("identity_loss", jnp.sum, [_t(x)])
    if red == "mean":
        return apply_op("identity_loss", jnp.mean, [_t(x)])
    return _t(x)
