"""Incubate optimizers (ref ``python/paddle/incubate/optimizer/``):
``LookAhead`` (lookahead.py:26), ``ModelAverage`` (modelaverage.py:28),
``DistributedFusedLamb`` (distributed_fused_lamb.py:86 — on TPU the fused
sharded LAMB is ``optimizer.Lamb`` under a ZeRO sharding rule; see
``parallel.sharding``, so only the alias lives here).
"""

from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...optimizer.optimizer import Optimizer
from ...optimizer.optimizers import Lamb as DistributedFusedLamb  # noqa: F401


class LookAhead(Optimizer):
    """Wraps an inner optimizer; every k steps pulls fast weights toward the
    slow (lookahead) copy: slow += alpha * (fast - slow); fast = slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        # snapshot at construction (ref lookahead.py: slow params start as
        # the initial weights, so the first sync damps the whole window)
        self._slow = {id(p): p._value
                      for p in inner_optimizer._parameter_list}
        self._step_num = 0
        # not calling super().__init__: this is a wrapper, state lives inner

    @property
    def _parameter_list(self):
        return self.inner_optimizer._parameter_list

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def set_lr(self, value):
        return self.inner_optimizer.set_lr(value)

    def clear_grad(self, set_to_zero=False):
        return self.inner_optimizer.clear_grad(set_to_zero)

    def step(self):
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k:
            return
        for p in self.inner_optimizer._parameter_list:
            slow = self._slow.get(id(p), p._value)
            slow = slow + self.alpha * (p._value - slow)
            self._slow[id(p)] = slow
            p._set_value(slow)

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["lookahead_step"] = self._step_num
        return sd

    def set_state_dict(self, state):
        self._step_num = state.pop("lookahead_step", 0)
        self.inner_optimizer.set_state_dict(state)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()


class ModelAverage(Optimizer):
    """Running average of parameters for evaluation
    (ref modelaverage.py:28). ``apply()`` swaps averaged weights in,
    ``restore()`` swaps them back."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(learning_rate=0.0, parameters=parameters)
        self.avg_rate = average_window_rate
        self.min_window = min_average_window
        self.max_window = max_average_window
        self._sum = {}
        self._count = {}
        self._saved = None

    def step(self):
        for p in self._parameter_list:
            s = self._sum.get(id(p))
            if s is None:
                s, c = jnp.zeros_like(p._value), 0
            else:
                c = self._count[id(p)]
            if c >= self.max_window:
                # restart window (ref: num_accumulates window rotation)
                s, c = jnp.zeros_like(p._value), 0
            self._sum[id(p)] = s + p._value
            self._count[id(p)] = c + 1

    def apply(self, executor=None, need_restore=True):
        self._saved = {id(p): p._value for p in self._parameter_list}
        for p in self._parameter_list:
            c = self._count.get(id(p), 0)
            if c:
                p._set_value(self._sum[id(p)] / c)
        return _RestoreCtx(self) if need_restore else None

    def restore(self, executor=None):
        if self._saved is None:
            return
        for p in self._parameter_list:
            saved = self._saved.get(id(p))
            if saved is not None:
                p._set_value(saved)
        self._saved = None

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self.step()


class _RestoreCtx:
    def __init__(self, ma):
        self._ma = ma

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._ma.restore()


__all__ = ["LookAhead", "ModelAverage", "DistributedFusedLamb"]
