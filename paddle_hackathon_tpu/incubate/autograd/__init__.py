"""Functional autograd transforms (ref ``python/paddle/incubate/autograd/
functional.py`` — jvp:23, vjp:81, Jacobian:172, plus Hessian).

The reference implements these with its primitive-rule AD (``primx.py``,
``primrules.py``); here they are direct applications of JAX's functional
transforms — the framework's ops are jax-traceable, so forward- and
reverse-mode compose for free (including the higher-order cases the eager
tape declines).

Jacobian/Hessian follow the reference's matrix view: every input is
flattened to length N, every output to length M, giving J of shape [M, N]
(or [B, M, N] with ``is_batched=True``, where flattening excludes the
leading batch dim).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor


def _unwrap(xs):
    if isinstance(xs, (list, tuple)):
        return tuple(x._value if isinstance(x, Tensor) else jnp.asarray(x)
                     for x in xs)
    return (xs._value if isinstance(xs, Tensor) else jnp.asarray(xs),)


def _wrap(vals):
    if isinstance(vals, (list, tuple)):
        out = tuple(Tensor(v, stop_gradient=True) for v in vals)
        return out[0] if len(out) == 1 else out
    return Tensor(vals, stop_gradient=True)


def _as_jax_fn(func):
    """Lift a Tensor->Tensor function to a pure jax function."""

    def fn(*jax_xs):
        with_tensors = [Tensor(x, stop_gradient=False) for x in jax_xs]
        out = func(*with_tensors)
        if isinstance(out, (list, tuple)):
            return tuple(o._value if isinstance(o, Tensor) else o for o in out)
        return out._value if isinstance(out, Tensor) else out

    return fn


def jvp(func, xs, v=None):
    """Forward-mode Jacobian-vector product. Returns (outputs, jvp)."""
    jax_xs = _unwrap(xs)
    tangents = (_unwrap(v) if v is not None
                else tuple(jnp.ones_like(x) for x in jax_xs))
    out, tangent_out = jax.jvp(_as_jax_fn(func), jax_xs, tangents)
    return _wrap(out), _wrap(tangent_out)


def vjp(func, xs, v=None):
    """Reverse-mode vector-Jacobian product. Returns (outputs, vjp)."""
    jax_xs = _unwrap(xs)
    out, vjp_fn = jax.vjp(_as_jax_fn(func), *jax_xs)
    if v is None:
        cot = (jax.tree_util.tree_map(jnp.ones_like, out)
               if isinstance(out, tuple) else jnp.ones_like(out))
    else:
        cot = _unwrap(v)
        cot = cot if isinstance(out, tuple) else cot[0]
    grads = vjp_fn(cot)
    return _wrap(out), _wrap(grads)


def _flat_fn(fn, template_xs):
    """Wrap fn to map one flat 1-D input vector -> one flat output vector."""
    sizes = [max(int(np.prod(x.shape)), 1) for x in template_xs]
    shapes = [x.shape for x in template_xs]

    def flat_fn(flat_x):
        parts, o = [], 0
        for shp, n in zip(shapes, sizes):
            parts.append(flat_x[o:o + n].reshape(shp))
            o += n
        out = fn(*parts)
        outs = out if isinstance(out, (list, tuple)) else (out,)
        return jnp.concatenate([jnp.ravel(o_) for o_ in outs])

    return flat_fn, sizes


def _pack(jax_xs):
    return jnp.concatenate([jnp.ravel(x) for x in jax_xs])


class Jacobian:
    """Full Jacobian as an [M, N] matrix ([B, M, N] when batched)."""

    def __init__(self, func, xs, is_batched=False):
        jax_xs = _unwrap(xs)
        fn = _as_jax_fn(func)
        self.is_batched = is_batched
        if not is_batched:
            flat_fn, _ = _flat_fn(fn, jax_xs)
            self._value = jax.jacrev(flat_fn)(_pack(jax_xs))
        else:
            sample_xs = tuple(x[0] for x in jax_xs)

            def sample_fn(*sample):
                # re-add the batch dim the user's fn expects, strip it after
                out = fn(*[s[None] for s in sample])
                outs = out if isinstance(out, (list, tuple)) else (out,)
                return jnp.concatenate([jnp.ravel(o_) for o_ in outs])

            flat_sample_fn, _ = _flat_fn(sample_fn, sample_xs)
            per_sample = jax.jacrev(flat_sample_fn)
            self._value = jax.vmap(lambda *s: per_sample(_pack(s)))(*jax_xs)

    @property
    def shape(self):
        return tuple(self._value.shape)

    def __getitem__(self, idx):
        return Tensor(self._value[idx], stop_gradient=True)

    def numpy(self):
        return np.asarray(self._value)


class Hessian:
    """Hessian of a scalar function as an [N, N] matrix ([B, N, N] when
    batched: the function maps each sample to a scalar)."""

    def __init__(self, func, xs, is_batched=False):
        jax_xs = _unwrap(xs)
        fn = _as_jax_fn(func)
        self.is_batched = is_batched

        if not is_batched:
            flat_fn, _ = _flat_fn(fn, jax_xs)
            self._value = jax.hessian(
                lambda fx: flat_fn(fx).sum())(_pack(jax_xs))
        else:
            sample_xs = tuple(x[0] for x in jax_xs)

            def sample_fn(*sample):
                out = fn(*[s[None] for s in sample])
                outs = out if isinstance(out, (list, tuple)) else (out,)
                return jnp.concatenate([jnp.ravel(o_) for o_ in outs])

            flat_sample_fn, _ = _flat_fn(sample_fn, sample_xs)
            hess = jax.hessian(lambda fx: flat_sample_fn(fx).sum())
            self._value = jax.vmap(lambda *s: hess(_pack(s)))(*jax_xs)

    @property
    def shape(self):
        return tuple(self._value.shape)

    def __getitem__(self, idx):
        return Tensor(self._value[idx], stop_gradient=True)

    def numpy(self):
        return np.asarray(self._value)


__all__ = ["jvp", "vjp", "Jacobian", "Hessian"]
