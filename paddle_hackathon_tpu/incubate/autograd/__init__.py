"""Functional autograd transforms (ref ``python/paddle/incubate/autograd/
functional.py`` — jvp:23, vjp:81, Jacobian:172, plus Hessian).

The reference implements these with its primitive-rule AD (``primx.py``,
``primrules.py``); here they are direct applications of JAX's functional
transforms — the framework's ops are jax-traceable, so forward- and
reverse-mode compose for free (including the higher-order cases the eager
tape declines).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor


def _unwrap(xs):
    if isinstance(xs, (list, tuple)):
        return tuple(x._value if isinstance(x, Tensor) else jnp.asarray(x)
                     for x in xs)
    return (xs._value if isinstance(xs, Tensor) else jnp.asarray(xs),)


def _wrap(vals):
    if isinstance(vals, (list, tuple)):
        out = tuple(Tensor(v, stop_gradient=True) for v in vals)
        return out[0] if len(out) == 1 else out
    return Tensor(vals, stop_gradient=True)


def _as_jax_fn(func):
    """Lift a Tensor->Tensor function to a pure jax function."""

    def fn(*jax_xs):
        with_tensors = [Tensor(x, stop_gradient=False) for x in jax_xs]
        out = func(*with_tensors)
        if isinstance(out, (list, tuple)):
            return tuple(o._value if isinstance(o, Tensor) else o for o in out)
        return out._value if isinstance(out, Tensor) else out

    return fn


def jvp(func, xs, v=None):
    """Forward-mode Jacobian-vector product. Returns (outputs, jvp)."""
    jax_xs = _unwrap(xs)
    tangents = (_unwrap(v) if v is not None
                else tuple(jnp.ones_like(x) for x in jax_xs))
    out, tangent_out = jax.jvp(_as_jax_fn(func), jax_xs, tangents)
    return _wrap(out), _wrap(tangent_out)


def vjp(func, xs, v=None):
    """Reverse-mode vector-Jacobian product. Returns (outputs, vjp)."""
    jax_xs = _unwrap(xs)
    out, vjp_fn = jax.vjp(_as_jax_fn(func), *jax_xs)
    if v is None:
        cot = (jax.tree_util.tree_map(jnp.ones_like, out)
               if isinstance(out, tuple) else jnp.ones_like(out))
    else:
        cot = _unwrap(v)
        cot = cot if isinstance(out, tuple) else cot[0]
    grads = vjp_fn(cot)
    return _wrap(out), _wrap(grads)


class Jacobian:
    """Lazy full Jacobian (ref functional.py:172). Index as J[:] or J[i, j]."""

    def __init__(self, func, xs, is_batched=False):
        jax_xs = _unwrap(xs)
        jac = jax.jacrev(_as_jax_fn(func), argnums=tuple(range(len(jax_xs))))(
            *jax_xs)
        if len(jax_xs) == 1 and not isinstance(jac, tuple):
            jac = (jac,)
        flat = []
        for j in jac if isinstance(jac, tuple) else (jac,):
            arr = j
            if is_batched:
                b = arr.shape[0]
                flat.append(arr.reshape(b, -1, *([1] if arr.ndim < 3 else []))
                            if arr.ndim < 3 else
                            arr.reshape(b, arr.shape[1], -1))
            else:
                flat.append(arr.reshape(_rows(arr), -1))
        self._value = jnp.concatenate(flat, axis=-1)
        self.is_batched = is_batched

    @property
    def shape(self):
        return tuple(self._value.shape)

    def __getitem__(self, idx):
        return Tensor(self._value[idx], stop_gradient=True)

    def numpy(self):
        import numpy as np
        return np.asarray(self._value)


def _rows(arr):
    # output dims come first in jacrev's result; collapse to 2-D [out, in]
    return arr.shape[0] if arr.ndim >= 1 else 1


class Hessian:
    """Full Hessian of a scalar function (ref functional.py Hessian)."""

    def __init__(self, func, xs, is_batched=False):
        jax_xs = _unwrap(xs)
        hes = jax.hessian(_as_jax_fn(func), argnums=tuple(range(len(jax_xs))))(
            *jax_xs)
        if len(jax_xs) == 1:
            h = hes[0][0] if isinstance(hes, tuple) else hes
            n = 1
            for s in jax_xs[0].shape:
                n *= s
            self._value = jnp.reshape(h, (n, n))
        else:
            blocks = []
            sizes = [int(jnp.size(x)) for x in jax_xs]
            for i, row in enumerate(hes):
                blocks.append(jnp.concatenate(
                    [jnp.reshape(row[j], (sizes[i], sizes[j]))
                     for j in range(len(jax_xs))], axis=1))
            self._value = jnp.concatenate(blocks, axis=0)

    @property
    def shape(self):
        return tuple(self._value.shape)

    def __getitem__(self, idx):
        return Tensor(self._value[idx], stop_gradient=True)

    def numpy(self):
        import numpy as np
        return np.asarray(self._value)


__all__ = ["jvp", "vjp", "Jacobian", "Hessian"]
