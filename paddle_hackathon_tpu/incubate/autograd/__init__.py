"""Functional autograd transforms (ref ``python/paddle/incubate/autograd/
functional.py`` — jvp:23, vjp:81, Jacobian:172, plus Hessian).

The reference implements these with its primitive-rule AD (``primx.py``,
``primrules.py``); here they are direct applications of JAX's functional
transforms — the framework's ops are jax-traceable, so forward- and
reverse-mode compose for free (including the higher-order cases the eager
tape declines).

Jacobian/Hessian follow the reference's matrix view: every input is
flattened to length N, every output to length M, giving J of shape [M, N]
(or [B, M, N] with ``is_batched=True``, where flattening excludes the
leading batch dim).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor


def _unwrap(xs):
    if isinstance(xs, (list, tuple)):
        return tuple(x._value if isinstance(x, Tensor) else jnp.asarray(x)
                     for x in xs)
    return (xs._value if isinstance(xs, Tensor) else jnp.asarray(xs),)


def _wrap(vals):
    if isinstance(vals, (list, tuple)):
        out = tuple(Tensor(v, stop_gradient=True) for v in vals)
        return out[0] if len(out) == 1 else out
    return Tensor(vals, stop_gradient=True)


def _as_jax_fn(func):
    """Lift a Tensor->Tensor function to a pure jax function."""

    def fn(*jax_xs):
        with_tensors = [Tensor(x, stop_gradient=False) for x in jax_xs]
        out = func(*with_tensors)
        if isinstance(out, (list, tuple)):
            return tuple(o._value if isinstance(o, Tensor) else o for o in out)
        return out._value if isinstance(out, Tensor) else out

    return fn


def jvp(func, xs, v=None):
    """Forward-mode Jacobian-vector product. Returns (outputs, jvp)."""
    jax_xs = _unwrap(xs)
    tangents = (_unwrap(v) if v is not None
                else tuple(jnp.ones_like(x) for x in jax_xs))
    out, tangent_out = jax.jvp(_as_jax_fn(func), jax_xs, tangents)
    return _wrap(out), _wrap(tangent_out)


def vjp(func, xs, v=None):
    """Reverse-mode vector-Jacobian product. Returns (outputs, vjp)."""
    jax_xs = _unwrap(xs)
    out, vjp_fn = jax.vjp(_as_jax_fn(func), *jax_xs)
    if v is None:
        cot = (jax.tree_util.tree_map(jnp.ones_like, out)
               if isinstance(out, tuple) else jnp.ones_like(out))
    else:
        cot = _unwrap(v)
        cot = cot if isinstance(out, tuple) else cot[0]
    grads = vjp_fn(cot)
    return _wrap(out), _wrap(grads)


def _flat_fn(fn, template_xs):
    """Wrap fn to map one flat 1-D input vector -> one flat output vector."""
    sizes = [max(int(np.prod(x.shape)), 1) for x in template_xs]
    shapes = [x.shape for x in template_xs]

    def flat_fn(flat_x):
        parts, o = [], 0
        for shp, n in zip(shapes, sizes):
            parts.append(flat_x[o:o + n].reshape(shp))
            o += n
        out = fn(*parts)
        outs = out if isinstance(out, (list, tuple)) else (out,)
        return jnp.concatenate([jnp.ravel(o_) for o_ in outs])

    return flat_fn, sizes


def _pack(jax_xs):
    return jnp.concatenate([jnp.ravel(x) for x in jax_xs])


class Jacobian:
    """Full Jacobian as an [M, N] matrix ([B, M, N] when batched)."""

    def __init__(self, func, xs, is_batched=False):
        jax_xs = _unwrap(xs)
        fn = _as_jax_fn(func)
        self.is_batched = is_batched
        if not is_batched:
            flat_fn, _ = _flat_fn(fn, jax_xs)
            self._value = jax.jacrev(flat_fn)(_pack(jax_xs))
        else:
            sample_xs = tuple(x[0] for x in jax_xs)

            def sample_fn(*sample):
                # re-add the batch dim the user's fn expects, strip it after
                out = fn(*[s[None] for s in sample])
                outs = out if isinstance(out, (list, tuple)) else (out,)
                return jnp.concatenate([jnp.ravel(o_) for o_ in outs])

            flat_sample_fn, _ = _flat_fn(sample_fn, sample_xs)
            per_sample = jax.jacrev(flat_sample_fn)
            self._value = jax.vmap(lambda *s: per_sample(_pack(s)))(*jax_xs)

    @property
    def shape(self):
        return tuple(self._value.shape)

    def __getitem__(self, idx):
        return Tensor(self._value[idx], stop_gradient=True)

    def numpy(self):
        return np.asarray(self._value)


class Hessian:
    """Hessian of a scalar function as an [N, N] matrix ([B, N, N] when
    batched: the function maps each sample to a scalar)."""

    def __init__(self, func, xs, is_batched=False):
        jax_xs = _unwrap(xs)
        fn = _as_jax_fn(func)
        self.is_batched = is_batched

        if not is_batched:
            flat_fn, _ = _flat_fn(fn, jax_xs)
            self._value = jax.hessian(
                lambda fx: flat_fn(fx).sum())(_pack(jax_xs))
        else:
            sample_xs = tuple(x[0] for x in jax_xs)

            def sample_fn(*sample):
                out = fn(*[s[None] for s in sample])
                outs = out if isinstance(out, (list, tuple)) else (out,)
                return jnp.concatenate([jnp.ravel(o_) for o_ in outs])

            flat_sample_fn, _ = _flat_fn(sample_fn, sample_xs)
            hess = jax.hessian(lambda fx: flat_sample_fn(fx).sum())
            self._value = jax.vmap(lambda *s: hess(_pack(s)))(*jax_xs)

    @property
    def shape(self):
        return tuple(self._value.shape)

    def __getitem__(self, idx):
        return Tensor(self._value[idx], stop_gradient=True)

    def numpy(self):
        return np.asarray(self._value)


__all__ = ["jvp", "vjp", "Jacobian", "Hessian", "forward_grad", "grad", "enable_prim", "disable_prim", "prim_enabled"]


# -- primitive-mode API (ref incubate/autograd/primx.py enable_prim etc.) ----
# In the reference, "prim" mode lowers ops to primitive rules so the static
# AD pass can transpose them. Here every op IS already differentiable jax
# primitives — prim mode is the permanent state — so the toggles record
# intent only.
_prim_enabled = [False]


def enable_prim():
    _prim_enabled[0] = True


def disable_prim():
    _prim_enabled[0] = False


def prim_enabled():
    return _prim_enabled[0]


def forward_grad(outputs, inputs, grad_inputs=None):
    """Forward-mode AD over the static Program (ref
    incubate/autograd/primapi.py forward_grad — static-only there too):
    records a JVP-replay instruction computing d outputs / d inputs with
    the given input tangents (default ones)."""
    from ...static import program as _prog
    if not _prog.in_static_mode():
        raise RuntimeError(
            "forward_grad is a static-graph API (as in the reference); "
            "use incubate.autograd.jvp for eager forward-mode")
    singles = not isinstance(outputs, (list, tuple))
    outs = [outputs] if singles else list(outputs)
    ins = [inputs] if not isinstance(inputs, (list, tuple)) else list(inputs)
    tangent_args = (None if grad_inputs is None else
                    ([grad_inputs] if not isinstance(grad_inputs,
                                                     (list, tuple))
                     else list(grad_inputs)))
    prog = _prog.default_main_program()
    sub = list(prog._instructions)
    feeds = list(prog._feeds)
    params = prog.all_parameters()
    feed_ids = [f._var_id for f in feeds]
    in_ids = [x._var_id for x in ins]
    out_ids = [o._var_id for o in outs]
    n_tan = len(tangent_args) if tangent_args else 0

    def _replay(env, param_vals, want):
        for ins_ in sub:
            if set(ins_.out_ids) <= set(env):
                continue
            vals_ = []
            for kind, ref in ins_.inputs:
                if kind == "var":
                    vals_.append(env[ref])
                elif kind == "param":
                    vals_.append(param_vals[id(ref)])
                else:
                    vals_.append(ref)
            o = ins_.fn(*vals_)
            os_ = (o,) if ins_.n_outputs == 1 and not isinstance(
                o, tuple) else o
            for vid, val in zip(ins_.out_ids, os_):
                env[vid] = val
        return tuple(env[i] for i in want)

    def jvp_fn(*vals):
        feed_vals = list(vals[:len(feed_ids)])
        tan_vals = list(vals[len(feed_ids):len(feed_ids) + n_tan])
        param_vals = dict(zip((id(p) for p in params),
                              vals[len(feed_ids) + n_tan:]))

        def forward(wrt):
            env = dict(zip(feed_ids, feed_vals))
            env.update(zip(in_ids, wrt))
            return _replay(env, param_vals, out_ids)

        primals = _replay(dict(zip(feed_ids, feed_vals)), param_vals, in_ids)
        tangents = (tuple(tan_vals) if tan_vals
                    else tuple(jnp.ones_like(p) for p in primals))
        _, out_tangents = jax.jvp(forward, (primals,), (tangents,))
        return out_tangents if len(out_ids) > 1 else out_tangents[0]

    rec_args = feeds + (tangent_args or []) + params
    res = prog.record_op("forward_grad", jvp_fn, rec_args,
                         n_outputs=len(out_ids))
    return res


def grad(outputs, inputs, grad_outputs=None):
    """Reverse-mode grad (ref incubate.autograd.grad) — delegates to the
    eager engine's grad()."""
    from ...core.autograd import grad as _grad
    return _grad(outputs, inputs, grad_outputs=grad_outputs,
                 allow_unused=True)
