"""ASP — automatic structured (n:m) sparsity.

Ref ``python/paddle/incubate/asp/`` — ``prune_model``, ``decorate``,
``calculate_density``, mask algorithms (mask_1d / best-in-group by
magnitude). The reference targets Ampere sparse tensor cores; on TPU n:m
masks are a magnitude-pruning capability (XLA has no sparse MXU path), so
the semantics — masks computed once, re-applied after every optimizer step
so pruned weights stay zero — are preserved exactly.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

_excluded = set()


def set_excluded_layers(param_names, main_program=None):
    _excluded.update(param_names)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def calculate_density(x) -> float:
    arr = np.asarray(getattr(x, "_value", x))
    return float((arr != 0).sum() / arr.size)


def _nm_mask_1d(w: np.ndarray, n: int, m: int) -> np.ndarray:
    """Keep the n largest-|w| entries in every group of m along the last
    axis (ref sparsity/utils.py get_mask_1d)."""
    orig = w.shape
    flat = w.reshape(-1, orig[-1])
    cols = orig[-1]
    pad = (-cols) % m
    if pad:
        flat = np.pad(flat, ((0, 0), (0, pad)))
    g = flat.reshape(flat.shape[0], -1, m)
    idx = np.argsort(np.abs(g), axis=-1)[..., : m - n]  # smallest m-n -> drop
    mask = np.ones_like(g, dtype=bool)
    np.put_along_axis(mask, idx, False, axis=-1)
    mask = mask.reshape(flat.shape[0], -1)[:, :cols]
    return mask.reshape(orig)


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Compute and apply n:m masks to every prunable parameter of
    ``model`` (2-D+ weights, not biases/norms, not excluded)."""
    pruned = {}
    for name, p in model.named_parameters():
        if name in _excluded or p.ndim < 2:
            continue
        w = np.asarray(p._value)
        mask = _nm_mask_1d(w, n, m)
        p._set_value(jnp.asarray(w * mask, dtype=p._value.dtype))
        if with_mask:
            # stored on the parameter itself: dies with it (a global map
            # keyed by id() would leak and could collide on id reuse)
            p._asp_mask = jnp.asarray(mask, dtype=p._value.dtype)
        pruned[name] = mask
    return pruned


class OptimizerWithSparsityGuarantee:
    """Wraps an optimizer: after each step, re-applies the stored masks so
    pruned entries stay zero (ref asp.py ASPHelper._decorate)."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def step(self):
        self._optimizer.step()
        for p in self._optimizer._parameter_list:
            mask = getattr(p, "_asp_mask", None)
            if mask is not None:
                p._set_value(p._value * mask)


def decorate(optimizer):
    return OptimizerWithSparsityGuarantee(optimizer)


__all__ = ["prune_model", "decorate", "calculate_density",
           "set_excluded_layers", "reset_excluded_layers"]
