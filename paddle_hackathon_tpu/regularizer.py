"""paddle.regularizer (ref ``python/paddle/regularizer.py``): weight decay
as an optimizer-coupled penalty — re-exported from the optimizer module
where the coefficients are consumed."""

from .optimizer import L1Decay, L2Decay  # noqa: F401

__all__ = ["L1Decay", "L2Decay"]
