"""Vision datasets (ref ``python/paddle/vision/datasets/`` — MNIST
``mnist.py``, Cifar ``cifar.py``, FashionMNIST, Flowers).

The reference downloads archives on first use; this environment has no
network egress, so each dataset reads the standard on-disk format from
``data_file``/``data_dir`` when present and raises a clear error otherwise.
``FakeData`` provides deterministic synthetic samples for tests and smoke
runs (mirrors the role of the reference's unittest fake readers).
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData"]


def _maybe(tf, img, label):
    return img, label


class MNIST(Dataset):
    """IDX-format MNIST (ref ``vision/datasets/mnist.py``)."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        self.mode = mode.lower()
        self.transform = transform
        if image_path is None or label_path is None:
            base = os.environ.get("PADDLE_DATA_HOME",
                                  os.path.expanduser("~/.cache/paddle/datasets"))
            stem = "train" if self.mode == "train" else "t10k"
            image_path = image_path or os.path.join(
                base, self.NAME, f"{stem}-images-idx3-ubyte.gz")
            label_path = label_path or os.path.join(
                base, self.NAME, f"{stem}-labels-idx1-ubyte.gz")
        if not (os.path.exists(image_path) and os.path.exists(label_path)):
            raise FileNotFoundError(
                f"{self.NAME} files not found at {image_path}; this build "
                "has no network access — place the IDX archives there or "
                "use vision.datasets.FakeData for smoke runs")
        self.images = self._read_idx(image_path, 3)
        self.labels = self._read_idx(label_path, 1)

    @staticmethod
    def _read_idx(path, ndim):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            magic = struct.unpack(">I", f.read(4))[0]
            dims = [struct.unpack(">I", f.read(4))[0]
                    for _ in range(magic & 0xFF)]
            data = np.frombuffer(f.read(), np.uint8)
        return data.reshape(dims)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = np.asarray(self.labels[idx], np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """CIFAR pickle batches from the standard tar.gz (ref cifar.py)."""

    _ARCHIVE = "cifar-10-python.tar.gz"
    _PREFIX = "cifar-10-batches-py"
    _LABEL_KEY = b"labels"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        self.mode = mode.lower()
        self.transform = transform
        if data_file is None:
            base = os.environ.get("PADDLE_DATA_HOME",
                                  os.path.expanduser("~/.cache/paddle/datasets"))
            data_file = os.path.join(base, "cifar", self._ARCHIVE)
        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"cifar archive not found at {data_file}; this build has no "
                "network access — place the archive there or use FakeData")
        names = ([f"data_batch_{i}" for i in range(1, 6)]
                 if self.mode == "train" else ["test_batch"])
        if self._PREFIX == "cifar-100-python":
            names = ["train"] if self.mode == "train" else ["test"]
        imgs, labels = [], []
        with tarfile.open(data_file, "r:gz") as tf:
            for n in names:
                f = tf.extractfile(f"{self._PREFIX}/{n}")
                batch = pickle.load(f, encoding="bytes")
                imgs.append(batch[b"data"])
                labels.extend(batch[self._LABEL_KEY])
        self.images = np.concatenate(imgs).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        img = np.transpose(self.images[idx], (1, 2, 0))  # HWC for transforms
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    _ARCHIVE = "cifar-100-python.tar.gz"
    _PREFIX = "cifar-100-python"
    _LABEL_KEY = b"fine_labels"


class FakeData(Dataset):
    """Deterministic synthetic image classification data (for tests and
    benchmarks; fills the role of the reference's fake data feeds)."""

    def __init__(self, num_samples=100, image_shape=(3, 32, 32),
                 num_classes=10, transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.randint(0, 256, self.image_shape[1:] + (self.image_shape[0],),
                          dtype=np.uint8)  # HWC like real loaders
        label = np.asarray(rng.randint(0, self.num_classes), np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.num_samples
