"""paddle.vision equivalent: model zoo, transforms, datasets, detection ops
(ref ``python/paddle/vision/``)."""

from . import datasets, models, ops, transforms  # noqa: F401
from .models import *  # noqa: F401,F403
