"""paddle.vision equivalent: model zoo, transforms, datasets, detection ops
(ref ``python/paddle/vision/``)."""

from . import datasets, models, ops, transforms  # noqa: F401
from .models import *  # noqa: F401,F403

_image_backend = "pil"


def set_image_backend(backend):
    """Ref vision/image.py set_image_backend ('pil'|'cv2'|'tensor')."""
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unsupported image backend {backend!r}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image file (ref vision/image.py image_load). With the
    'tensor' backend returns an HWC uint8 framework Tensor."""
    backend = backend or _image_backend
    try:
        from PIL import Image
    except ImportError:
        Image = None
    import numpy as _np
    if backend == "cv2":
        cv2 = __import__("cv2")
        return cv2.imread(str(path))
    if Image is None:
        raise RuntimeError("PIL is unavailable; use the 'cv2' backend")
    img = Image.open(path)
    if backend == "pil":
        return img
    from ..core.tensor import Tensor
    import jax.numpy as _jnp
    return Tensor(_jnp.asarray(_np.asarray(img)))
