"""GoogLeNet / Inception v1 (ref ``python/paddle/vision/models/googlenet.py``)."""

from __future__ import annotations

from ... import nn
from ...ops import manipulation as M


class _Inception(nn.Layer):
    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = nn.Sequential(nn.Conv2D(in_ch, c1, 1), nn.ReLU())
        self.b2 = nn.Sequential(nn.Conv2D(in_ch, c3r, 1), nn.ReLU(),
                                nn.Conv2D(c3r, c3, 3, padding=1), nn.ReLU())
        self.b3 = nn.Sequential(nn.Conv2D(in_ch, c5r, 1), nn.ReLU(),
                                nn.Conv2D(c5r, c5, 5, padding=2), nn.ReLU())
        self.b4 = nn.Sequential(nn.MaxPool2D(3, 1, padding=1),
                                nn.Conv2D(in_ch, proj, 1), nn.ReLU())

    def forward(self, x):
        return M.concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                        axis=1)


class GoogLeNet(nn.Layer):
    """Returns (main, aux1, aux2) logits like the reference."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1))
        self.inc3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.inc3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, 2, padding=1)
        self.inc4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.inc4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.inc4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.inc4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.inc4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, 2, padding=1)
        self.inc5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.inc5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool5 = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)
            # aux heads (ref GoogLeNetOutputs)
            self.aux1 = nn.Sequential(
                nn.AdaptiveAvgPool2D(4), nn.Conv2D(512, 128, 1), nn.ReLU())
            self.aux1_fc = nn.Sequential(
                nn.Linear(128 * 16, 1024), nn.ReLU(), nn.Dropout(0.7),
                nn.Linear(1024, num_classes))
            self.aux2 = nn.Sequential(
                nn.AdaptiveAvgPool2D(4), nn.Conv2D(528, 128, 1), nn.ReLU())
            self.aux2_fc = nn.Sequential(
                nn.Linear(128 * 16, 1024), nn.ReLU(), nn.Dropout(0.7),
                nn.Linear(1024, num_classes))

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.inc3b(self.inc3a(x)))
        x = self.inc4a(x)
        aux1 = None
        if self.num_classes > 0:
            aux1 = self.aux1_fc(M.flatten(self.aux1(x), 1))
        x = self.inc4d(self.inc4c(self.inc4b(x)))
        aux2 = None
        if self.num_classes > 0:
            aux2 = self.aux2_fc(M.flatten(self.aux2(x), 1))
        x = self.pool4(self.inc4e(x))
        x = self.inc5b(self.inc5a(x))
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(M.flatten(x, 1)))
        return x, aux1, aux2


def googlenet(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return GoogLeNet(**kwargs)
