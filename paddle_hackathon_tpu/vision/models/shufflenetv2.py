"""ShuffleNetV2 (ref ``python/paddle/vision/models/shufflenetv2.py``)."""

from __future__ import annotations

from ... import nn
from ...ops import manipulation as M


def _channel_shuffle(x, groups):
    from ...nn import functional as F
    return F.channel_shuffle(x, groups)


class _InvertedResidual(nn.Layer):
    def __init__(self, in_ch, out_ch, stride, act_cls):
        super().__init__()
        self.stride = stride
        branch = out_ch // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                nn.Conv2D(in_ch // 2, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), act_cls(),
                nn.Conv2D(branch, branch, 3, stride=1, padding=1,
                          groups=branch, bias_attr=False),
                nn.BatchNorm2D(branch),
                nn.Conv2D(branch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), act_cls())
            self.branch1 = None
        else:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_ch, in_ch, 3, stride=stride, padding=1,
                          groups=in_ch, bias_attr=False),
                nn.BatchNorm2D(in_ch),
                nn.Conv2D(in_ch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), act_cls())
            self.branch2 = nn.Sequential(
                nn.Conv2D(in_ch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), act_cls(),
                nn.Conv2D(branch, branch, 3, stride=stride, padding=1,
                          groups=branch, bias_attr=False),
                nn.BatchNorm2D(branch),
                nn.Conv2D(branch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), act_cls())

    def forward(self, x):
        if self.stride == 1:
            half = x.shape[1] // 2
            x1 = x[:, :half]
            x2 = x[:, half:]
            out = M.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = M.concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


_STAGES = {  # scale -> per-stage out channels + final conv
    0.25: ([24, 48, 96], 512), 0.33: ([32, 64, 128], 512),
    0.5: ([48, 96, 192], 1024), 1.0: ([116, 232, 464], 1024),
    1.5: ([176, 352, 704], 1024), 2.0: ([224, 488, 976], 2048),
}
_REPEATS = [4, 8, 4]


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        act_cls = nn.Swish if act == "swish" else nn.ReLU
        chans, final = _STAGES[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, 24, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(24), act_cls())
        self.max_pool = nn.MaxPool2D(3, 2, padding=1)
        blocks = []
        in_ch = 24
        for out_ch, rep in zip(chans, _REPEATS):
            blocks.append(_InvertedResidual(in_ch, out_ch, 2, act_cls))
            for _ in range(rep - 1):
                blocks.append(_InvertedResidual(out_ch, out_ch, 1, act_cls))
            in_ch = out_ch
        self.blocks = nn.Sequential(*blocks)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_ch, final, 1, bias_attr=False),
            nn.BatchNorm2D(final), act_cls())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(final, num_classes)

    def forward(self, x):
        x = self.conv_last(self.blocks(self.max_pool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(M.flatten(x, 1))
        return x


def _shufflenet(scale, act, pretrained, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, "relu", pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, "swish", pretrained, **kwargs)
