"""MobileNet v1/v2/v3 (ref ``python/paddle/vision/models/mobilenetv1.py``,
``mobilenetv2.py``, ``mobilenetv3.py``).

Depthwise convs are grouped convs (groups == in_channels) — XLA lowers
these to VPU-friendly per-channel loops on TPU.
"""

from __future__ import annotations

from ... import nn
from ...ops import manipulation as M


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNLayer(nn.Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, groups=1, activation=nn.ReLU):
        super().__init__()
        self.conv = nn.Conv2D(in_channels, out_channels, kernel_size,
                              stride=stride, padding=padding, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_channels)
        self.act = activation() if activation is not None else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act is not None else x


# ---------------------------------------------------------------------------
# v1
# ---------------------------------------------------------------------------

class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_channels, out_channels1, out_channels2, num_groups,
                 stride, scale):
        super().__init__()
        self.dw = ConvBNLayer(in_channels, int(out_channels1 * scale), 3,
                              stride=stride, padding=1,
                              groups=int(num_groups * scale))
        self.pw = ConvBNLayer(int(out_channels1 * scale),
                              int(out_channels2 * scale), 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = ConvBNLayer(3, int(32 * scale), 3, stride=2, padding=1)
        cfg = [  # in, c1, c2, groups, stride
            (32, 32, 64, 32, 1), (64, 64, 128, 64, 2),
            (128, 128, 128, 128, 1), (128, 128, 256, 128, 2),
            (256, 256, 256, 256, 1), (256, 256, 512, 256, 2),
            (512, 512, 512, 512, 1), (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1), (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1), (512, 512, 1024, 512, 2),
            (1024, 1024, 1024, 1024, 1)]
        self.blocks = nn.Sequential(*[
            DepthwiseSeparable(int(i * scale), c1, c2, g, s, scale)
            for i, c1, c2, g, s in cfg])
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(M.flatten(x, 1))
        return x


# ---------------------------------------------------------------------------
# v2
# ---------------------------------------------------------------------------

class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden_dim = int(round(inp * expand_ratio))
        self.use_res_connect = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNLayer(inp, hidden_dim, 1,
                                      activation=nn.ReLU6))
        layers += [
            ConvBNLayer(hidden_dim, hidden_dim, 3, stride=stride, padding=1,
                        groups=hidden_dim, activation=nn.ReLU6),
            ConvBNLayer(hidden_dim, oup, 1, activation=None)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res_connect else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        input_channel = _make_divisible(32 * scale)
        cfg = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        features = [ConvBNLayer(3, input_channel, 3, stride=2, padding=1,
                                activation=nn.ReLU6)]
        for t, c, n, s in cfg:
            output_channel = _make_divisible(c * scale)
            for i in range(n):
                features.append(InvertedResidual(
                    input_channel, output_channel, s if i == 0 else 1, t))
                input_channel = output_channel
        self.last_channel = _make_divisible(1280 * max(1.0, scale))
        features.append(ConvBNLayer(input_channel, self.last_channel, 1,
                                    activation=nn.ReLU6))
        self.features = nn.Sequential(*features)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(self.last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(M.flatten(x, 1))
        return x


# ---------------------------------------------------------------------------
# v3
# ---------------------------------------------------------------------------

class SqueezeExcite(nn.Layer):
    def __init__(self, channel, reduction=4):
        super().__init__()
        mid = _make_divisible(channel // reduction)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(channel, mid, 1)
        self.fc2 = nn.Conv2D(mid, channel, 1)

    def forward(self, x):
        from ...nn import functional as F
        s = self.pool(x)
        s = F.relu(self.fc1(s))
        s = F.hardsigmoid(self.fc2(s))
        return x * s


class _V3Block(nn.Layer):
    def __init__(self, inp, hidden, out, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and inp == out
        A = nn.Hardswish if act == "HS" else nn.ReLU
        layers = []
        if hidden != inp:
            layers.append(ConvBNLayer(inp, hidden, 1, activation=A))
        layers.append(ConvBNLayer(hidden, hidden, kernel, stride=stride,
                                  padding=kernel // 2, groups=hidden,
                                  activation=A))
        if use_se:
            layers.append(SqueezeExcite(hidden))
        layers.append(ConvBNLayer(hidden, out, 1, activation=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_V3_LARGE = [  # k, exp, out, se, act, s
    (3, 16, 16, False, "RE", 1), (3, 64, 24, False, "RE", 2),
    (3, 72, 24, False, "RE", 1), (5, 72, 40, True, "RE", 2),
    (5, 120, 40, True, "RE", 1), (5, 120, 40, True, "RE", 1),
    (3, 240, 80, False, "HS", 2), (3, 200, 80, False, "HS", 1),
    (3, 184, 80, False, "HS", 1), (3, 184, 80, False, "HS", 1),
    (3, 480, 112, True, "HS", 1), (3, 672, 112, True, "HS", 1),
    (5, 672, 160, True, "HS", 2), (5, 960, 160, True, "HS", 1),
    (5, 960, 160, True, "HS", 1)]

_V3_SMALL = [
    (3, 16, 16, True, "RE", 2), (3, 72, 24, False, "RE", 2),
    (3, 88, 24, False, "RE", 1), (5, 96, 40, True, "HS", 2),
    (5, 240, 40, True, "HS", 1), (5, 240, 40, True, "HS", 1),
    (5, 120, 48, True, "HS", 1), (5, 144, 48, True, "HS", 1),
    (5, 288, 96, True, "HS", 2), (5, 576, 96, True, "HS", 1),
    (5, 576, 96, True, "HS", 1)]


class MobileNetV3(nn.Layer):
    def __init__(self, config, last_channel, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        inp = _make_divisible(16 * scale)
        layers = [ConvBNLayer(3, inp, 3, stride=2, padding=1,
                              activation=nn.Hardswish)]
        for k, exp, out, se, act, s in config:
            exp_c = _make_divisible(exp * scale)
            out_c = _make_divisible(out * scale)
            layers.append(_V3Block(inp, exp_c, out_c, k, s, se, act))
            inp = out_c
        last_exp = _make_divisible(config[-1][1] * scale)
        layers.append(ConvBNLayer(inp, last_exp, 1, activation=nn.Hardswish))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_exp, last_channel),
                nn.Hardswish(),
                nn.Dropout(0.2),
                nn.Linear(last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(M.flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return MobileNetV2(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return MobileNetV3Large(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return MobileNetV3Small(scale=scale, **kwargs)


class MobileNetV3Small(MobileNetV3):
    """Ref mobilenetv3.py MobileNetV3Small (last_channel scales with
    `scale`: _make_divisible(1024 * scale))."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, _make_divisible(1024 * scale),
                         scale=scale, num_classes=num_classes,
                         with_pool=with_pool)


class MobileNetV3Large(MobileNetV3):
    """Ref mobilenetv3.py MobileNetV3Large."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, _make_divisible(1280 * scale),
                         scale=scale, num_classes=num_classes,
                         with_pool=with_pool)
