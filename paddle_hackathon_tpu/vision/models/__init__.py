"""Model zoo (ref ``python/paddle/vision/models/``)."""

from .lenet import LeNet
from .mobilenet import (MobileNetV1, MobileNetV2, MobileNetV3, mobilenet_v1,
                        mobilenet_v2, mobilenet_v3_large, mobilenet_v3_small)
from .resnet import (BasicBlock, BottleneckBlock, ResNet, resnet18, resnet34,
                     resnet50, resnet101, resnet152, resnext50_32x4d,
                     resnext101_32x4d, resnext152_32x4d, wide_resnet50_2,
                     wide_resnet101_2)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19

__all__ = [
    "LeNet", "ResNet", "BasicBlock", "BottleneckBlock", "resnet18",
    "resnet34", "resnet50", "resnet101", "resnet152", "resnext50_32x4d",
    "resnext101_32x4d", "resnext152_32x4d", "wide_resnet50_2",
    "wide_resnet101_2", "VGG", "vgg11", "vgg13", "vgg16", "vgg19",
    "MobileNetV1", "MobileNetV2", "MobileNetV3", "mobilenet_v1",
    "mobilenet_v2", "mobilenet_v3_large", "mobilenet_v3_small",
]
