"""Detection ops (ref ``python/paddle/vision/ops.py`` — nms, roi_align,
roi_pool, box coders; backed there by CUDA kernels in
``paddle/phi/kernels/gpu/{nms,roi_align}_kernel.cu``).

On TPU these are XLA compositions: nms is a sequential suppression loop
(lax.fori_loop — small N, scalar control on the VPU), roi_align is a
gather+bilinear composition that XLA vectorises.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply_op
from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Hard-NMS. Returns indices of kept boxes sorted by descending score.

    Ref ``vision/ops.py nms``; category-aware by offsetting boxes per class
    (the standard batched-nms trick) so one pass covers all classes.
    """
    boxes = _t(boxes)
    n = boxes.shape[0]
    if scores is None:
        scores_v = jnp.zeros((n,), jnp.float32)
    else:
        scores_v = _t(scores)._value.astype(jnp.float32)
    boxes_v = boxes._value.astype(jnp.float32)
    if category_idxs is not None:
        cat = _t(category_idxs)._value.astype(jnp.float32)
        span = (boxes_v.max() - boxes_v.min()) + 1.0
        boxes_v = boxes_v + (cat * span)[:, None]

    order = jnp.argsort(-scores_v)
    b = boxes_v[order]

    x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    areas = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)

    def body(i, keep):
        xx1 = jnp.maximum(x1[i], x1)
        yy1 = jnp.maximum(y1[i], y1)
        xx2 = jnp.minimum(x2[i], x2)
        yy2 = jnp.minimum(y2[i], y2)
        inter = (jnp.maximum(xx2 - xx1, 0) * jnp.maximum(yy2 - yy1, 0))
        iou = inter / jnp.maximum(areas[i] + areas - inter, 1e-10)
        # suppress j>i with high IoU if i itself is still kept
        suppress = (iou > iou_threshold) & (jnp.arange(x1.shape[0]) > i)
        return jnp.where(keep[i], keep & ~suppress, keep)

    keep = jax.lax.fori_loop(0, x1.shape[0], body,
                             jnp.ones((x1.shape[0],), bool))
    kept_sorted_idx = jnp.nonzero(keep, size=x1.shape[0], fill_value=-1)[0]
    result = order[kept_sorted_idx]
    result = result[kept_sorted_idx >= 0]
    if top_k is not None:
        result = result[:top_k]
    return Tensor(result)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign over NCHW features (ref phi roi_align_kernel).

    ``boxes``: (R, 4) [x1, y1, x2, y2]; ``boxes_num``: rois per image.
    """
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    ratio = 1 if sampling_ratio <= 0 else sampling_ratio

    x = _t(x)
    boxes = _t(boxes)
    bn = jnp.asarray(_t(boxes_num)._value, jnp.int32)
    batch_idx = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                           total_repeat_length=boxes.shape[0])

    def fn(feat, rois):
        off = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - off
        y1 = rois[:, 1] * spatial_scale - off
        x2 = rois[:, 2] * spatial_scale - off
        y2 = rois[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-4 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-4 if aligned else 1.0)
        bh = rh / ph
        bw = rw / pw

        # sample grid: (R, ph*ratio) y-coords and (R, pw*ratio) x-coords
        iy = (jnp.arange(ph * ratio) + 0.5) / ratio
        ix = (jnp.arange(pw * ratio) + 0.5) / ratio
        ys = y1[:, None] + bh[:, None] * iy[None, :]
        xs = x1[:, None] + bw[:, None] * ix[None, :]

        H, W = feat.shape[2], feat.shape[3]

        def bilinear(r_feat, yy, xx):
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
            y1i = jnp.clip(y0 + 1, 0, H - 1)
            x1i = jnp.clip(x0 + 1, 0, W - 1)
            wy = jnp.clip(yy - y0, 0, 1)
            wx = jnp.clip(xx - x0, 0, 1)
            # r_feat: (C, H, W); gather the 4 corners on the sample grid
            g = lambda yi, xi: r_feat[:, yi][:, :, xi]  # (C, ny, nx)
            v = (g(y0, x0) * ((1 - wy)[:, None] * (1 - wx)[None, :])
                 + g(y1i, x0) * (wy[:, None] * (1 - wx)[None, :])
                 + g(y0, x1i) * ((1 - wy)[:, None] * wx[None, :])
                 + g(y1i, x1i) * (wy[:, None] * wx[None, :]))
            return v

        def one_roi(r):
            r_feat = feat[batch_idx[r]]
            v = bilinear(r_feat, ys[r], xs[r])  # (C, ph*ratio, pw*ratio)
            C = v.shape[0]
            v = v.reshape(C, ph, ratio, pw, ratio).mean(axis=(2, 4))
            return v

        return jax.vmap(one_roi)(jnp.arange(boxes.shape[0]))

    return apply_op("roi_align", fn, [x, boxes])


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """Max-pool RoI (legacy; implemented via dense sampling + max)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    out = roi_align(x, boxes, boxes_num, output_size,
                    spatial_scale=spatial_scale, sampling_ratio=1,
                    aligned=False)
    return out


def box_iou(boxes1, boxes2):
    b1 = _t(boxes1)
    b2 = _t(boxes2)

    def fn(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(area1[:, None] + area2[None, :] - inter,
                                   1e-10)

    return apply_op("box_iou", fn, [b1, b2])


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None, axis=0):
    """Encode/decode target boxes against prior (anchor) boxes (ref
    ``phi/kernels/box_coder_kernel.h``; python
    ``fluid/layers/detection.py:827`` — the SSD-family box transform).

    encode: target [N, 4] x prior [M, 4] -> [N, M, 4] offsets
    decode: target [N, M, 4] x prior broadcast along ``axis`` -> boxes
    ``prior_box_var`` may be a [M, 4] tensor, a 4-list, or None.
    """
    pb = _t(prior_box)
    tb = _t(target_box)
    var_is_tensor = not (prior_box_var is None
                         or isinstance(prior_box_var, (list, tuple)))
    var_list = (None if var_is_tensor or prior_box_var is None
                else jnp.asarray(prior_box_var, jnp.float32))

    def _center_size(b):
        # [xmin, ymin, xmax, ymax] -> center x/y, w/h (+1 when unnormalized,
        # matching the reference's pixel-box convention)
        norm = 0.0 if box_normalized else 1.0
        w = b[..., 2] - b[..., 0] + norm
        h = b[..., 3] - b[..., 1] + norm
        cx = b[..., 0] + w * 0.5
        cy = b[..., 1] + h * 0.5
        return cx, cy, w, h

    def fn(pbv, tbv, *rest):
        var = rest[0] if rest else var_list
        pcx, pcy, pw, ph = _center_size(pbv)            # (M,)
        if code_type == "encode_center_size":
            tcx, tcy, tw, th = _center_size(tbv)        # (N,)
            ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
            oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
            ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
            oh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
            out = jnp.stack([ox, oy, ow, oh], axis=-1)  # (N, M, 4)
            if var is not None:
                v = var if var.ndim == 1 else var[None, :, :]
                out = out / v
            return out
        if code_type != "decode_center_size":
            raise ValueError(f"unknown code_type {code_type!r}")
        # decode: tbv (N, M, 4); `axis` is the target dim the prior
        # broadcasts ACROSS (axis=0: prior [M,4] aligns with dim 1)
        expand = (None, slice(None)) if axis == 0 else (slice(None), None)
        pcx, pcy, pw, ph = (a[expand] for a in (pcx, pcy, pw, ph))
        t = tbv
        if var is not None:
            if var.ndim == 1:
                t = t * var
            else:
                t = t * (var[expand + (slice(None),)])
        dcx = pw * t[..., 0] + pcx
        dcy = ph * t[..., 1] + pcy
        dw = jnp.exp(t[..., 2]) * pw
        dh = jnp.exp(t[..., 3]) * ph
        norm = 0.0 if box_normalized else 1.0
        return jnp.stack([dcx - dw * 0.5, dcy - dh * 0.5,
                          dcx + dw * 0.5 - norm,
                          dcy + dh * 0.5 - norm], axis=-1)

    args = [pb, tb]
    if var_is_tensor:
        args.append(_t(prior_box_var))
    return apply_op("box_coder", fn, args)


class RoIAlign(object):
    """Layer wrapper of roi_align (ref vision/ops.py RoIAlign)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale, aligned=aligned)


class RoIPool(object):
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """Position-sensitive RoI pooling (ref phi PsroiPoolKernel): channel
    block (c, i, j) feeds output bin (i, j)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    x = _t(x)
    C = x.shape[1]
    if C % (ph * pw):
        raise ValueError(f"channels {C} must be divisible by {ph}*{pw}")
    out_c = C // (ph * pw)
    # sample each position-sensitive block densely then select its own bin
    full = roi_align(x, boxes, boxes_num, output_size,
                     spatial_scale=spatial_scale, sampling_ratio=1,
                     aligned=False)  # (R, C, ph, pw)

    def fn(v):
        R = v.shape[0]
        v = v.reshape(R, out_c, ph, pw, ph, pw)
        ii = jnp.arange(ph)
        jj = jnp.arange(pw)
        return v[:, :, ii[:, None], jj[None, :], ii[:, None], jj[None, :]]
    return apply_op("psroi_pool", fn, [full])


class PSRoIPool(object):
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None):
    """Deformable conv v1/v2 (ref phi DeformableConvKernel): bilinear
    sampling at offset kernel taps, then a dense contraction — the gather
    feeds the MXU matmul, the TPU-native formulation."""
    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    sh, sw = pair(stride)
    ph_, pw_ = pair(padding)
    dh, dw = pair(dilation)
    x = _t(x)
    offset = _t(offset)
    weight = _t(weight)

    def fn(v, off, w, *rest):
        msk = rest[0] if mask is not None else None
        N, Cin, H, W = v.shape
        Cout, _, kh, kw = w.shape
        Ho = (H + 2 * ph_ - dh * (kh - 1) - 1) // sh + 1
        Wo = (W + 2 * pw_ - dw * (kw - 1) - 1) // sw + 1
        K = kh * kw
        dg = deformable_groups
        off = off.reshape(N, dg, K, 2, Ho, Wo)  # (y, x) per tap
        # base sampling positions
        hh = jnp.arange(Ho) * sh - ph_
        ww = jnp.arange(Wo) * sw - pw_
        ky = jnp.arange(kh) * dh
        kx = jnp.arange(kw) * dw
        taps_y = jnp.repeat(ky, kw).reshape(K, 1, 1)
        taps_x = jnp.tile(kx, kh).reshape(K, 1, 1)
        pos_y = hh[None, :, None] + taps_y  # (K, Ho, 1)
        pos_x = ww[None, None, :] + taps_x  # (K, 1, Wo)
        # add offsets: (N, dg, K, Ho, Wo)
        sy = pos_y[None, None] + off[:, :, :, 0]
        sx = pos_x[None, None] + off[:, :, :, 1]

        y0 = jnp.floor(sy)
        x0 = jnp.floor(sx)
        wy = sy - y0
        wx = sx - x0

        def gather(yi, xi):
            yc = jnp.clip(yi.astype(jnp.int32), 0, H - 1)
            xc = jnp.clip(xi.astype(jnp.int32), 0, W - 1)
            inb = ((yi >= 0) & (yi <= H - 1) & (xi >= 0) & (xi <= W - 1)
                   ).astype(v.dtype)
            # v: (N, Cin, H, W) -> samples (N, dg, cpg, K, Ho, Wo)
            cpg = Cin // dg
            vg = v.reshape(N, dg, cpg, H, W)
            flat = yc * W + xc  # (N, dg, K, Ho, Wo)
            vgf = vg.reshape(N, dg, cpg, H * W)
            g = jnp.take_along_axis(
                vgf[:, :, :, None, :],
                flat[:, :, None, :, :, :].reshape(N, dg, 1, K, Ho * Wo),
                axis=-1)
            return (g.reshape(N, dg, cpg, K, Ho, Wo)
                    * inb[:, :, None]), None

        (v00, _) = gather(y0, x0)
        (v01, _) = gather(y0, x0 + 1)
        (v10, _) = gather(y0 + 1, x0)
        (v11, _) = gather(y0 + 1, x0 + 1)
        wy_ = wy[:, :, None]
        wx_ = wx[:, :, None]
        samp = (v00 * (1 - wy_) * (1 - wx_) + v01 * (1 - wy_) * wx_
                + v10 * wy_ * (1 - wx_) + v11 * wy_ * wx_)
        if msk is not None:
            samp = samp * msk.reshape(N, dg, 1, K, Ho, Wo)
        samp = samp.reshape(N, Cin, K, Ho, Wo)
        # contraction: (Cout, Cin/groups, K) x (N, Cin, K, Ho, Wo)
        wk = w.reshape(Cout, -1, K)
        if groups == 1:
            out = jnp.einsum("ock,nckhw->nohw", wk, samp)
        else:
            cpg_in = Cin // groups
            cpg_out = Cout // groups
            sampg = samp.reshape(N, groups, cpg_in, K, Ho, Wo)
            wg = wk.reshape(groups, cpg_out, cpg_in, K)
            out = jnp.einsum("gock,ngckhw->ngohw", wg, sampg
                             ).reshape(N, Cout, Ho, Wo)
        if rest and mask is None:
            out = out + rest[0].reshape(1, -1, 1, 1)
        elif len(rest) > 1:
            out = out + rest[1].reshape(1, -1, 1, 1)
        return out

    args = [x, offset, weight]
    if mask is not None:
        args.append(_t(mask))
    if bias is not None:
        args.append(_t(bias))
    return apply_op("deform_conv2d", fn, args)


class DeformConv2D:
    """Deformable conv layer owning weight/bias (ref vision/ops.py
    DeformConv2D). Import under nn-layer protocol lazily to keep vision.ops
    importable standalone."""

    def __new__(cls, in_channels, out_channels, kernel_size, stride=1,
                padding=0, dilation=1, deformable_groups=1, groups=1,
                weight_attr=None, bias_attr=None):
        from ..nn.layer import Layer
        from ..nn.parameter import create_parameter

        def pair(v):
            return (v, v) if isinstance(v, int) else tuple(v)

        class _DeformConv2D(Layer):
            def __init__(self):
                super().__init__()
                kh, kw = pair(kernel_size)
                self.weight = create_parameter(
                    [out_channels, in_channels // groups, kh, kw], "float32",
                    attr=weight_attr)
                self.bias = (None if bias_attr is False else create_parameter(
                    [out_channels], "float32", attr=bias_attr, is_bias=True))

            def forward(self, x, offset, mask=None):
                return deform_conv2d(x, offset, self.weight, self.bias,
                                     stride, padding, dilation,
                                     deformable_groups, groups, mask)

        return _DeformConv2D()


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5, name=None):
    """Decode a YOLOv3 head into boxes + scores (ref phi YoloBoxKernel)."""
    x = _t(x)
    img_size = _t(img_size)
    an = np.asarray(anchors, np.float32).reshape(-1, 2)
    na = an.shape[0]

    def fn(v, imgs):
        N, C, H, W = v.shape
        v = v.reshape(N, na, -1, H, W)  # (N, A, 5+cls[(+1 iou)], H, W)
        if iou_aware:
            ioup = jax.nn.sigmoid(v[:, :, -1])
            v = v[:, :, :-1]
        tx, ty, tw, th, tobj = (v[:, :, i] for i in range(5))
        cls_logits = v[:, :, 5:5 + class_num]
        gx = jnp.arange(W)[None, None, None, :]
        gy = jnp.arange(H)[None, None, :, None]
        bx = ((jax.nn.sigmoid(tx) - 0.5) * scale_x_y + 0.5 + gx) / W
        by = ((jax.nn.sigmoid(ty) - 0.5) * scale_x_y + 0.5 + gy) / H
        anw = an[:, 0][None, :, None, None]
        anh = an[:, 1][None, :, None, None]
        bw = jnp.exp(tw) * anw / (W * downsample_ratio)
        bh = jnp.exp(th) * anh / (H * downsample_ratio)
        obj = jax.nn.sigmoid(tobj)
        if iou_aware:
            obj = obj ** (1 - iou_aware_factor) * ioup ** iou_aware_factor
        imh = imgs[:, 0].astype(v.dtype)[:, None, None, None]
        imw = imgs[:, 1].astype(v.dtype)[:, None, None, None]
        x1 = (bx - bw / 2) * imw
        y1 = (by - bh / 2) * imh
        x2 = (bx + bw / 2) * imw
        y2 = (by + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(N, -1, 4)
        probs = jax.nn.sigmoid(cls_logits) * obj[:, :, None]
        probs = jnp.where(obj[:, :, None] < conf_thresh, 0.0, probs)
        scores = probs.transpose(0, 1, 3, 4, 2).reshape(N, -1, class_num)
        return boxes, scores

    return apply_op("yolo_box", fn, [x, img_size], n_outputs=2)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, scale_x_y=1.0, name=None):
    """YOLOv3 loss (ref yolov3_loss op): box regression on responsible
    anchors + objectness with ignore region + classification."""
    x = _t(x)
    gt_box = _t(gt_box)
    gt_label = _t(gt_label)
    an_full = np.asarray(anchors, np.float32).reshape(-1, 2)
    msk = list(anchor_mask)
    an = an_full[msk]
    na = len(msk)

    def fn(v, gb, gl, *rest):
        gs = rest[0] if rest else None
        N, C, H, W = v.shape
        in_w = W * downsample_ratio
        in_h = H * downsample_ratio
        v = v.reshape(N, na, 5 + class_num, H, W)
        tx, ty, tw, th, tobj = (v[:, :, i] for i in range(5))
        tcls = v[:, :, 5:]
        B = gb.shape[1]
        # gt in [0,1] cx,cy,w,h (relative); responsible cell + anchor
        gx, gy = gb[..., 0], gb[..., 1]
        gw, gh = gb[..., 2], gb[..., 3]
        gi = jnp.clip((gx * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gy * H).astype(jnp.int32), 0, H - 1)
        valid = (gw > 0) & (gh > 0)
        # best anchor by IoU of (w,h) vs all anchors (shifted to origin)
        aw = an_full[:, 0] / in_w
        ah = an_full[:, 1] / in_h
        inter = (jnp.minimum(gw[..., None], aw) * jnp.minimum(gh[..., None], ah))
        union = gw[..., None] * gh[..., None] + aw * ah - inter
        best = jnp.argmax(inter / (union + 1e-9), -1)  # (N, B) global anchor id
        # map to local mask position (or -1)
        local = jnp.full_like(best, -1)
        for li, g in enumerate(msk):
            local = jnp.where(best == g, li, local)
        resp = valid & (local >= 0)
        # predicted boxes for ignore mask
        cellx = (jax.nn.sigmoid(tx) - 0.5) * scale_x_y + 0.5
        celly = (jax.nn.sigmoid(ty) - 0.5) * scale_x_y + 0.5
        px = (cellx + jnp.arange(W)[None, None, None, :]) / W
        py = (celly + jnp.arange(H)[None, None, :, None]) / H
        pw = jnp.exp(tw) * an[:, 0][None, :, None, None] / in_w
        ph2 = jnp.exp(th) * an[:, 1][None, :, None, None] / in_h
        # IoU of each prediction with each gt (N, A, H, W, B)
        px1, px2 = px - pw / 2, px + pw / 2
        py1, py2 = py - ph2 / 2, py + ph2 / 2
        gx1, gx2 = gx - gw / 2, gx + gw / 2
        gy1, gy2 = gy - gh / 2, gy + gh / 2
        ix1 = jnp.maximum(px1[..., None], gx1[:, None, None, None, :])
        ix2 = jnp.minimum(px2[..., None], gx2[:, None, None, None, :])
        iy1 = jnp.maximum(py1[..., None], gy1[:, None, None, None, :])
        iy2 = jnp.minimum(py2[..., None], gy2[:, None, None, None, :])
        inter2 = (jnp.clip(ix2 - ix1, 0) * jnp.clip(iy2 - iy1, 0))
        area_p = pw * ph2
        area_g = (gw * gh)[:, None, None, None, :]
        iou = inter2 / (area_p[..., None] + area_g - inter2 + 1e-9)
        iou = jnp.where(valid[:, None, None, None, :], iou, 0.0)
        ignore = (jnp.max(iou, -1) > ignore_thresh)
        # objectness target: scatter 1 at responsible (n, local, gj, gi)
        obj_t = jnp.zeros((N, na, H, W))
        score_w = gs if gs is not None else jnp.ones((N, B))
        nidx = jnp.repeat(jnp.arange(N)[:, None], B, 1)
        sel = resp
        obj_t = obj_t.at[nidx, jnp.maximum(local, 0), gj, gi].max(
            jnp.where(sel, score_w, 0.0))
        obj_mask = jnp.zeros((N, na, H, W), bool).at[
            nidx, jnp.maximum(local, 0), gj, gi].max(sel)
        noobj_mask = (~obj_mask) & (~ignore)
        # losses
        bce = lambda lg, t: jnp.maximum(lg, 0) - lg * t + jnp.log1p(
            jnp.exp(-jnp.abs(lg)))
        obj_loss = (jnp.where(obj_mask, bce(tobj, obj_t), 0.0).sum((1, 2, 3))
                    + jnp.where(noobj_mask, bce(tobj, 0.0), 0.0).sum((1, 2, 3)))
        # box loss at responsible cells
        tgt_x = gx * W - gi
        tgt_y = gy * H - gj
        sel_aw = an_full[jnp.maximum(best, 0), 0]
        sel_ah = an_full[jnp.maximum(best, 0), 1]
        tgt_w = jnp.log(jnp.clip(gw * in_w / sel_aw, 1e-9, None))
        tgt_h = jnp.log(jnp.clip(gh * in_h / sel_ah, 1e-9, None))
        scale_box = 2.0 - gw * gh
        lx = tx[nidx, jnp.maximum(local, 0), gj, gi]
        ly = ty[nidx, jnp.maximum(local, 0), gj, gi]
        lw = tw[nidx, jnp.maximum(local, 0), gj, gi]
        lh = th[nidx, jnp.maximum(local, 0), gj, gi]
        box_loss = jnp.where(
            sel,
            (bce(lx, tgt_x) + bce(ly, tgt_y)) * scale_box * score_w
            + (jnp.abs(lw - tgt_w) + jnp.abs(lh - tgt_h)) * scale_box * score_w,
            0.0).sum(-1)
        # cls loss
        smooth = 1.0 / class_num if (use_label_smooth and class_num > 1) else 0.0
        onehot = jax.nn.one_hot(jnp.clip(gl, 0, class_num - 1), class_num)
        onehot = onehot * (1 - smooth) + smooth / class_num
        lcls = tcls.transpose(0, 1, 3, 4, 2)[nidx, jnp.maximum(local, 0), gj, gi]
        cls_loss = jnp.where(sel[..., None],
                             bce(lcls, onehot) * score_w[..., None],
                             0.0).sum((-1, -2))
        return obj_loss + box_loss + cls_loss

    args = [x, gt_box, gt_label]
    if gt_score is not None:
        args.append(_t(gt_score))
    return apply_op("yolo_loss", fn, args)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Assign RoIs to FPN levels by scale (ref phi
    DistributeFpnProposalsKernel). Host-side (ragged outputs)."""
    from ..core.autograd import no_grad
    with no_grad():
        rois = np.asarray(_t(fpn_rois)._value)
        off = 1.0 if pixel_offset else 0.0
        w = rois[:, 2] - rois[:, 0] + off
        h = rois[:, 3] - rois[:, 1] + off
        scale = np.sqrt(np.clip(w * h, 0, None))
        lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
        lvl = np.clip(lvl, min_level, max_level).astype(int)
        # per-image grouping: img_id of each roi from rois_num
        if rois_num is not None:
            rn = np.asarray(_t(rois_num)._value, np.int64)
            img_of = np.repeat(np.arange(len(rn)), rn)
            n_img = len(rn)
        else:
            img_of = np.zeros(len(rois), np.int64)
            n_img = 1
        outs, nums, order = [], [], []
        for L in range(min_level, max_level + 1):
            idx = np.nonzero(lvl == L)[0]
            # keep image-major order within the level (reference layout)
            idx = idx[np.argsort(img_of[idx], kind="stable")]
            outs.append(Tensor(jnp.asarray(rois[idx])))
            per_img = np.bincount(img_of[idx], minlength=n_img).astype(np.int32)
            nums.append(Tensor(jnp.asarray(per_img)))
            order.extend(idx.tolist())
        restore = np.argsort(np.asarray(order, np.int64))
        return outs, Tensor(jnp.asarray(restore.astype(np.int32))), nums


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False, name=None):
    """RPN proposal generation (ref phi GenerateProposalsV2Kernel):
    decode anchors+deltas, clip, filter small, NMS. Host-side."""
    from ..core.autograd import no_grad
    with no_grad():
        sc = np.asarray(_t(scores)._value)          # (N, A, H, W)
        bd = np.asarray(_t(bbox_deltas)._value)     # (N, A*4, H, W)
        ims = np.asarray(_t(img_size)._value)       # (N, 2) h, w
        anc = np.asarray(_t(anchors)._value).reshape(-1, 4)
        var = np.asarray(_t(variances)._value).reshape(-1, 4)
        N, A, H, W = sc.shape
        all_rois, all_nums, all_scores = [], [], []
        off = 1.0 if pixel_offset else 0.0
        for n in range(N):
            s = sc[n].transpose(1, 2, 0).reshape(-1)           # H*W*A
            d = bd[n].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
            # anchors/variances arrive as (H*W*A, 4) position-major
            a = anc.reshape(-1, 4)
            if a.shape[0] == A:  # per-anchor only: broadcast over positions
                a = np.broadcast_to(a[None, None], (H, W, A, 4)).reshape(-1, 4)
            v = var.reshape(-1, 4)
            if v.shape[0] == A:
                v = np.broadcast_to(v[None, None], (H, W, A, 4)).reshape(-1, 4)
            order = np.argsort(-s)[:pre_nms_top_n]
            s, d, a, v = s[order], d[order], a[order], v[order]
            aw = a[:, 2] - a[:, 0] + off
            ah = a[:, 3] - a[:, 1] + off
            acx = a[:, 0] + aw / 2
            acy = a[:, 1] + ah / 2
            cx = v[:, 0] * d[:, 0] * aw + acx
            cy = v[:, 1] * d[:, 1] * ah + acy
            wN = np.exp(np.clip(v[:, 2] * d[:, 2], None, 10)) * aw
            hN = np.exp(np.clip(v[:, 3] * d[:, 3], None, 10)) * ah
            x1 = cx - wN / 2
            y1 = cy - hN / 2
            x2 = cx + wN / 2 - off
            y2 = cy + hN / 2 - off
            imh, imw = ims[n]
            x1 = np.clip(x1, 0, imw - off)
            y1 = np.clip(y1, 0, imh - off)
            x2 = np.clip(x2, 0, imw - off)
            y2 = np.clip(y2, 0, imh - off)
            keep = ((x2 - x1 + off >= min_size)
                    & (y2 - y1 + off >= min_size))
            boxes = np.stack([x1, y1, x2, y2], 1)[keep]
            s = s[keep]
            # greedy NMS
            sel = []
            idxs = np.argsort(-s)
            while len(idxs) and len(sel) < post_nms_top_n:
                i = idxs[0]
                sel.append(i)
                if len(idxs) == 1:
                    break
                rest = idxs[1:]
                xx1 = np.maximum(boxes[i, 0], boxes[rest, 0])
                yy1 = np.maximum(boxes[i, 1], boxes[rest, 1])
                xx2 = np.minimum(boxes[i, 2], boxes[rest, 2])
                yy2 = np.minimum(boxes[i, 3], boxes[rest, 3])
                iw = np.clip(xx2 - xx1 + off, 0, None)
                ih = np.clip(yy2 - yy1 + off, 0, None)
                inter = iw * ih
                ai = ((boxes[i, 2] - boxes[i, 0] + off)
                      * (boxes[i, 3] - boxes[i, 1] + off))
                ar = ((boxes[rest, 2] - boxes[rest, 0] + off)
                      * (boxes[rest, 3] - boxes[rest, 1] + off))
                iou = inter / (ai + ar - inter + 1e-9)
                idxs = rest[iou <= nms_thresh]
            sel = np.asarray(sel, int)
            all_rois.append(boxes[sel])
            all_scores.append(s[sel])
            all_nums.append(len(sel))
        rois = Tensor(jnp.asarray(np.concatenate(all_rois)
                                  if all_rois else np.zeros((0, 4))))
        rscores = Tensor(jnp.asarray(np.concatenate(all_scores)
                                     if all_scores else np.zeros((0,))))
        nums = Tensor(jnp.asarray(np.asarray(all_nums, np.int32)))
        if return_rois_num:
            return rois, rscores, nums
        return rois, rscores


def read_file(filename, name=None):
    """Read raw bytes into a uint8 tensor (ref phi ReadFileKernel)."""
    data = np.fromfile(filename, dtype=np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to CHW uint8 (ref phi DecodeJpegKernel —
    nvjpeg there; PIL on host here)."""
    import io as _io
    try:
        from PIL import Image
    except ImportError as e:
        raise RuntimeError("decode_jpeg needs PIL on this build") from e
    data = bytes(np.asarray(_t(x)._value, np.uint8))
    img = Image.open(_io.BytesIO(data))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))
