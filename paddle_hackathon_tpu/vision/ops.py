"""Detection ops (ref ``python/paddle/vision/ops.py`` — nms, roi_align,
roi_pool, box coders; backed there by CUDA kernels in
``paddle/phi/kernels/gpu/{nms,roi_align}_kernel.cu``).

On TPU these are XLA compositions: nms is a sequential suppression loop
(lax.fori_loop — small N, scalar control on the VPU), roi_align is a
gather+bilinear composition that XLA vectorises.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.autograd import apply_op
from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Hard-NMS. Returns indices of kept boxes sorted by descending score.

    Ref ``vision/ops.py nms``; category-aware by offsetting boxes per class
    (the standard batched-nms trick) so one pass covers all classes.
    """
    boxes = _t(boxes)
    n = boxes.shape[0]
    if scores is None:
        scores_v = jnp.zeros((n,), jnp.float32)
    else:
        scores_v = _t(scores)._value.astype(jnp.float32)
    boxes_v = boxes._value.astype(jnp.float32)
    if category_idxs is not None:
        cat = _t(category_idxs)._value.astype(jnp.float32)
        span = (boxes_v.max() - boxes_v.min()) + 1.0
        boxes_v = boxes_v + (cat * span)[:, None]

    order = jnp.argsort(-scores_v)
    b = boxes_v[order]

    x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    areas = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)

    def body(i, keep):
        xx1 = jnp.maximum(x1[i], x1)
        yy1 = jnp.maximum(y1[i], y1)
        xx2 = jnp.minimum(x2[i], x2)
        yy2 = jnp.minimum(y2[i], y2)
        inter = (jnp.maximum(xx2 - xx1, 0) * jnp.maximum(yy2 - yy1, 0))
        iou = inter / jnp.maximum(areas[i] + areas - inter, 1e-10)
        # suppress j>i with high IoU if i itself is still kept
        suppress = (iou > iou_threshold) & (jnp.arange(x1.shape[0]) > i)
        return jnp.where(keep[i], keep & ~suppress, keep)

    keep = jax.lax.fori_loop(0, x1.shape[0], body,
                             jnp.ones((x1.shape[0],), bool))
    kept_sorted_idx = jnp.nonzero(keep, size=x1.shape[0], fill_value=-1)[0]
    result = order[kept_sorted_idx]
    result = result[kept_sorted_idx >= 0]
    if top_k is not None:
        result = result[:top_k]
    return Tensor(result)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign over NCHW features (ref phi roi_align_kernel).

    ``boxes``: (R, 4) [x1, y1, x2, y2]; ``boxes_num``: rois per image.
    """
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    ratio = 1 if sampling_ratio <= 0 else sampling_ratio

    x = _t(x)
    boxes = _t(boxes)
    bn = jnp.asarray(_t(boxes_num)._value, jnp.int32)
    batch_idx = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                           total_repeat_length=boxes.shape[0])

    def fn(feat, rois):
        off = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - off
        y1 = rois[:, 1] * spatial_scale - off
        x2 = rois[:, 2] * spatial_scale - off
        y2 = rois[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-4 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-4 if aligned else 1.0)
        bh = rh / ph
        bw = rw / pw

        # sample grid: (R, ph*ratio) y-coords and (R, pw*ratio) x-coords
        iy = (jnp.arange(ph * ratio) + 0.5) / ratio
        ix = (jnp.arange(pw * ratio) + 0.5) / ratio
        ys = y1[:, None] + bh[:, None] * iy[None, :]
        xs = x1[:, None] + bw[:, None] * ix[None, :]

        H, W = feat.shape[2], feat.shape[3]

        def bilinear(r_feat, yy, xx):
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
            y1i = jnp.clip(y0 + 1, 0, H - 1)
            x1i = jnp.clip(x0 + 1, 0, W - 1)
            wy = jnp.clip(yy - y0, 0, 1)
            wx = jnp.clip(xx - x0, 0, 1)
            # r_feat: (C, H, W); gather the 4 corners on the sample grid
            g = lambda yi, xi: r_feat[:, yi][:, :, xi]  # (C, ny, nx)
            v = (g(y0, x0) * ((1 - wy)[:, None] * (1 - wx)[None, :])
                 + g(y1i, x0) * (wy[:, None] * (1 - wx)[None, :])
                 + g(y0, x1i) * ((1 - wy)[:, None] * wx[None, :])
                 + g(y1i, x1i) * (wy[:, None] * wx[None, :]))
            return v

        def one_roi(r):
            r_feat = feat[batch_idx[r]]
            v = bilinear(r_feat, ys[r], xs[r])  # (C, ph*ratio, pw*ratio)
            C = v.shape[0]
            v = v.reshape(C, ph, ratio, pw, ratio).mean(axis=(2, 4))
            return v

        return jax.vmap(one_roi)(jnp.arange(boxes.shape[0]))

    return apply_op("roi_align", fn, [x, boxes])


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """Max-pool RoI (legacy; implemented via dense sampling + max)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    out = roi_align(x, boxes, boxes_num, output_size,
                    spatial_scale=spatial_scale, sampling_ratio=1,
                    aligned=False)
    return out


def box_iou(boxes1, boxes2):
    b1 = _t(boxes1)
    b2 = _t(boxes2)

    def fn(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(area1[:, None] + area2[None, :] - inter,
                                   1e-10)

    return apply_op("box_iou", fn, [b1, b2])
