from . import functional  # noqa: F401
from .transforms import (BaseTransform, CenterCrop, ColorJitter,  # noqa: F401
                         Compose, Grayscale, Normalize, Pad, RandomCrop,
                         RandomHorizontalFlip, RandomResizedCrop,
                         RandomRotation, RandomVerticalFlip, Resize, ToTensor,
                         Transpose)
