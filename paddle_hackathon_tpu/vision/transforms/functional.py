"""Functional image transforms (ref ``python/paddle/vision/transforms/
functional.py`` + ``functional_cv2.py``).

Operate on numpy HWC uint8/float arrays (the reference's cv2/PIL backends)
or on framework Tensors (CHW); transforms run on host as part of the input
pipeline — device work starts at ``to_tensor``.
"""

from __future__ import annotations

import numbers

import numpy as np

from ...core.tensor import Tensor


def _as_hwc(img):
    if isinstance(img, Tensor):
        img = np.asarray(img._value)
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def to_tensor(pic, data_format="CHW"):
    """HWC [0,255] uint8 (or float) image -> float32 tensor scaled to [0,1]."""
    img = _as_hwc(pic)
    if img.dtype == np.uint8:
        img = img.astype(np.float32) / 255.0
    else:
        img = img.astype(np.float32)
    if data_format == "CHW":
        img = np.transpose(img, (2, 0, 1))
    return Tensor(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    if isinstance(img, Tensor):
        arr = np.asarray(img._value).astype(np.float32)
    else:
        arr = np.asarray(img).astype(np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    ch = arr.shape[0] if data_format == "CHW" else arr.shape[-1]
    if mean.ndim and mean.shape[0] not in (1, ch):
        raise ValueError(
            f"normalize mean has {mean.shape[0]} entries but the image has "
            f"{ch} channels")
    if data_format == "CHW":
        arr = (arr - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)
    else:
        arr = (arr - mean) / std
    return Tensor(arr) if isinstance(img, Tensor) else arr


def _interp_resize(img, size):
    """Bilinear resize of an HWC numpy image (no cv2/PIL dependency)."""
    h, w = img.shape[:2]
    if isinstance(size, numbers.Number):
        # shorter edge -> size, keep aspect (paddle semantics)
        if h <= w:
            oh, ow = int(size), max(int(size * w / h), 1)
        else:
            oh, ow = max(int(size * h / w), 1), int(size)
    else:
        oh, ow = size
    if (oh, ow) == (h, w):
        return img
    ys = (np.arange(oh) + 0.5) * h / oh - 0.5
    xs = (np.arange(ow) + 0.5) * w / ow - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    f = img.astype(np.float32)
    out = ((f[y0][:, x0] * (1 - wy) + f[y1][:, x0] * wy) * (1 - wx)
           + (f[y0][:, x1] * (1 - wy) + f[y1][:, x1] * wy) * wx)
    return out.astype(img.dtype) if img.dtype == np.uint8 else out


def resize(img, size, interpolation="bilinear"):
    return _interp_resize(_as_hwc(img), size)


def crop(img, top, left, height, width):
    return _as_hwc(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    img = _as_hwc(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = img.shape[:2]
    th, tw = output_size
    top = int(round((h - th) / 2.0))
    left = int(round((w - tw) / 2.0))
    return crop(img, top, left, th, tw)


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


def pad(img, padding, fill=0, padding_mode="constant"):
    img = _as_hwc(img)
    if isinstance(padding, numbers.Number):
        padding = (padding,) * 4
    elif len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    left, top, right, bottom = padding
    pads = ((top, bottom), (left, right), (0, 0))
    if padding_mode == "constant":
        return np.pad(img, pads, mode="constant", constant_values=fill)
    return np.pad(img, pads, mode={"edge": "edge", "reflect": "reflect",
                                   "symmetric": "symmetric"}[padding_mode])


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Nearest-neighbour rotation (host-side; ref functional_cv2.rotate)."""
    img = _as_hwc(img)
    h, w = img.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None else center[::-1]
    theta = np.deg2rad(angle)
    cos, sin = np.cos(theta), np.sin(theta)
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    ys = cos * (yy - cy) - sin * (xx - cx) + cy
    xs = sin * (yy - cy) + cos * (xx - cx) + cx
    yi = np.round(ys).astype(int)
    xi = np.round(xs).astype(int)
    valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
    out = np.full_like(img, fill)
    out[valid] = img[yi[valid], xi[valid]]
    return out


def adjust_brightness(img, brightness_factor):
    img = _as_hwc(img)
    out = img.astype(np.float32) * brightness_factor
    return np.clip(out, 0, 255).astype(img.dtype)


def adjust_contrast(img, contrast_factor):
    img = _as_hwc(img)
    mean = img.astype(np.float32).mean()
    out = (img.astype(np.float32) - mean) * contrast_factor + mean
    return np.clip(out, 0, 255).astype(img.dtype)


def adjust_hue(img, hue_factor):
    """Cheap hue shift by channel rotation interpolation."""
    img = _as_hwc(img).astype(np.float32)
    if img.shape[2] < 3:
        return img.astype(np.uint8)
    shifted = np.roll(img[:, :, :3], 1, axis=2)
    t = abs(hue_factor) * 2.0
    out = img.copy()
    out[:, :, :3] = img[:, :, :3] * (1 - t) + shifted * t
    return np.clip(out, 0, 255).astype(np.uint8)


def to_grayscale(img, num_output_channels=1):
    img = _as_hwc(img).astype(np.float32)
    if img.shape[2] >= 3:
        g = (0.299 * img[:, :, 0] + 0.587 * img[:, :, 1]
             + 0.114 * img[:, :, 2])
    else:
        g = img[:, :, 0]
    g = g[:, :, None]
    if num_output_channels == 3:
        g = np.repeat(g, 3, axis=2)
    return np.clip(g, 0, 255).astype(np.uint8)


def adjust_saturation(img, saturation_factor):
    """Blend with grayscale (ref functional_tensor.adjust_saturation)."""
    img = _as_hwc(img)
    gray = (0.299 * img[..., 0] + 0.587 * img[..., 1]
            + 0.114 * img[..., 2])[..., None]
    out = gray + saturation_factor * (img.astype(np.float64) - gray)
    return np.clip(out, 0, 255 if img.dtype == np.uint8 else 1.0
                   ).astype(img.dtype)


def erase(img, i, j, h, w, v, inplace=False):
    """Erase a region with value v (ref functional.erase). Works on HWC
    numpy or framework tensors (CHW Tensor path mirrors the reference)."""
    from ...core.tensor import Tensor as _FT
    if isinstance(img, _FT):
        import jax.numpy as _jnp
        arr = img._value
        val = v._value if isinstance(v, _FT) else _jnp.asarray(v)
        patch = _jnp.broadcast_to(val, arr[..., i:i + h, j:j + w].shape)
        return _FT(arr.at[..., i:i + h, j:j + w].set(patch.astype(arr.dtype)))
    img2 = img if inplace else np.array(img)
    img2[i:i + h, j:j + w] = v
    return img2


def _solve_perspective(src, dst):
    """8-dof homography coefficients mapping dst->src (cv2.getPerspectiveTransform)."""
    A, b = [], []
    for (x, y), (u, v) in zip(dst, src):
        A.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
        A.append([0, 0, 0, x, y, 1, -v * x, -v * y])
        b += [u, v]
    return np.linalg.solve(np.asarray(A, np.float64), np.asarray(b, np.float64))


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    """Perspective warp via inverse homography, nearest sampling
    (ref functional.perspective)."""
    img = _as_hwc(img)
    h, w = img.shape[:2]
    coef = _solve_perspective(startpoints, endpoints)
    a, b, c, d, e, f, g, hh = coef
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    den = g * xx + hh * yy + 1.0
    xs = (a * xx + b * yy + c) / den
    ys = (d * xx + e * yy + f) / den
    xi, yi = np.round(xs).astype(int), np.round(ys).astype(int)
    valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
    out = np.full_like(img, fill)
    out[valid] = img[yi[valid], xi[valid]]
    return out


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """Affine warp: rotate+translate+scale+shear by inverse mapping
    (ref functional.affine)."""
    img = _as_hwc(img)
    h, w = img.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None else center[::-1]
    theta = np.deg2rad(angle)
    sx, sy = (np.deg2rad(s) for s in (shear if isinstance(shear, (list, tuple))
                                      else (shear, 0.0)))
    # forward matrix: T(center) R(angle) Sh(shear) S(scale) T(-center) + translate
    R = np.array([[np.cos(theta + sy), -np.sin(theta + sx)],
                  [np.sin(theta + sy), np.cos(theta + sx)]]) * scale
    inv = np.linalg.inv(R)
    tx, ty = translate
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    pts = np.stack([xx - cx - tx, yy - cy - ty])
    src = np.einsum("ij,jhw->ihw", inv, pts)
    xs, ys = src[0] + cx, src[1] + cy
    xi, yi = np.round(xs).astype(int), np.round(ys).astype(int)
    valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
    out = np.full_like(img, fill)
    out[valid] = img[yi[valid], xi[valid]]
    return out
