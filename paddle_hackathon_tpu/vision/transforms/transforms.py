"""Transform classes (ref ``python/paddle/vision/transforms/transforms.py``).

Same API shape as the reference (``BaseTransform`` with _apply_image, keys
support elided to the common image path)."""

from __future__ import annotations

import numbers
import random

import numpy as np

from . import functional as F


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data

    def __repr__(self):
        inner = ", ".join(repr(t) for t in self.transforms)
        return f"Compose({inner})"


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        # scalars stay scalar: expanding to 3 entries would silently
        # broadcast single-channel images up to 3 channels
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = F.pad(img, self.padding, self.fill, self.padding_mode)
        img = np.asarray(img)
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            img = F.pad(img, (max(tw - w, 0), max(th - h, 0)), self.fill,
                        self.padding_mode)
            h, w = img.shape[:2]
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return F.crop(img, top, left, th, tw)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.hflip(img) if random.random() < self.prob else img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.vflip(img) if random.random() < self.prob else img


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]),
                                       np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                crop = F.crop(img, top, left, ch, cw)
                return F.resize(crop, self.size, self.interpolation)
        return F.resize(F.center_crop(img, min(h, w)), self.size,
                        self.interpolation)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.kw = dict(interpolation=interpolation, expand=expand,
                       center=center, fill=fill)

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return F.rotate(img, angle, **self.kw)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.brightness = brightness
        self.contrast = contrast
        self.hue = hue

    def _apply_image(self, img):
        if self.brightness:
            img = F.adjust_brightness(
                img, random.uniform(max(0, 1 - self.brightness),
                                    1 + self.brightness))
        if self.contrast:
            img = F.adjust_contrast(
                img, random.uniform(max(0, 1 - self.contrast),
                                    1 + self.contrast))
        if self.hue:
            img = F.adjust_hue(img, random.uniform(-self.hue, self.hue))
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return np.transpose(np.asarray(img), self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("contrast value must be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(-self.value, self.value)
        return F.adjust_hue(img, f)


class RandomAffine(BaseTransform):
    """Random affine transformation (ref transforms.RandomAffine)."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = ((-degrees, degrees) if np.isscalar(degrees)
                        else tuple(degrees))
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        h, w = np.asarray(img).shape[:2]
        angle = np.random.uniform(*self.degrees)
        translate = (0, 0)
        if self.translate is not None:
            tx, ty = self.translate
            translate = (np.random.uniform(-tx, tx) * w,
                         np.random.uniform(-ty, ty) * h)
        scale = (np.random.uniform(*self.scale) if self.scale else 1.0)
        shear = 0.0
        if self.shear is not None:
            sh = ((-self.shear, self.shear) if np.isscalar(self.shear)
                  else tuple(self.shear))
            shear = np.random.uniform(sh[0], sh[1])
        return F.affine(img, angle, translate, scale, shear,
                        self.interpolation, self.fill, self.center)


class RandomErasing(BaseTransform):
    """Randomly erase a rectangle (ref transforms.RandomErasing)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        from ...core.tensor import Tensor as _FT
        chw = isinstance(img, _FT)  # framework tensors are CHW; arrays HWC
        shape = tuple(img.shape) if chw else np.asarray(img).shape
        h, w = shape[-2:] if chw else shape[:2]
        area = h * w
        for _ in range(10):
            target = np.random.uniform(*self.scale) * area
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh)
                j = np.random.randint(0, w - ew)
                if self.value == "random":
                    v = (np.random.rand(*shape[:-2], eh, ew) if chw
                         else np.random.rand(eh, ew, *shape[2:]))
                else:
                    v = self.value
                return F.erase(img, i, j, eh, ew, v, self.inplace)
        return img


class RandomPerspective(BaseTransform):
    """Random perspective distortion (ref transforms.RandomPerspective)."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        h, w = np.asarray(img).shape[:2]
        d = self.distortion_scale
        dx, dy = int(d * w / 2), int(d * h / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(np.random.randint(0, dx + 1), np.random.randint(0, dy + 1)),
               (w - 1 - np.random.randint(0, dx + 1), np.random.randint(0, dy + 1)),
               (w - 1 - np.random.randint(0, dx + 1), h - 1 - np.random.randint(0, dy + 1)),
               (np.random.randint(0, dx + 1), h - 1 - np.random.randint(0, dy + 1))]
        return F.perspective(img, start, end, self.interpolation, self.fill)
