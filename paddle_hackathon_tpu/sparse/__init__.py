"""Sparse tensors: COO/CSR formats + sparse ops + SelectedRows.

TPU-native counterpart of phi's sparse types and kernels
(``paddle/phi/core/sparse_coo_tensor.h:30``, ``sparse_csr_tensor.h:33``,
``paddle/phi/kernels/sparse/``) and the ``paddle.incubate.sparse`` python
surface, plus ``SelectedRows`` (``paddle/phi/core/selected_rows.h:27``) —
the rows+values sparse-gradient format embedding layers emit.

Mechanism: formats hold static index structure (host numpy) alongside
values that are framework Tensors, so sparse ops tape into the same
autograd engine as dense ops (unary ops differentiate through values; spmm
differentiates through both values and the dense operand). Kernels lower
to XLA gather/segment-sum with static nnz — the shapes XLA can tile for
TPU; for training-speed n:m sparsity see ``incubate.asp``.
"""

from .tensors import (SelectedRows, SparseCooTensor, SparseCsrTensor,  # noqa: F401
                      sparse_coo_tensor, sparse_csr_tensor, to_sparse_coo,
                      to_sparse_csr)
from .ops import (add, coalesce, masked_matmul, matmul, mv,  # noqa: F401
                  relu, sin, sqrt, tanh, transpose)
from . import nn  # noqa: F401

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "SelectedRows",
    "sparse_coo_tensor", "sparse_csr_tensor", "to_sparse_coo",
    "to_sparse_csr", "add", "coalesce", "masked_matmul", "matmul", "mv",
    "relu", "sin", "sqrt", "tanh", "transpose", "nn",
]
