"""Sparse ops (ref ``paddle/phi/kernels/sparse/`` + the
``paddle.incubate.sparse`` functional surface). Unary ops act on values
(zero-preserving functions only, matching the reference's ``unary_kernel``
set); ``matmul`` contracts sparse x dense as gather + segment-sum with
static nnz, which XLA tiles efficiently on TPU."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.autograd import apply_op
from ..core.tensor import Tensor
from .tensors import SparseCooTensor, SparseCsrTensor


def _unary(name, fn, x):
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x._indices, apply_op(name, fn, [x._values]),
                               x._shape, coalesced=x._coalesced)
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(x._crows, x._cols,
                               apply_op(name, fn, [x._values]), x._shape)
    raise TypeError(f"sparse.{name} expects a sparse tensor, got {type(x)}")


def relu(x):
    return _unary("sparse_relu", lambda v: jnp.maximum(v, 0), x)


def tanh(x):
    return _unary("sparse_tanh", jnp.tanh, x)


def sqrt(x):
    return _unary("sparse_sqrt", jnp.sqrt, x)


def sin(x):
    return _unary("sparse_sin", jnp.sin, x)


def coalesce(x: SparseCooTensor) -> SparseCooTensor:
    return x.coalesce()


def transpose(x: SparseCooTensor, perm) -> SparseCooTensor:
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.transpose expects a SparseCooTensor")
    perm = list(perm)
    if len(perm) != x.sparse_dim:
        raise ValueError("transpose currently permutes sparse dims only")
    new_idx = x._indices[jnp.asarray(perm, jnp.int32)]
    new_shape = tuple(x._shape[p] for p in perm) + tuple(
        x._shape[x.sparse_dim:])
    return SparseCooTensor(new_idx, x._values, new_shape)


def add(a, b):
    """sparse + sparse (same shape) -> sparse (union of patterns)."""
    if isinstance(a, SparseCooTensor) and isinstance(b, SparseCooTensor):
        if a._shape != b._shape:
            raise ValueError(f"shape mismatch {a._shape} vs {b._shape}")
        idx = jnp.concatenate([a._indices, b._indices], axis=1)
        vals = apply_op("sparse_concat_values",
                        lambda va, vb: jnp.concatenate([va, vb], axis=0),
                        [a._values, b._values])
        return SparseCooTensor(idx, vals, a._shape).coalesce()
    raise TypeError("sparse.add expects two SparseCooTensors")


def matmul(a, b):
    """sparse[m,k] @ dense[k,n] -> dense[m,n] (ref
    ``sparse/cpu|gpu/matmul_kernel``). Grad flows to both the sparse values
    and the dense operand."""
    if isinstance(a, SparseCsrTensor):
        a = a.to_sparse_coo()
    if not isinstance(a, SparseCooTensor):
        raise TypeError("sparse.matmul expects sparse lhs")
    if a.sparse_dim != 2 or len(a._shape) != 2:
        raise ValueError(
            f"matmul supports a purely 2-D sparse lhs; got shape "
            f"{list(a._shape)} with sparse_dim={a.sparse_dim}")
    rows, cols = a._indices[0], a._indices[1]
    m = a._shape[0]
    bt = b if isinstance(b, Tensor) else Tensor(jnp.asarray(b))
    if bt._value.ndim != 2 or bt._value.shape[0] != a._shape[1]:
        # must be explicit: jax's clamped gather would otherwise return
        # silently wrong numbers on a contraction-dim mismatch
        raise ValueError(
            f"sparse.matmul shape mismatch: sparse {list(a._shape)} @ "
            f"dense {list(bt._value.shape)}")

    def fn(vals, dense):
        contrib = vals[:, None] * dense[cols]          # [nnz, n]
        return jax.ops.segment_sum(contrib, rows, num_segments=m)

    return apply_op("sparse_matmul", fn, [a._values, bt])


def mv(a, x):
    """sparse[m,k] @ dense[k] -> dense[m]."""
    xt = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    out = matmul(a, apply_op("reshape", lambda v: v[:, None], [xt]))
    return apply_op("reshape", lambda v: v[:, 0], [out])


def masked_matmul(x, y, mask):
    """dense[m,k] @ dense[k,n], evaluated only at ``mask``'s nonzero
    coordinates -> sparse (ref ``masked_matmul_kernel``; the SDDMM op)."""
    if not isinstance(mask, SparseCooTensor):
        raise TypeError("mask must be a SparseCooTensor")
    rows, cols = mask._indices[0], mask._indices[1]
    xt = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    yt = y if isinstance(y, Tensor) else Tensor(jnp.asarray(y))

    def fn(xa, ya):
        return jnp.einsum("nk,nk->n", xa[rows], ya.T[cols])

    vals = apply_op("sparse_masked_matmul", fn, [xt, yt])
    return SparseCooTensor(mask._indices, vals, mask._shape,
                           coalesced=mask._coalesced)
