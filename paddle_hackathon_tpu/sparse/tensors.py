"""Sparse tensor types (ref ``phi/core/sparse_coo_tensor.h:30``,
``sparse_csr_tensor.h:33``, ``selected_rows.h:27``)."""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor


def _as_tensor(x, dtype=None) -> Tensor:
    if isinstance(x, Tensor):
        return x
    v = jnp.asarray(x, dtype)
    return Tensor(v)


class SparseCooTensor:
    """Coordinate-format sparse tensor.

    ``indices`` is a dense [sparse_dim, nnz] int array (static); ``values``
    is a framework Tensor [nnz, *dense_dims] participating in autograd.
    Mirrors phi's invariant layout (``sparse_coo_tensor.h:30``).
    """

    def __init__(self, indices, values: Tensor, shape: Sequence[int],
                 coalesced: bool = False):
        self._indices = jnp.asarray(indices, jnp.int32)
        self._values = values if isinstance(values, Tensor) else Tensor(
            jnp.asarray(values))
        self._shape = tuple(int(d) for d in shape)
        self._coalesced = coalesced

    # -- phi-parity accessors ----------------------------------------------
    def indices(self) -> Tensor:
        return Tensor(self._indices)

    def values(self) -> Tensor:
        return self._values

    @property
    def shape(self):
        return list(self._shape)

    @property
    def nnz(self) -> int:
        return int(self._indices.shape[1])

    @property
    def sparse_dim(self) -> int:
        return int(self._indices.shape[0])

    @property
    def dtype(self):
        return self._values.dtype

    def is_sparse_coo(self) -> bool:
        return True

    def is_sparse_csr(self) -> bool:
        return False

    # -- conversions ---------------------------------------------------------
    def to_dense(self) -> Tensor:
        from ..core.autograd import apply_op
        idx = self._indices
        shape = self._shape

        def fn(vals):
            out = jnp.zeros(shape, vals.dtype)
            return out.at[tuple(idx)].add(vals)

        return apply_op("sparse_coo_to_dense", fn, [self._values])

    def to_sparse_csr(self) -> "SparseCsrTensor":
        if len(self._shape) != 2:
            raise ValueError("to_sparse_csr supports 2-D tensors")
        coo = self.coalesce()
        rows = np.asarray(coo._indices[0])
        cols = np.asarray(coo._indices[1])
        order = np.lexsort((cols, rows))
        crows = np.zeros(self._shape[0] + 1, np.int32)
        np.add.at(crows[1:], rows[order], 1)
        crows = np.cumsum(crows).astype(np.int32)
        vals = coo._values
        if np.array_equal(order, np.arange(order.size)):
            # coalesce() emits row-major order, so the permutation is the
            # identity there; only user-constructed coalesced=True tensors
            # with unsorted indices pay the reorder gather
            sorted_vals = vals
        else:
            perm = jnp.asarray(order, jnp.int32)
            from ..core.autograd import apply_op
            sorted_vals = apply_op("sparse_reorder",
                                   lambda v: jnp.take(v, perm, axis=0), [vals])
        return SparseCsrTensor(crows, cols[order], sorted_vals, self._shape)

    def coalesce(self) -> "SparseCooTensor":
        """Merge duplicate coordinates (ref sparse_coo_tensor coalesced
        invariant)."""
        if self._coalesced:
            return self
        idx_np = np.asarray(self._indices)
        flat = np.ravel_multi_index(
            idx_np, self._shape[:self.sparse_dim])
        uniq, inv = np.unique(flat, return_inverse=True)
        new_idx = np.stack(np.unravel_index(
            uniq, self._shape[:self.sparse_dim])).astype(np.int32)
        seg = jnp.asarray(inv, jnp.int32)
        n_out = int(uniq.size)
        from ..core.autograd import apply_op
        new_vals = apply_op(
            "sparse_coalesce",
            lambda v: jax.ops.segment_sum(v, seg, num_segments=n_out),
            [self._values])
        return SparseCooTensor(new_idx, new_vals, self._shape, coalesced=True)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """Compressed-sparse-row tensor (2-D; ref ``sparse_csr_tensor.h:33``)."""

    def __init__(self, crows, cols, values: Tensor, shape: Sequence[int]):
        self._crows = jnp.asarray(crows, jnp.int32)
        self._cols = jnp.asarray(cols, jnp.int32)
        self._values = values if isinstance(values, Tensor) else Tensor(
            jnp.asarray(values))
        self._shape = tuple(int(d) for d in shape)

    def crows(self) -> Tensor:
        return Tensor(self._crows)

    def cols(self) -> Tensor:
        return Tensor(self._cols)

    def values(self) -> Tensor:
        return self._values

    @property
    def shape(self):
        return list(self._shape)

    @property
    def nnz(self) -> int:
        return int(self._cols.shape[0])

    @property
    def dtype(self):
        return self._values.dtype

    def is_sparse_coo(self) -> bool:
        return False

    def is_sparse_csr(self) -> bool:
        return True

    def _row_ids(self) -> np.ndarray:
        """Expand crows to one row id per stored entry."""
        counts = np.diff(np.asarray(self._crows))
        return np.repeat(np.arange(self._shape[0]), counts).astype(np.int32)

    def to_sparse_coo(self, sparse_dim: int = 2) -> SparseCooTensor:
        idx = np.stack([self._row_ids(), np.asarray(self._cols)])
        return SparseCooTensor(idx, self._values, self._shape, coalesced=True)

    def to_dense(self) -> Tensor:
        return self.to_sparse_coo().to_dense()

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


class SelectedRows:
    """Rows+values sparse gradient (ref ``phi/core/selected_rows.h:27``):
    the format a vocab-size embedding grad takes — only touched rows are
    materialized. ``height`` is the full first-dim size."""

    def __init__(self, rows, values: Tensor, height: int):
        self.rows = jnp.asarray(rows, jnp.int32)
        self.value = values if isinstance(values, Tensor) else Tensor(
            jnp.asarray(values))
        self.height = int(height)

    def to_dense(self) -> Tensor:
        from ..core.autograd import apply_op
        rows = self.rows
        h = self.height

        def fn(v):
            out = jnp.zeros((h,) + v.shape[1:], v.dtype)
            return out.at[rows].add(v)

        return apply_op("selected_rows_to_dense", fn, [self.value])

    def merge_add(self) -> "SelectedRows":
        """Merge duplicate rows (ref ``merge_selected_rows`` op)."""
        rows_np = np.asarray(self.rows)
        uniq, inv = np.unique(rows_np, return_inverse=True)
        seg = jnp.asarray(inv, jnp.int32)
        n = int(uniq.size)
        from ..core.autograd import apply_op
        merged = apply_op(
            "selected_rows_merge",
            lambda v: jax.ops.segment_sum(v, seg, num_segments=n),
            [self.value])
        return SelectedRows(uniq.astype(np.int32), merged, self.height)


# ---------------------------------------------------------------------------
# factories
# ---------------------------------------------------------------------------

def _values_with_grad_flag(values, dtype, stop_gradient: bool) -> Tensor:
    vals = _as_tensor(values, dtype)
    if not stop_gradient and vals.stop_gradient:
        if isinstance(values, Tensor):
            # don't mutate the caller's tensor: the factory's stop_gradient
            # applies to the sparse tensor's values view only
            vals = Tensor(vals._value, stop_gradient=False)
        else:
            vals.stop_gradient = False
    return vals


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient: bool = True) -> SparseCooTensor:
    """Ref ``paddle.incubate.sparse.sparse_coo_tensor``."""
    idx = np.asarray(indices)
    vals = _values_with_grad_flag(values, dtype, stop_gradient)
    if shape is None:
        if idx.size == 0:
            raise ValueError("shape= is required for an empty (nnz=0) "
                             "sparse tensor; it cannot be inferred")
        shape = tuple(int(m) + 1 for m in idx.max(axis=1)) + tuple(
            vals._value.shape[1:])
    return SparseCooTensor(idx, vals, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient: bool = True) -> SparseCsrTensor:
    vals = _values_with_grad_flag(values, dtype, stop_gradient)
    return SparseCsrTensor(crows, cols, vals, shape)


def to_sparse_coo(x: Tensor, sparse_dim: Optional[int] = None
                  ) -> SparseCooTensor:
    """Dense -> COO (ref ``Tensor.to_sparse_coo``). Nonzero structure is
    computed on host (dynamic nnz is data-dependent — not a jit-safe op,
    same as the reference's eager-only conversion)."""
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    sd = sparse_dim or arr.ndim
    flat = arr.reshape(arr.shape[:sd] + (-1,))
    mask = np.abs(flat).sum(axis=-1) != 0 if flat.ndim > sd else flat != 0
    idx = np.stack(np.nonzero(mask)).astype(np.int32)
    from ..core.autograd import apply_op
    jidx = tuple(jnp.asarray(i) for i in idx)
    vals = apply_op("dense_to_sparse_coo",
                    lambda v: v[jidx],
                    [x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))])
    return SparseCooTensor(idx, vals, arr.shape, coalesced=True)


def to_sparse_csr(x: Tensor) -> SparseCsrTensor:
    return to_sparse_coo(x, 2).to_sparse_csr()
