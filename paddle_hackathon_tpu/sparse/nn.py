"""Sparse layers (ref ``paddle.incubate.sparse.nn``: ReLU, Softmax ...)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.autograd import apply_op
from ..nn.layer import Layer
from . import ops as sops
from .tensors import SparseCsrTensor


class ReLU(Layer):
    def forward(self, x):
        return sops.relu(x)


class Softmax(Layer):
    """Row-wise softmax over a CSR matrix's stored entries (ref
    ``sparse/softmax_kernel``: only nonzeros participate)."""

    def __init__(self, axis: int = -1):
        super().__init__()
        if axis != -1:
            raise ValueError("sparse softmax supports axis=-1 (rows)")

    def forward(self, x: SparseCsrTensor):
        if not isinstance(x, SparseCsrTensor):
            raise TypeError("sparse Softmax expects a SparseCsrTensor")
        rows = jnp.asarray(x._row_ids(), jnp.int32)
        m = x._shape[0]

        def fn(vals):
            row_max = jax.ops.segment_max(vals, rows, num_segments=m)
            e = jnp.exp(vals - row_max[rows])
            denom = jax.ops.segment_sum(e, rows, num_segments=m)
            return e / denom[rows]

        return SparseCsrTensor(x._crows, x._cols,
                               apply_op("sparse_softmax", fn, [x._values]),
                               x._shape)
