"""paddle.dataset.movielens (ref ``python/paddle/dataset/movielens.py``).

ML-1M-shaped readers and metadata accessors over the deterministic
``paddle.text.Movielens`` corpus.
"""

from __future__ import annotations

import numpy as np

from . import common

__all__ = []

age_table = [1, 18, 25, 35, 45, 50, 56]  # ref movielens.py:43

_N_USERS, _N_MOVIES, _N_JOBS = 6040, 3952, 21
_CATEGORIES = [
    "Action", "Adventure", "Animation", "Children's", "Comedy", "Crime",
    "Documentary", "Drama", "Fantasy", "Film-Noir", "Horror", "Musical",
    "Mystery", "Romance", "Sci-Fi", "Thriller", "War", "Western"]


class MovieInfo:
    """ref ``movielens.py:46``."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        """[id, [category ids], [title word ids]]"""
        return [self.index,
                [_CATEGORIES.index(c) for c in self.categories],
                [_word_id(w) for w in self.title.split()]]

    def __str__(self):
        return (f"<MovieInfo id({self.index}), title({self.title}), "
                f"categories({self.categories})>")

    __repr__ = __str__


class UserInfo:
    """ref ``movielens.py:73``."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == 'M'
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        """[id, 0/1 gender, age bucket, job id]"""
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]

    def __str__(self):
        return (f"<UserInfo id({self.index}), gender({'M' if self.is_male else 'F'}), "
                f"age({age_table[self.age]}), job({self.job_id})>")

    __repr__ = __str__


def _word_id(w):
    """Stable title-word id: Python's hash() is salted per process
    (PYTHONHASHSEED), so use md5 — same id across runs and worker procs."""
    import hashlib
    return int(hashlib.md5(w.encode()).hexdigest()[:8], 16) % 5000


_META = None


def __initialize_meta_info__():
    """ref ``movielens.py:105`` — build deterministic movie/user tables."""
    global _META
    if _META is None:
        r = common.rng("movielens-meta")
        movies, users = {}, {}
        for mid in range(1, _N_MOVIES + 1):
            cats = [_CATEGORIES[i] for i in sorted(
                set(r.randint(0, len(_CATEGORIES), r.randint(1, 4))))]
            title = " ".join(f"t{w}" for w in r.randint(0, 5000, 3))
            movies[mid] = MovieInfo(mid, cats, title)
        for uid in range(1, _N_USERS + 1):
            users[uid] = UserInfo(
                uid, 'M' if r.rand() < 0.5 else 'F',
                age_table[r.randint(0, len(age_table))],
                r.randint(0, _N_JOBS))
        _META = (movies, users)
    return _META


def __reader__(rand_seed=0, test_ratio=0.1, is_test=False):
    from ..text.datasets import Movielens
    ds = Movielens(mode="test" if is_test else "train",
                   test_ratio=test_ratio, rand_seed=rand_seed)
    movies, users = __initialize_meta_info__()
    for (user, gender, age, job, movie, cats, title, rating) in ds.items:
        usr = users[int(user)]
        mov = movies[int(movie)]
        yield usr.value() + mov.value() + [[float(rating)]]


def __reader_creator__(**kwargs):
    return lambda: __reader__(**kwargs)


# ref movielens.py:179-180: train() returns a reader; train()() iterates
import functools  # noqa: E402

train = functools.partial(__reader_creator__, is_test=False)
test = functools.partial(__reader_creator__, is_test=True)


def get_movie_title_dict():
    """ref ``movielens.py:188``."""
    movies, _ = __initialize_meta_info__()
    words = set()
    for m in movies.values():
        words.update(m.title.split())
    return {w: i for i, w in enumerate(sorted(words))}


def max_movie_id():
    """ref ``movielens.py:208``."""
    movies, _ = __initialize_meta_info__()
    return max(movies.keys())


def max_user_id():
    """ref ``movielens.py:221``."""
    _, users = __initialize_meta_info__()
    return max(users.keys())


def max_job_id():
    """ref ``movielens.py:241``."""
    _, users = __initialize_meta_info__()
    return max(u.job_id for u in users.values())


def movie_categories():
    """ref ``movielens.py:255``."""
    return {c: i for i, c in enumerate(_CATEGORIES)}


def user_info():
    """ref ``movielens.py:268``."""
    _, users = __initialize_meta_info__()
    return users


def movie_info():
    """ref ``movielens.py:281``."""
    movies, _ = __initialize_meta_info__()
    return movies


def unittest():
    """ref ``movielens.py:289``."""
    for train_count, _ in enumerate(train()()):
        pass
    for test_count, _ in enumerate(test()()):
        pass
    print(train_count, test_count)


def fetch():
    """ref ``movielens.py:303``."""
    __initialize_meta_info__()
