"""Dataset cache/download helpers (ref
``python/paddle/dataset/common.py:41-231``).

This build runs with zero network egress, so ``download`` validates/copies
local files instead of fetching URLs; every built-in dataset falls back to
deterministic synthetic samples with the reference's shapes and dtypes when
the real archives are absent (same policy as ``paddle.text`` datasets).
"""

from __future__ import annotations

import glob
import hashlib
import os
import pickle

import numpy as np

__all__ = []

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_DATA_HOME", "~/.cache/paddle/dataset"))


def must_mkdirs(path):
    os.makedirs(path, exist_ok=True)


must_mkdirs(DATA_HOME)


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """Resolve the dataset file under DATA_HOME (ref ``common.py:62``).

    Zero-egress: if the file already exists locally (placed by the user) it
    is returned, with an md5 warning when it mismatches; otherwise a
    FileNotFoundError explains how to provide it.
    """
    dirname = os.path.join(DATA_HOME, module_name)
    must_mkdirs(dirname)
    filename = os.path.join(
        dirname, url.split('/')[-1] if save_name is None else save_name)
    if os.path.exists(filename):
        if md5sum and md5file(filename) != md5sum:
            import warnings
            warnings.warn(f"md5 of {filename} does not match the expected "
                          f"{md5sum}; using the local file anyway")
        return filename
    raise FileNotFoundError(
        f"{filename} not found and this build has no network access; "
        f"download {url} manually to {dirname}, or use the dataset's "
        "synthetic fallback readers")


def fetch_all():
    """ref ``common.py:119`` — eagerly fetch every dataset; with no network
    this just ensures the cache directories exist."""
    for name in ("mnist", "cifar", "uci_housing", "imdb", "imikolov",
                 "movielens", "conll05", "wmt14", "wmt16", "flowers",
                 "voc2012"):
        must_mkdirs(os.path.join(DATA_HOME, name))


def split(reader, line_count, suffix="%05d.pickle", dumper=pickle.dump):
    """Split reader samples into pickled chunk files of ``line_count``
    (ref ``common.py:129``)."""
    indx_f = 0
    lines = []
    for i, d in enumerate(reader()):
        lines.append(d)
        if i >= line_count and i % line_count == 0:
            with open(suffix % indx_f, "wb") as f:
                dumper(lines, f)
                lines = []
                indx_f += 1
    if lines:
        with open(suffix % indx_f, "wb") as f:
            dumper(lines, f)


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=pickle.load):
    """Read this trainer's shard of chunk files (ref ``common.py:167``)."""

    def reader():
        flist = sorted(glob.glob(files_pattern))
        my_file_list = []
        for idx, fn in enumerate(flist):
            if idx % trainer_count == trainer_id:
                print("append file: %s" % fn)
                my_file_list.append(fn)
        for fn in my_file_list:
            with open(fn, "rb") as f:
                lines = loader(f)
                for line in lines:
                    yield line

    return reader


def _check_exists_and_download(path, url, md5, module_name, download_flag=True):
    if path and os.path.exists(path):
        return path
    if download_flag:
        return download(url, module_name, md5)
    raise ValueError(f"{path} not exists and auto download disabled")


def rng(*key_parts) -> np.random.RandomState:
    """Deterministic per-(dataset, split) RNG for synthetic fallbacks."""
    seed = int(hashlib.md5(repr(key_parts).encode()).hexdigest()[:8], 16)
    return np.random.RandomState(seed)
