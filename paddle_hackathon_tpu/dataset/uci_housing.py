"""paddle.dataset.uci_housing (ref ``python/paddle/dataset/uci_housing.py``).

``train()``/``test()`` yield ``(features_f32[13], price_f32[1])`` with the
reference's 404/102 split, backed by the same deterministic synthetic data
as ``paddle.text.UCIHousing``.
"""

from __future__ import annotations

import numpy as np

__all__ = []

feature_names = ['CRIM', 'ZN', 'INDUS', 'CHAS', 'NOX', 'RM', 'AGE', 'DIS',
                 'RAD', 'TAX', 'PTRATIO', 'B', 'LSTAT']

UCI_TRAIN_DATA = None
UCI_TEST_DATA = None


def feature_range(maximums, minimums):
    """ref ``uci_housing.py:48`` — plotting helper; no-op without matplotlib."""
    try:
        import matplotlib  # noqa: F401
    except ImportError:
        return


def load_data(filename=None, feature_num=14, ratio=0.8):
    """ref ``uci_housing.py:69`` — populate the train/test globals."""
    global UCI_TRAIN_DATA, UCI_TEST_DATA
    if UCI_TRAIN_DATA is not None and UCI_TEST_DATA is not None:
        return
    from ..text.datasets import UCIHousing
    tr = UCIHousing(mode="train")
    te = UCIHousing(mode="test")
    UCI_TRAIN_DATA = tr.data
    UCI_TEST_DATA = te.data


def _reader_creator(split):
    def reader():
        load_data()
        data = UCI_TRAIN_DATA if split == "train" else UCI_TEST_DATA
        for row in data:
            yield (np.asarray(row[:-1], np.float32),
                   np.asarray(row[-1:], np.float32))

    return reader


def train():
    """ref ``uci_housing.py:92``."""
    return _reader_creator("train")


def test():
    """ref ``uci_housing.py:117``."""
    return _reader_creator("test")


def predict_reader():
    """ref ``uci_housing.py:155`` — first 100 test feature rows."""
    load_data()
    return (np.asarray(d[:-1], np.float32) for d in UCI_TEST_DATA[:100])


def fluid_model():
    """ref ``uci_housing.py:137`` — pretrained demo model is not bundled."""
    raise NotImplementedError(
        "the pretrained fit_a_line demo model is not bundled in this build")


def fetch():
    """ref ``uci_housing.py:172``."""
    load_data()
