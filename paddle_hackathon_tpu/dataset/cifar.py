"""paddle.dataset.cifar (ref ``python/paddle/dataset/cifar.py:49-170``).

Readers yield ``(image_f32[3072] in [0,1], int label)``. Real pickle
archives under DATA_HOME are used when present, else a deterministic
synthetic fallback.
"""

from __future__ import annotations

import itertools
import os

import numpy as np

from . import common

__all__ = []

_SYNTH = {"train": 1024, "test": 256}


def reader_creator(filename, sub_name, cycle=False):
    """ref ``cifar.py:49`` — stream one split from the pickle archive."""
    from ..vision.datasets import Cifar10, Cifar100
    # the dataset family is encoded in the archive filename (the reference
    # passes cifar-100-python.tar.gz / cifar-10-python.tar.gz); sub_name
    # only selects the split — cifar100 uses 'train'/'test', cifar10 uses
    # 'data_batch_N'/'test_batch'
    cls = Cifar100 if "100" in os.path.basename(str(filename)) else Cifar10
    mode = "train" if "train" in sub_name or "data_batch" in sub_name \
        else "test"

    def reader():
        ds = cls(data_file=filename, mode=mode)
        it = itertools.cycle(range(len(ds))) if cycle else range(len(ds))
        for i in it:
            img, label = ds[i]
            yield (np.transpose(img, (2, 0, 1)).reshape(-1).astype(
                np.float32) / 255.0, int(label))

    return reader


def _synthetic(mode, n_classes, cycle=False):
    def reader():
        r = common.rng("cifar", mode, n_classes)
        n = _SYNTH[mode]
        imgs = r.rand(n, 3072).astype(np.float32)
        labels = r.randint(0, n_classes, n)
        idx = itertools.cycle(range(n)) if cycle else range(n)
        for i in idx:
            yield imgs[i], int(labels[i])

    return reader


def _make(archive, sub_name, mode, n_classes, cycle=False):
    path = os.path.join(common.DATA_HOME, "cifar", archive)
    if os.path.exists(path):
        return reader_creator(path, sub_name, cycle)
    return _synthetic(mode, n_classes, cycle)


def train100():
    """ref ``cifar.py:81``."""
    return _make("cifar-100-python.tar.gz", "train", "train", 100)


def test100():
    """ref ``cifar.py:101``."""
    return _make("cifar-100-python.tar.gz", "test", "test", 100)


def train10(cycle=False):
    """ref ``cifar.py:121``."""
    return _make("cifar-10-python.tar.gz", "data_batch", "train", 10, cycle)


def test10(cycle=False):
    """ref ``cifar.py:144``."""
    return _make("cifar-10-python.tar.gz", "test_batch", "test", 10, cycle)


def fetch():
    """ref ``cifar.py:167``."""
    common.must_mkdirs(os.path.join(common.DATA_HOME, "cifar"))
