"""paddle.dataset.wmt16 (ref ``python/paddle/dataset/wmt16.py``).

ACL-WMT16 en-de readers: ``(src_ids, trg_ids, trg_ids_next)``; dicts keyed
by language (``wmt16.py:104-338``).
"""

from __future__ import annotations

__all__ = []

TOTAL_EN_WORDS = 11250
TOTAL_DE_WORDS = 19220

START_MARK = "<s>"
END_MARK = "<e>"
UNK_MARK = "<unk>"


def __get_dict_size(src_dict_size, trg_dict_size, src_lang):
    """ref ``wmt16.py:96``."""
    src_dict_size = min(src_dict_size, (TOTAL_EN_WORDS if src_lang == "en"
                                        else TOTAL_DE_WORDS))
    trg_dict_size = min(trg_dict_size, (TOTAL_DE_WORDS if src_lang == "en"
                                        else TOTAL_EN_WORDS))
    return src_dict_size, trg_dict_size


def _dataset(mode, src_dict_size, trg_dict_size, src_lang):
    from ..text.datasets import WMT16
    return WMT16(mode=mode, src_dict_size=src_dict_size,
                 trg_dict_size=trg_dict_size, lang=src_lang)


def reader_creator(tar_file, file_name, src_dict_size, trg_dict_size,
                   src_lang):
    """ref ``wmt16.py:104``."""
    mode = ("test" if "test" in str(file_name)
            else "val" if "val" in str(file_name) else "train")

    def reader():
        ds = _dataset(mode, src_dict_size, trg_dict_size, src_lang)
        for src, trg_in, trg_next in ds.pairs:
            yield ([int(x) for x in src], [int(x) for x in trg_in],
                   [int(x) for x in trg_next])

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en"):
    """ref ``wmt16.py:148``."""
    if src_lang not in ["en", "de"]:
        raise ValueError("An error language type. Only support: "
                         "en (for English); de(for Germany).")
    src_dict_size, trg_dict_size = __get_dict_size(src_dict_size,
                                                   trg_dict_size, src_lang)
    return reader_creator(None, "wmt16/train", src_dict_size, trg_dict_size,
                          src_lang)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    """ref ``wmt16.py:201``."""
    if src_lang not in ["en", "de"]:
        raise ValueError("An error language type. Only support: "
                         "en (for English); de(for Germany).")
    src_dict_size, trg_dict_size = __get_dict_size(src_dict_size,
                                                   trg_dict_size, src_lang)
    return reader_creator(None, "wmt16/test", src_dict_size, trg_dict_size,
                          src_lang)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    """ref ``wmt16.py:254``."""
    if src_lang not in ["en", "de"]:
        raise ValueError("An error language type. Only support: "
                         "en (for English); de(for Germany).")
    src_dict_size, trg_dict_size = __get_dict_size(src_dict_size,
                                                   trg_dict_size, src_lang)
    return reader_creator(None, "wmt16/val", src_dict_size, trg_dict_size,
                          src_lang)


def get_dict(lang, dict_size, reverse=False):
    """ref ``wmt16.py:305`` — the dict of one language."""
    dict_size = min(dict_size, (TOTAL_EN_WORDS if lang == "en"
                                else TOTAL_DE_WORDS))
    ds = _dataset("train", dict_size, dict_size, "en")
    src, trg = ds.get_dict(reverse=reverse)
    return src if lang == "en" else trg


def fetch():
    """ref ``wmt16.py:340``."""
