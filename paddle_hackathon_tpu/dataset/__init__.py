"""paddle.dataset — legacy generator-reader dataset package
(ref ``python/paddle/dataset/__init__.py``)."""

from . import common  # noqa: F401
from . import mnist  # noqa: F401
from . import imikolov  # noqa: F401
from . import imdb  # noqa: F401
from . import cifar  # noqa: F401
from . import movielens  # noqa: F401
from . import conll05  # noqa: F401
from . import uci_housing  # noqa: F401
from . import wmt14  # noqa: F401
from . import wmt16  # noqa: F401
from . import flowers  # noqa: F401
from . import voc2012  # noqa: F401
from . import image  # noqa: F401

__all__ = []
