"""paddle.dataset.imikolov (ref ``python/paddle/dataset/imikolov.py``).

PTB-style n-gram / sequence readers over the deterministic
``paddle.text.Imikolov`` corpus.
"""

from __future__ import annotations

__all__ = []


class DataType:
    """ref ``imikolov.py:37``."""
    NGRAM = 1
    SEQ = 2


def word_count(f, word_freq=None):
    """ref ``imikolov.py:42`` — count words of an open token-line file."""
    if word_freq is None:
        word_freq = {}
    for line in f:
        for w in line.strip().split():
            word_freq[w] = word_freq.get(w, 0) + 1
        word_freq['<s>'] = word_freq.get('<s>', 0) + 1
        word_freq['<e>'] = word_freq.get('<e>', 0) + 1
    return word_freq


def build_dict(min_word_freq=50):
    """ref ``imikolov.py:55`` — word -> id with '<unk>' mapped last."""
    from ..text.datasets import Imikolov
    ds = Imikolov(mode="train", data_type="SEQ")
    d = dict(ds.word_idx)
    d.setdefault('<unk>', len(d))
    return d


def reader_creator(filename, word_idx, n, data_type):
    """ref ``imikolov.py:85``."""
    mode = "test" if "valid" in str(filename) or "test" in str(filename) \
        else "train"
    return _reader(mode, word_idx, n, data_type)


def _reader(mode, word_idx, n, data_type):
    from ..text.datasets import Imikolov

    def reader():
        # The reference maps every word — boundary markers included —
        # through the *caller's* word_idx with '<unk>' as fallback
        # (imikolov.py:98-107: ``[word_idx.get(w, UNK) for w in l]``).
        # Corpus ids are translated corpus-id -> word -> caller-id so a
        # custom dict (different min_word_freq, own boundary ids) works.
        if data_type == DataType.NGRAM or str(data_type).upper() == "NGRAM":
            ds = Imikolov(mode=mode, data_type="NGRAM", window_size=n)
        else:
            ds = Imikolov(mode=mode, data_type="SEQ")
        rev = {v: k for k, v in ds.word_idx.items()}
        if word_idx and dict(word_idx) != dict(ds.word_idx):
            unk = word_idx.get('<unk>', len(word_idx))

            def xl(i):
                return int(word_idx.get(rev[int(i)], unk))
        else:  # caller dict is the corpus dict (the build_dict() case)
            def xl(i):
                return int(i)
        if data_type == DataType.NGRAM or str(data_type).upper() == "NGRAM":
            for gram in ds.data:
                yield tuple(xl(w) for w in gram)
        else:
            lookup = word_idx if word_idx else ds.word_idx
            unk = lookup.get('<unk>', len(lookup))
            s_id = lookup.get('<s>', unk)
            e_id = lookup.get('<e>', unk)
            for sent in ds.data:
                ids = [xl(w) for w in sent]
                # <s> sentence <e> input/target split (ref imikolov.py:103)
                src = [s_id] + ids
                trg = ids + [e_id]
                yield src, trg

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    """ref ``imikolov.py:121``."""
    return _reader("train", word_idx, n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    """ref ``imikolov.py:146``."""
    return _reader("test", word_idx, n, data_type)


def fetch():
    """ref ``imikolov.py:171``."""
