"""paddle.dataset.imikolov (ref ``python/paddle/dataset/imikolov.py``).

PTB-style n-gram / sequence readers over the deterministic
``paddle.text.Imikolov`` corpus.
"""

from __future__ import annotations

__all__ = []


class DataType:
    """ref ``imikolov.py:37``."""
    NGRAM = 1
    SEQ = 2


def word_count(f, word_freq=None):
    """ref ``imikolov.py:42`` — count words of an open token-line file."""
    if word_freq is None:
        word_freq = {}
    for line in f:
        for w in line.strip().split():
            word_freq[w] = word_freq.get(w, 0) + 1
        word_freq['<s>'] = word_freq.get('<s>', 0) + 1
        word_freq['<e>'] = word_freq.get('<e>', 0) + 1
    return word_freq


def build_dict(min_word_freq=50):
    """ref ``imikolov.py:55`` — word -> id with '<unk>' mapped last."""
    from ..text.datasets import Imikolov
    ds = Imikolov(mode="train", data_type="SEQ")
    d = dict(ds.word_idx)
    d.setdefault('<unk>', len(d))
    return d


def reader_creator(filename, word_idx, n, data_type):
    """ref ``imikolov.py:85``."""
    mode = "test" if "valid" in str(filename) or "test" in str(filename) \
        else "train"
    return _reader(mode, word_idx, n, data_type)


def _reader(mode, word_idx, n, data_type):
    from ..text.datasets import Imikolov

    def reader():
        if data_type == DataType.NGRAM or str(data_type).upper() == "NGRAM":
            ds = Imikolov(mode=mode, data_type="NGRAM", window_size=n)
            for gram in ds.data:
                yield tuple(int(w) for w in gram)
        else:
            ds = Imikolov(mode=mode, data_type="SEQ")
            for sent in ds.data:
                ids = [int(w) for w in sent]
                # <s> sentence <e> input/target split (ref imikolov.py:103)
                src = [0] + ids
                trg = ids + [1]
                yield src, trg

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    """ref ``imikolov.py:121``."""
    return _reader("train", word_idx, n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    """ref ``imikolov.py:146``."""
    return _reader("test", word_idx, n, data_type)


def fetch():
    """ref ``imikolov.py:171``."""
