"""paddle.dataset.wmt14 (ref ``python/paddle/dataset/wmt14.py``).

Readers yield ``(src_ids, trg_ids, trg_ids_next)`` with <s>=0, <e>=1,
<unk>=2 (``wmt14.py:79-118``).
"""

from __future__ import annotations

__all__ = []

START = "<s>"
END = "<e>"
UNK = "<unk>"
UNK_IDX = 2


def _dataset(mode, dict_size):
    from ..text.datasets import WMT14
    return WMT14(mode=mode, dict_size=dict_size)


def reader_creator(tar_file, file_name, dict_size):
    """ref ``wmt14.py:79``."""
    mode = "test" if "test" in str(file_name) else "train"

    def reader():
        ds = _dataset(mode, dict_size)
        for src, trg_in, trg_next in ds.pairs:
            yield ([int(x) for x in src], [int(x) for x in trg_in],
                   [int(x) for x in trg_next])

    return reader


def train(dict_size):
    """ref ``wmt14.py:121``."""
    return reader_creator(None, "train/train", dict_size)


def test(dict_size):
    """ref ``wmt14.py:142``."""
    return reader_creator(None, "test/test", dict_size)


def gen(dict_size):
    """ref ``wmt14.py:163``."""
    return reader_creator(None, "gen/gen", dict_size)


def get_dict(dict_size, reverse=True):
    """ref ``wmt14.py:174`` — (src dict, trg dict), id->word if reverse."""
    ds = _dataset("train", dict_size)
    return ds.get_dict(reverse=reverse)


def fetch():
    """ref ``wmt14.py:190``."""
