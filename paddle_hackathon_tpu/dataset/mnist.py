"""paddle.dataset.mnist (ref ``python/paddle/dataset/mnist.py:43-146``).

``train()``/``test()`` yield ``(image, label)`` with image a float32[784]
normalized to (-1, 1) and label an int. Real IDX archives are used when
present under DATA_HOME; otherwise a deterministic synthetic fallback with
the reference's split sizes (60k/10k) and value ranges.
"""

from __future__ import annotations

import numpy as np

from . import common

__all__ = []

TRAIN_SIZE, TEST_SIZE = 60000, 10000
_SYNTH_SIZE = {"train": 1024, "test": 256}  # fallback keeps smoke runs fast


def _idx_paths(mode):
    import os
    stem = "train" if mode == "train" else "t10k"
    base = os.path.join(common.DATA_HOME, "mnist")
    return (os.path.join(base, f"{stem}-images-idx3-ubyte.gz"),
            os.path.join(base, f"{stem}-labels-idx1-ubyte.gz"))


def reader_creator(image_filename, label_filename, buffer_size):
    """ref ``mnist.py:43`` — stream (normalized image row, int label)."""
    from ..vision.datasets import MNIST

    def reader():
        ds = MNIST(image_path=image_filename, label_path=label_filename)
        for i in range(len(ds)):
            img, label = ds[i]
            img = img.reshape(-1).astype(np.float32) / 127.5 - 1.0
            yield img, int(label)

    return reader


def _synthetic_reader(mode):
    def reader():
        r = common.rng("mnist", mode)
        n = _SYNTH_SIZE[mode]
        imgs = (r.rand(n, 784).astype(np.float32) * 2.0 - 1.0)
        labels = r.randint(0, 10, n)
        for i in range(n):
            yield imgs[i], int(labels[i])

    return reader


def _reader(mode):
    import os
    images, labels = _idx_paths(mode)
    if os.path.exists(images) and os.path.exists(labels):
        return reader_creator(images, labels, 100)
    return _synthetic_reader(mode)


def train():
    """ref ``mnist.py:100``."""
    return _reader("train")


def test():
    """ref ``mnist.py:122``."""
    return _reader("test")


def fetch():
    """ref ``mnist.py:143``."""
    common.must_mkdirs(common.DATA_HOME + "/mnist")
