"""paddle.dataset.flowers (ref ``python/paddle/dataset/flowers.py``).

102-category flower classification; readers yield
``(chw_float32_image, int label)`` after the reference's mapper pipeline.
Synthetic fallback images are used when the real archives are absent.
"""

from __future__ import annotations

import functools
import itertools

import numpy as np

from . import common
from .image import simple_transform
from ..reader import xmap_readers

__all__ = []

_N_CLASSES = 102
_SYNTH = {"train": 256, "test": 64, "valid": 64}


def default_mapper(is_train, sample):
    """ref ``flowers.py:70`` — decode + simple_transform(256, 224)."""
    img, label = sample
    if isinstance(img, bytes):
        from .image import load_image_bytes
        img = load_image_bytes(img)
    img = simple_transform(np.asarray(img), 256, 224, is_train)
    return img.flatten().astype('float32'), label


train_mapper = functools.partial(default_mapper, True)
test_mapper = functools.partial(default_mapper, False)


def _synthetic_raw(mode):
    def reader():
        r = common.rng("flowers", mode)
        for i in range(_SYNTH[mode]):
            img = (r.rand(256, 256, 3) * 255).astype(np.uint8)
            yield img, int(r.randint(0, _N_CLASSES))

    return reader


def reader_creator(data_file, label_file, setid_file, dataset_name, mapper,
                   buffered_size=1024, use_xmap=True, cycle=False):
    """ref ``flowers.py:88``."""
    mode = {"tstid": "train", "trnid": "test",
            "valid": "valid"}.get(dataset_name, "train")
    base = _synthetic_raw(mode)

    def maybe_cycle(r):
        if not cycle:
            return r

        def cycled():
            while True:
                for s in r():
                    yield s
        return cycled

    raw = maybe_cycle(base)
    if use_xmap:
        return xmap_readers(mapper, raw, min(4, 8), buffered_size, order=False)

    def mapped():
        for s in raw():
            yield mapper(s)

    return mapped


def train(mapper=train_mapper, buffered_size=1024, use_xmap=True,
          cycle=False):
    """ref ``flowers.py:152`` (the reference trains on the 'tstid' split)."""
    return reader_creator(None, None, None, "tstid", mapper, buffered_size,
                          use_xmap, cycle)


def test(mapper=test_mapper, buffered_size=1024, use_xmap=True, cycle=False):
    """ref ``flowers.py:185``."""
    return reader_creator(None, None, None, "trnid", mapper, buffered_size,
                          use_xmap, cycle)


def valid(mapper=test_mapper, buffered_size=1024, use_xmap=True):
    """ref ``flowers.py:218``."""
    return reader_creator(None, None, None, "valid", mapper, buffered_size,
                          use_xmap)


def fetch():
    """ref ``flowers.py:240``."""
    common.must_mkdirs(common.DATA_HOME + "/flowers")
