"""paddle.dataset.conll05 (ref ``python/paddle/dataset/conll05.py``).

Semantic-role-labeling readers; items are the 9-slot tuple
(word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_id, mark, labels)
the reference's ``reader_creator`` emits (``conll05.py:151-209``).
"""

from __future__ import annotations

import numpy as np

from . import common

__all__ = []

UNK_IDX = 0


def load_label_dict(filename):
    """ref ``conll05.py:49``."""
    d = {}
    tag_dict = set()
    with open(filename) as f:
        for line in f:
            line = line.strip()
            if line.startswith("B-"):
                tag_dict.add(line[2:])
            elif line.startswith("I-"):
                tag_dict.add(line[2:])
        index = 1
        for tag in sorted(tag_dict):
            d["B-" + tag] = index
            index += 1
            d["I-" + tag] = index
            index += 1
        d["O"] = 0
    return d


def load_dict(filename):
    """ref ``conll05.py:69``."""
    d = {}
    with open(filename) as f:
        for i, line in enumerate(f):
            d[line.strip()] = i
    return d


def _dataset(mode="test"):
    from ..text.datasets import Conll05st
    return Conll05st(mode=mode)


def corpus_reader(data_path=None, words_name=None, props_name=None):
    """ref ``conll05.py:77`` — yields (sentences, predicate, labels)."""

    def reader():
        ds = _dataset()
        id_to_word = {v: k for k, v in ds.word_dict.items()}
        id_to_verb = {v: k for k, v in ds.predicate_dict.items()}
        id_to_label = {v: k for k, v in ds.label_dict.items()}
        for words, pred, mark, labels in ds.examples:
            sentences = [id_to_word[int(w)] for w in words]
            predicate = id_to_verb[int(pred)]
            lbls = [id_to_label[int(l)] for l in labels]
            yield sentences, predicate, lbls

    return reader


def reader_creator(corpus_reader, word_dict=None, predicate_dict=None,
                   label_dict=None):
    """ref ``conll05.py:151`` — to the 9-slot model input tuple."""

    def reader():
        ds = _dataset()
        for words, pred, mark, labels in ds.examples:
            w = [int(x) for x in words]
            n = len(w)

            def ctx(offset):
                return [w[max(0, min(n - 1, i + offset))] for i in range(n)]

            yield (w, ctx(-2), ctx(-1), ctx(0), ctx(1), ctx(2),
                   [int(pred)] * n, [int(m) for m in mark],
                   [int(l) for l in labels])

    return reader


def get_dict():
    """ref ``conll05.py:212`` — (word_dict, verb_dict, label_dict)."""
    ds = _dataset()
    return ds.word_dict, ds.predicate_dict, ds.label_dict


def get_embedding():
    """ref ``conll05.py:230`` — pretrained word embeddings; deterministic
    32-dim synthetic matrix here (the reference ships emb data)."""
    ds = _dataset()
    r = common.rng("conll05-emb")
    return r.randn(len(ds.word_dict), 32).astype(np.float32)


def test():
    """ref ``conll05.py:242``."""
    word_dict, verb_dict, label_dict = get_dict()
    return reader_creator(corpus_reader(), word_dict, verb_dict, label_dict)


def fetch():
    """ref ``conll05.py:267``."""
