"""paddle.dataset.voc2012 (ref ``python/paddle/dataset/voc2012.py``).

Segmentation readers yield ``(image_chw_uint8, label_map_uint8)``;
synthetic fallback when the VOC archive is absent.
"""

from __future__ import annotations

import numpy as np

from . import common

__all__ = []

_SYNTH = {"train": 64, "test": 32, "val": 32}
_N_CLASSES = 21


def reader_creator(filename, sub_name):
    """ref ``voc2012.py:44``."""
    mode = {"trainval": "train", "train": "train", "val": "val",
            "test": "test"}.get(sub_name, "train")

    def reader():
        r = common.rng("voc2012", mode)
        for i in range(_SYNTH[mode]):
            h, w = int(r.randint(120, 260)), int(r.randint(120, 260))
            img = (r.rand(3, h, w) * 255).astype(np.uint8)
            label = r.randint(0, _N_CLASSES, (h, w)).astype(np.uint8)
            yield img, label

    return reader


def train():
    """ref ``voc2012.py:74``."""
    return reader_creator(None, "trainval")


def test():
    """ref ``voc2012.py:86``."""
    return reader_creator(None, "train")


def val():
    """ref ``voc2012.py:98``."""
    return reader_creator(None, "val")
