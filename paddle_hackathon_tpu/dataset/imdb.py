"""paddle.dataset.imdb (ref ``python/paddle/dataset/imdb.py:40-169``).

Readers yield ``(word_id_list, 0/1 label)``; vocabulary from
``word_dict()``. Backed by the deterministic ``paddle.text.Imdb`` corpus.
"""

from __future__ import annotations

import numpy as np

__all__ = []


def _dataset(mode):
    from ..text.datasets import Imdb
    return Imdb(mode=mode)


def tokenize(pattern):
    """ref ``imdb.py:40`` — yield token lists of the docs matching the
    aclImdb tar pattern; 'train' or 'test' and 'pos'/'neg' are inferred."""
    mode = "test" if "test" in str(pattern) else "train"
    want = None
    if "pos" in str(pattern):
        want = 1
    elif "neg" in str(pattern):
        want = 0
    ds = _dataset(mode)
    idx_to_word = {v: k for k, v in ds.word_idx.items()}
    for doc, label in zip(ds.docs, ds.labels):
        if want is not None and int(label) != want:
            continue
        yield [idx_to_word[int(w)] for w in doc]


def build_dict(pattern, cutoff):
    """ref ``imdb.py:60`` — word -> id, '<unk>' last."""
    mode = "test" if "test" in str(pattern) else "train"
    return _dataset(mode).word_idx


def reader_creator(pos_pattern, neg_pattern, word_idx):
    """ref ``imdb.py:85``."""
    mode = "test" if "test" in str(pos_pattern) else "train"

    def reader():
        ds = _dataset(mode)
        for doc, label in zip(ds.docs, ds.labels):
            yield [int(w) for w in doc], int(label)

    return reader


def train(word_idx):
    """ref ``imdb.py:108`` — yields (ids, 0/1)."""
    return reader_creator("train/pos", "train/neg", word_idx)


def test(word_idx):
    """ref ``imdb.py:129``."""
    return reader_creator("test/pos", "test/neg", word_idx)


def word_dict():
    """ref ``imdb.py:150``."""
    return _dataset("train").word_idx


def fetch():
    """ref ``imdb.py:166``."""
