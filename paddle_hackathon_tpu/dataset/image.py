"""paddle.dataset.image (ref ``python/paddle/dataset/image.py:72-428``).

Image manipulation helpers. The reference shells out to cv2; here the
array-path helpers (crop/flip/chw/resize) are pure numpy so they always
work, and the file/bytes decoders use cv2 or PIL when available.
"""

from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

__all__ = []


def _decoder():
    try:
        import cv2
        return "cv2", cv2
    except ImportError:
        pass
    try:
        import PIL.Image
        return "pil", PIL.Image
    except ImportError:
        return None, None


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    """ref ``image.py:84`` — pickle batches of (jpeg bytes, label)."""
    batch_dir = data_file + "_batch"
    out_path = "%s/%s_%s" % (batch_dir, dataset_name, os.getpid())
    meta_file = "%s/%s_%s.txt" % (batch_dir, dataset_name, os.getpid())

    if os.path.exists(out_path):
        return meta_file
    os.makedirs(out_path, exist_ok=True)
    tf = tarfile.open(data_file)
    mems = tf.getmembers()
    data, labels = [], []
    file_id = 0
    for mem in mems:
        if mem.name in img2label:
            data.append(tf.extractfile(mem).read())
            labels.append(img2label[mem.name])
            if len(data) == num_per_batch:
                output = {'label': labels, 'data': data}
                with open(f"{out_path}/batch_{file_id}", 'wb') as f:
                    pickle.dump(output, f, protocol=2)
                file_id += 1
                data, labels = [], []
    if data:
        output = {'label': labels, 'data': data}
        with open(f"{out_path}/batch_{file_id}", 'wb') as f:
            pickle.dump(output, f, protocol=2)
    with open(meta_file, 'a') as meta:
        for file in os.listdir(out_path):
            meta.write(os.path.abspath(f"{out_path}/{file}") + "\n")
    return meta_file


def load_image_bytes(bytes, is_color=True):  # noqa: A002
    """ref ``image.py:145``."""
    kind, mod = _decoder()
    if kind == "cv2":
        import cv2
        flag = 1 if is_color else 0
        file_bytes = np.asarray(bytearray(bytes), dtype=np.uint8)
        return cv2.imdecode(file_bytes, flag)
    if kind == "pil":
        import io
        img = mod.open(io.BytesIO(bytes))
        img = img.convert("RGB" if is_color else "L")
        return np.asarray(img)
    raise ImportError("decoding image bytes needs cv2 or PIL; neither is "
                      "installed")


def load_image(file, is_color=True):  # noqa: A002
    """ref ``image.py:171``."""
    with open(file, "rb") as f:
        return load_image_bytes(f.read(), is_color)


def _resize_bilinear(im, h_out, w_out):
    """Pure-numpy bilinear resize (HWC or HW)."""
    im = np.asarray(im)
    h_in, w_in = im.shape[:2]
    ys = np.linspace(0, h_in - 1, h_out)
    xs = np.linspace(0, w_in - 1, w_out)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h_in - 1)
    x1 = np.minimum(x0 + 1, w_in - 1)
    wy = (ys - y0)[:, None]
    wx = (xs - x0)[None, :]
    if im.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    p00 = im[y0][:, x0]
    p01 = im[y0][:, x1]
    p10 = im[y1][:, x0]
    p11 = im[y1][:, x1]
    out = (p00 * (1 - wy) * (1 - wx) + p01 * (1 - wy) * wx +
           p10 * wy * (1 - wx) + p11 * wy * wx)
    return out.astype(im.dtype)


def resize_short(im, size):
    """ref ``image.py:201`` — resize so the shorter edge equals ``size``."""
    h, w = im.shape[:2]
    h_new, w_new = size, size
    if h > w:
        h_new = size * h // w
    else:
        w_new = size * w // h
    return _resize_bilinear(im, h_new, w_new)


def to_chw(im, order=(2, 0, 1)):
    """ref ``image.py:229``."""
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    """ref ``image.py:253``."""
    h, w = im.shape[:2]
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    h_end, w_end = h_start + size, w_start + size
    if is_color:
        return im[h_start:h_end, w_start:w_end, :]
    return im[h_start:h_end, w_start:w_end]


def random_crop(im, size, is_color=True):
    """ref ``image.py:281``."""
    h, w = im.shape[:2]
    h_start = np.random.randint(0, h - size + 1)
    w_start = np.random.randint(0, w - size + 1)
    h_end, w_end = h_start + size, w_start + size
    if is_color:
        return im[h_start:h_end, w_start:w_end, :]
    return im[h_start:h_end, w_start:w_end]


def left_right_flip(im, is_color=True):
    """ref ``image.py:309``."""
    if len(im.shape) == 3 and is_color:
        return im[:, ::-1, :]
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    """ref ``image.py:331`` — resize_short, crop, maybe flip, CHW, -mean."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color=is_color)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color=is_color)
    if len(im.shape) == 3:
        im = to_chw(im)
    im = im.astype('float32')
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1 and is_color:
            mean = mean[:, np.newaxis, np.newaxis]
        elif mean.ndim == 1:
            mean = mean
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    """ref ``image.py:387``."""
    im = load_image(filename, is_color)
    return simple_transform(im, resize_size, crop_size, is_train, is_color,
                            mean)
