"""paddle.metric equivalent (ref ``python/paddle/metric/metrics.py``)."""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name


def accuracy(input, label, k=1):  # noqa: A002
    """Functional top-k accuracy (ref ``paddle.metric.accuracy``)."""
    pred = _np(input)
    lbl = _np(label).reshape(-1)
    topk = np.argsort(-pred, axis=-1)[..., :k].reshape(len(lbl), k)
    correct = (topk == lbl[:, None]).any(axis=1)
    return Tensor(np.asarray(correct.mean(), np.float32))


class Accuracy(Metric):
    def __init__(self, topk=(1,), name="acc"):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self._name = name
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label):
        pred_np = _np(pred)
        lbl = _np(label)
        if lbl.ndim == pred_np.ndim and lbl.shape[-1] == 1:
            lbl = lbl[..., 0]
        maxk = max(self.topk)
        topk_idx = np.argsort(-pred_np, axis=-1)[..., :maxk]
        correct = topk_idx == lbl[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct):
        c = _np(correct)
        batch = c.reshape(-1, c.shape[-1])
        for i, k in enumerate(self.topk):
            self.total[i] += batch[:, :k].any(axis=1).sum()
            self.count[i] += batch.shape[0]
        return self.total[0] / max(self.count[0], 1)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2:
            p = p[:, -1]
        l = _np(labels).reshape(-1)
        bins = np.clip((p * self.num_thresholds).astype(np.int64), 0,
                       self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        area = 0.0
        pos = neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = pos + self._stat_pos[i]
            new_neg = neg + self._stat_neg[i]
            area += (new_neg - neg) * (pos + new_pos) / 2
            pos, neg = new_pos, new_neg
        return area / (tot_pos * tot_neg)
