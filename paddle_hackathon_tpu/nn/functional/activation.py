"""Activation functionals (ref ``python/paddle/nn/functional/activation.py``).

Pure elementwise jax.nn compositions; XLA fuses them into adjacent matmuls —
the hand-written fused epilogues of the reference
(``operators/fused/fused_gemm_epilogue_op.cu``) come for free.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.autograd import apply_op
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def relu(x, name=None):
    return apply_op("relu", jax.nn.relu, [_t(x)])


def relu6(x, name=None):
    return apply_op("relu6", jax.nn.relu6, [_t(x)])


def relu_(x):
    out = relu(x)
    # in-place rebind keeps the tape consistent (same as Tensor.__setitem__)
    x._value = out._value
    x._grad_node = out._grad_node
    x._out_idx = out._out_idx
    x.stop_gradient = out.stop_gradient
    return x


def sigmoid(x, name=None):
    return apply_op("sigmoid", jax.nn.sigmoid, [_t(x)])


def tanh(x, name=None):
    return apply_op("tanh", jnp.tanh, [_t(x)])


def gelu(x, approximate=False, name=None):
    return apply_op("gelu",
                    lambda v: jax.nn.gelu(v, approximate=approximate), [_t(x)])


def silu(x, name=None):
    return apply_op("silu", jax.nn.silu, [_t(x)])


def swish(x, name=None):
    return silu(x)


def mish(x, name=None):
    return apply_op("mish", lambda v: v * jnp.tanh(jax.nn.softplus(v)), [_t(x)])


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op("leaky_relu",
                    lambda v: jax.nn.leaky_relu(v, negative_slope), [_t(x)])


def elu(x, alpha=1.0, name=None):
    return apply_op("elu", lambda v: jax.nn.elu(v, alpha), [_t(x)])


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply_op(
        "selu", lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)),
        [_t(x)])


def celu(x, alpha=1.0, name=None):
    return apply_op("celu", lambda v: jax.nn.celu(v, alpha), [_t(x)])


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(v, w):
        if w.size > 1:
            shape = [1] * v.ndim
            ch_axis = 1 if data_format[1] == "C" else v.ndim - 1
            shape[ch_axis] = w.size
            w = w.reshape(shape)
        return jnp.where(v > 0, v, w * v)
    return apply_op("prelu", fn, [_t(x), _t(weight)])


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    from ...core import random as core_random
    if training:
        key = core_random.split_key()

        def fn(v):
            r = jax.random.uniform(key, v.shape, v.dtype, lower, upper)
            return jnp.where(v >= 0, v, r * v)
        return apply_op("rrelu", fn, [_t(x)])
    mid = (lower + upper) / 2.0
    return leaky_relu(x, mid)


def hardtanh(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    return apply_op("hardtanh", lambda v: jnp.clip(v, min, max), [_t(x)])


def hardsigmoid(x, slope=1.0 / 6.0, offset=0.5, name=None):
    return apply_op("hardsigmoid",
                    lambda v: jnp.clip(v * slope + offset, 0.0, 1.0), [_t(x)])


def hardswish(x, name=None):
    return apply_op("hardswish",
                    lambda v: v * jnp.clip(v / 6.0 + 0.5, 0.0, 1.0), [_t(x)])


def hardshrink(x, threshold=0.5, name=None):
    return apply_op("hardshrink",
                    lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0), [_t(x)])


def softshrink(x, threshold=0.5, name=None):
    return apply_op(
        "softshrink",
        lambda v: jnp.sign(v) * jnp.maximum(jnp.abs(v) - threshold, 0.0), [_t(x)])


def tanhshrink(x, name=None):
    return apply_op("tanhshrink", lambda v: v - jnp.tanh(v), [_t(x)])


def thresholded_relu(x, threshold=1.0, name=None):
    return apply_op("thresholded_relu",
                    lambda v: jnp.where(v > threshold, v, 0.0), [_t(x)])


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply_op(
        "softplus",
        lambda v: jnp.where(v * beta > threshold, v,
                            jax.nn.softplus(v * beta) / beta), [_t(x)])


def softsign(x, name=None):
    return apply_op("softsign", jax.nn.soft_sign, [_t(x)])


def softmax(x, axis=-1, dtype=None, name=None):
    return apply_op("softmax", lambda v: jax.nn.softmax(v, axis=axis), [_t(x)])


def log_softmax(x, axis=-1, dtype=None, name=None):
    return apply_op("log_softmax",
                    lambda v: jax.nn.log_softmax(v, axis=axis), [_t(x)])


def softmax_(x, axis=-1):
    out = softmax(x, axis)
    x._value = out._value
    x._grad_node = out._grad_node
    x._out_idx = out._out_idx
    x.stop_gradient = out.stop_gradient
    return x


def log_sigmoid(x, name=None):
    return apply_op("log_sigmoid", jax.nn.log_sigmoid, [_t(x)])


def maxout(x, groups, axis=1, name=None):
    def fn(v):
        ax = axis % v.ndim
        c = v.shape[ax]
        new_shape = v.shape[:ax] + (c // groups, groups) + v.shape[ax + 1:]
        return jnp.max(v.reshape(new_shape), axis=ax + 1)
    return apply_op("maxout", fn, [_t(x)])


def glu(x, axis=-1, name=None):
    return apply_op("glu", lambda v: jax.nn.glu(v, axis=axis), [_t(x)])


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import random as core_random
    key = core_random.split_key()

    def fn(v):
        g = jax.random.gumbel(key, v.shape, v.dtype)
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            onehot = jnp.zeros_like(y)
            onehot = jnp.put_along_axis(onehot, idx, 1.0, axis=axis,
                                        inplace=False)
            y = onehot + y - jax.lax.stop_gradient(y)
        return y
    return apply_op("gumbel_softmax", fn, [_t(x)])


def tanh_(x, name=None):
    """In-place tanh (tape-consistent rebind, see ops.inplace)."""
    from ...ops import inplace as _inp
    from ...ops import math as _math
    return _inp._rebind(_t(x), _math.tanh(x))


def elu_(x, alpha=1.0, name=None):
    from ...ops import inplace as _inp
    return _inp._rebind(_t(x), elu(x, alpha))


def relu_(x, name=None):
    from ...ops import inplace as _inp
    return _inp._rebind(_t(x), relu(x))


def softmax_(x, axis=-1, dtype=None, name=None):
    from ...ops import inplace as _inp
    return _inp._rebind(_t(x), softmax(x, axis=axis, dtype=dtype))
