"""Convolution functionals (ref ``python/paddle/nn/functional/conv.py``;
kernels ref ``paddle/phi/kernels/gpudnn/conv_*``).

All convs lower to one ``lax.conv_general_dilated`` — XLA maps it onto the MXU
(space-to-depth + matmul tiling), replacing the reference's cudnn algo search +
autotune cache (``phi/kernels/autotune``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.autograd import apply_op
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(i) for i in v)
    if len(v) == 1:
        return v * n
    return v


def _norm_padding(padding, n):
    """Normalise paddle padding spec to lax format: str | [(lo,hi)]*n."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, n,
             channel_last, op_name):
    spatial = "DHW"[3 - n:]
    if channel_last:
        lhs_spec = "N" + spatial + "C"
    else:
        lhs_spec = "NC" + spatial
    dn = (lhs_spec, "OI" + spatial, lhs_spec)
    strides = _tuple(stride, n)
    dil = _tuple(dilation, n)
    pad = _norm_padding(padding, n)

    def fn(v, w, *rest):
        out = jax.lax.conv_general_dilated(
            v, w, window_strides=strides, padding=pad,
            rhs_dilation=dil, dimension_numbers=dn,
            feature_group_count=groups)
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[1 if not channel_last else out.ndim - 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    args = [_t(x), _t(weight)]
    if bias is not None:
        args.append(_t(bias))
    return apply_op(op_name, fn, args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1,
                    data_format == "NLC", "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2,
                    data_format == "NHWC", "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3,
                    data_format == "NDHWC", "conv3d")


def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       dilation, groups, n, channel_last, op_name):
    spatial = "DHW"[3 - n:]
    lhs_spec = ("N" + spatial + "C") if channel_last else ("NC" + spatial)
    # paddle transpose-conv weight layout: (in_channels, out_channels/groups, *k)
    dn = (lhs_spec, "IO" + spatial, lhs_spec)
    strides = _tuple(stride, n)
    dil = _tuple(dilation, n)
    opad = _tuple(output_padding, n)

    def fn(v, w, *rest):
        if isinstance(padding, str):
            pad = padding.upper()
        else:
            p = _norm_padding(padding, n)
            k = [w.shape[2 + i] for i in range(n)]
            # gradient-of-conv padding transformation
            pad = [(dil[i] * (k[i] - 1) - p[i][0],
                    dil[i] * (k[i] - 1) - p[i][1] + opad[i]) for i in range(n)]
        out = jax.lax.conv_general_dilated(
            v, w, window_strides=(1,) * n, padding=pad,
            lhs_dilation=strides, rhs_dilation=dil, dimension_numbers=dn,
            feature_group_count=groups)
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[1 if not channel_last else out.ndim - 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    # kernel spatial flip for true transpose conv
    def flip_w(w):
        return jnp.flip(w, axis=tuple(range(2, 2 + n)))

    args = [_t(x), _t(weight)]
    if bias is not None:
        args.append(_t(bias))

    def wrapped(v, w, *rest):
        return fn(v, flip_w(w), *rest)
    return apply_op(op_name, wrapped, args)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 1, data_format == "NLC",
                              "conv1d_transpose")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 2, data_format == "NHWC",
                              "conv2d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 3, data_format == "NDHWC",
                              "conv3d_transpose")
