"""Attention functionals.

Equivalent of the reference's fused attention CUDA ops
(``paddle/fluid/operators/fused/fused_attention_op.cu``, ``fmha_ref.h``) —
but as a flash-style computation: when the Pallas kernel is available
(``incubate.flash_attention``) it is used; otherwise a pure-XLA softmax(QK)V
composition runs (still fused reasonably by XLA).

The reference has no flash attention (SURVEY §5.7) — this is a
capability-parity-plus feature required for long-context work.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core import flags
from ...core.autograd import apply_op
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, use_flash=None, name=None):
    """SDPA over (batch, seq, heads, head_dim) tensors (paddle layout).

    Uses the Pallas flash kernel on TPU when enabled (``use_flash`` overrides
    FLAGS_use_fused_kernels) and shapes qualify; falls back to the pure-XLA
    composition otherwise.
    """
    if use_flash is None:  # auto: flash only where it beats XLA (long seq)
        flash_ok = (flags.flag("use_fused_kernels")
                    and query.shape[1] >= flags.flag("flash_attention_min_seqlen"))
    else:
        flash_ok = use_flash
    if flash_ok and attn_mask is None:
        eff_drop = dropout_p if training else 0.0
        try:
            from ...incubate.nn.functional import flash_attention_bshd
            return flash_attention_bshd(_t(query), _t(key), _t(value),
                                        causal=is_causal,
                                        dropout_p=eff_drop)
        except ValueError:
            # the kernel's explicit unsupported-shape signal; anything else
            # is a real bug and must surface (a blanket except once hid a
            # 23x throughput regression via the O(S^2) fallback)
            pass

    scale = 1.0 / math.sqrt(query.shape[-1])
    drop_key = None
    if dropout_p > 0.0 and training:
        from ...core import random as core_random
        drop_key = core_random.split_key()

    def fn(q, k, v, *rest):
        # bshd -> bhsd
        qh = jnp.swapaxes(q, 1, 2)
        kh = jnp.swapaxes(k, 1, 2)
        vh = jnp.swapaxes(v, 1, 2)
        logits = jnp.einsum("bhsd,bhtd->bhst", qh, kh) * scale
        if is_causal:
            s, t = logits.shape[-2], logits.shape[-1]
            mask = jnp.tril(jnp.ones((s, t), bool))
            logits = jnp.where(mask, logits, -1e30)
        if rest:
            logits = logits + rest[0]
        probs = jax.nn.softmax(logits, axis=-1)
        if drop_key is not None:
            keep = jax.random.bernoulli(drop_key, 1.0 - dropout_p, probs.shape)
            probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0
                              ).astype(probs.dtype)
        out = jnp.einsum("bhst,bhtd->bhsd", probs, vh)
        return jnp.swapaxes(out, 1, 2)

    args = [_t(query), _t(key), _t(value)]
    if attn_mask is not None:
        args.append(_t(attn_mask))
    return apply_op("scaled_dot_product_attention", fn, args)


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    from ...core.dtype import convert_dtype
    lengths = _t(lengths)
    n = maxlen or int(jnp.max(lengths._value))
    d = convert_dtype(dtype)

    def fn(l):
        return (jnp.arange(n)[None, :] < l[:, None]).astype(d)
    return apply_op("sequence_mask", fn, [lengths])
