"""Pooling functionals (ref ``python/paddle/nn/functional/pooling.py``;
kernels ref ``paddle/phi/kernels/funcs/pooling.h``).

All pools lower to ``lax.reduce_window`` — XLA's windowed reduction maps to
the VPU with HBM-friendly tiling.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...core.autograd import apply_op
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _tuple(v, n):
    if v is None:
        return None
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(i) for i in v)
    return v * n if len(v) == 1 else v


def _pad_spec(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def _reduce_window(v, init, op, window, strides, pad, channel_last, n):
    if channel_last:
        dims = (1,) + window + (1,)
        strd = (1,) + strides + (1,)
        padc = [(0, 0)] + list(pad) + [(0, 0)] if not isinstance(pad, str) else pad
    else:
        dims = (1, 1) + window
        strd = (1, 1) + strides
        padc = [(0, 0), (0, 0)] + list(pad) if not isinstance(pad, str) else pad
    return jax.lax.reduce_window(v, init, op, dims, strd, padc)


def _ceil_extend(pad, v_shape, window, strides, channel_last, n):
    """Extra high-side padding so the last partial window is emitted
    (ceil_mode=True semantics, ref funcs/pooling.h AdaptStartEndIndex)."""
    spatial = (list(range(1, 1 + n)) if channel_last
               else list(range(2, 2 + n)))
    out = []
    for i, (lo, hi) in enumerate(pad):
        size = v_shape[spatial[i]]
        eff = size + lo + hi - window[i]
        out_floor = eff // strides[i] + 1
        out_ceil = -(-eff // strides[i]) + 1
        extra = (out_ceil - out_floor) * strides[i]
        out.append((lo, hi + extra))
    return out


def _max_pool(x, kernel_size, stride, padding, ceil_mode, n, channel_last,
              name, return_mask=False):
    if return_mask:
        if ceil_mode:
            raise NotImplementedError("return_mask with ceil_mode")
        return _max_pool_with_mask(x, kernel_size, stride, padding, n,
                                   channel_last, name)
    window = _tuple(kernel_size, n)
    strides = _tuple(stride, n) if stride is not None else window
    pad = _pad_spec(padding, n)

    def fn(v):
        p = pad
        if ceil_mode and not isinstance(p, str):
            p = _ceil_extend(p, v.shape, window, strides, channel_last, n)
        return _reduce_window(v, -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating)
                              else jnp.iinfo(v.dtype).min,
                              jax.lax.max, window, strides, p,
                              channel_last, n)
    return apply_op(name, fn, [_t(x)])


def _avg_pool(x, kernel_size, stride, padding, exclusive, n, channel_last,
              name, ceil_mode=False, divisor_override=None):
    window = _tuple(kernel_size, n)
    strides = _tuple(stride, n) if stride is not None else window
    pad = _pad_spec(padding, n)

    def fn(v):
        p = pad
        if ceil_mode and not isinstance(p, str):
            p = _ceil_extend(p, v.shape, window, strides, channel_last, n)
        s = _reduce_window(v.astype(jnp.float32), 0.0, jax.lax.add, window,
                           strides, p, channel_last, n)
        if divisor_override is not None:
            return (s / float(divisor_override)).astype(v.dtype)
        if (exclusive or ceil_mode) and not isinstance(p, str):
            ones = jnp.ones_like(v, jnp.float32)
            cnt = _reduce_window(ones, 0.0, jax.lax.add, window, strides, p,
                                 channel_last, n)
            return (s / cnt).astype(v.dtype)
        return (s / float(np.prod(window))).astype(v.dtype)
    return apply_op(name, fn, [_t(x)])


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    return _max_pool(x, kernel_size, stride, padding, ceil_mode, 1,
                     data_format == "NLC", "max_pool1d", return_mask)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _max_pool(x, kernel_size, stride, padding, ceil_mode, 2,
                     data_format == "NHWC", "max_pool2d", return_mask)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _max_pool(x, kernel_size, stride, padding, ceil_mode, 3,
                     data_format == "NDHWC", "max_pool3d", return_mask)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _avg_pool(x, kernel_size, stride, padding, exclusive, 1,
                     data_format == "NLC", "avg_pool1d", ceil_mode)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _avg_pool(x, kernel_size, stride, padding, exclusive, 2,
                     data_format == "NHWC", "avg_pool2d", ceil_mode,
                     divisor_override)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _avg_pool(x, kernel_size, stride, padding, exclusive, 3,
                     data_format == "NDHWC", "avg_pool3d", ceil_mode,
                     divisor_override)


def _adaptive_pool(x, output_size, n, channel_last, reducer, name):
    out_sizes = _tuple(output_size, n)

    def fn(v):
        spatial_axes = (list(range(1, 1 + n)) if channel_last
                        else list(range(2, 2 + n)))
        out = v
        for i, ax in enumerate(spatial_axes):
            osz = out_sizes[i]
            if osz is None:
                continue
            isz = out.shape[ax]
            if isz % osz == 0:
                k = isz // osz
                new_shape = (out.shape[:ax] + (osz, k) + out.shape[ax + 1:])
                out = reducer(out.reshape(new_shape), axis=ax + 1)
            else:
                # general case: per-output-bin slices
                starts = [int(np.floor(j * isz / osz)) for j in range(osz)]
                ends = [int(np.ceil((j + 1) * isz / osz)) for j in range(osz)]
                pieces = [
                    reducer(jax.lax.slice_in_dim(out, s, e, axis=ax), axis=ax,
                            keepdims=True)
                    for s, e in zip(starts, ends)]
                out = jnp.concatenate(pieces, axis=ax)
        return out
    return apply_op(name, fn, [_t(x)])


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, False, jnp.mean,
                          "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format == "NHWC", jnp.mean,
                          "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format == "NDHWC", jnp.mean,
                          "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, False, jnp.max,
                          "adaptive_max_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, False, jnp.max,
                          "adaptive_max_pool2d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, False, jnp.max,
                          "adaptive_max_pool3d")


def _max_pool_with_mask(x, kernel_size, stride, padding, n, channel_last,
                        name):
    """Max pool returning (out, mask) where mask holds flat spatial argmax
    indices into the unpadded input (paddle max_pool return_mask semantics;
    ref phi MaxPoolWithIndexKernel). Gather-based: O(out*k) reads — XLA
    turns the window gather into vectorized loads."""
    window = _tuple(kernel_size, n)
    strides = _tuple(stride, n) if stride is not None else window
    pad = _pad_spec(padding, n)
    if isinstance(pad, str):
        raise NotImplementedError("string padding with return_mask")

    def fn(v):
        if channel_last:  # normalize to channel-first for the math
            v = jnp.moveaxis(v, -1, 1)
        spatial = v.shape[2:]
        neg = (-jnp.inf if jnp.issubdtype(v.dtype, jnp.floating)
               else jnp.iinfo(v.dtype).min)
        cfg = [(0, 0), (0, 0)] + [tuple(p) for p in pad]
        vp = jnp.pad(v, cfg, constant_values=neg)
        # windowed view: iteratively gather each spatial dim
        out_sizes = [ (spatial[i] + sum(pad[i]) - window[i]) // strides[i] + 1
                      for i in range(n) ]
        w = vp
        # after loop: shape (N, C, o1, k1, o2, k2, ...)
        for i in range(n):
            axis = 2 + 2 * i  # current spatial dim position
            starts = jnp.arange(out_sizes[i]) * strides[i]
            idx = starts[:, None] + jnp.arange(window[i])[None, :]
            w = jnp.take(w, idx, axis=axis)
        # -> (N, C, o1..on, k1..kn)
        perm = ([0, 1] + [2 + 2 * i for i in range(n)]
                + [3 + 2 * i for i in range(n)])
        w = jnp.transpose(w, perm)
        lead = w.shape[:2 + n]
        w = w.reshape(lead + (-1,))
        out = jnp.max(w, -1)
        local = jnp.argmax(w, -1)  # flat index within the window
        # local -> per-dim offsets -> global unpadded flat index
        flat = jnp.zeros(local.shape, jnp.int32)
        rem = local
        for i in range(n):
            kprod = 1
            for j in range(i + 1, n):
                kprod *= window[j]
            off = rem // kprod
            rem = rem % kprod
            starts = (jnp.arange(out_sizes[i]) * strides[i] - pad[i][0])
            shape = [1] * (2 + n)
            shape[2 + i] = out_sizes[i]
            gpos = starts.reshape(shape) + off
            sprod = 1
            for j in range(i + 1, n):
                sprod *= spatial[j]
            flat = flat + gpos.astype(jnp.int32) * sprod
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
            flat = jnp.moveaxis(flat, 1, -1)
        return out, flat

    return apply_op(name, fn, [_t(x)], n_outputs=2)


def _max_unpool(x, indices, kernel_size, stride, padding, output_size, n,
                channel_last, name):
    """Scatter pooled values back by mask indices (ref phi MaxUnpool kernels)."""
    window = _tuple(kernel_size, n)
    strides = _tuple(stride, n) if stride is not None else window
    pad = _pad_spec(padding, n)

    def fn(v, idx):
        if channel_last:
            v = jnp.moveaxis(v, -1, 1)
            idx = jnp.moveaxis(idx, -1, 1)
        spatial = v.shape[2:]
        if output_size is not None:
            out_sp = tuple(output_size)[-n:]
        else:
            out_sp = tuple((spatial[i] - 1) * strides[i] - 2 * pad[i][0]
                           + window[i] for i in range(n))
        os = 1
        for s in out_sp:
            os *= s
        nb, c = v.shape[0], v.shape[1]
        vf = v.reshape(nb * c, -1)
        idxf = idx.reshape(nb * c, -1).astype(jnp.int32)
        scat = jax.vmap(lambda i, val: jnp.zeros((os,), v.dtype).at[i].set(val))
        out = scat(idxf, vf).reshape((nb, c) + out_sp)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    return apply_op(name, fn, [_t(x), _t(indices)])


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, output_size,
                       1, data_format == "NLC", "max_unpool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, output_size,
                       2, data_format == "NHWC", "max_unpool2d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, output_size,
                       3, data_format == "NDHWC", "max_unpool3d")
