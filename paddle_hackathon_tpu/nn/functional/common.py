"""Common functionals: linear, dropout, embedding, one_hot, normalize,
interpolate, pixel_shuffle, unfold (ref ``python/paddle/nn/functional/common.py``,
``input.py``, ``vision.py``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import random as core_random
from ...core.autograd import apply_op
from ...core.dtype import convert_dtype
from ...core.tensor import Tensor
from ...ops.manipulation import pad as _pad_op


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b (ref ``F.linear`` ``nn/functional/common.py:1637``;
    the reference composes matmul+add in ``eager_final_state_custom_python_api.h:32-44``
    — here XLA fuses the bias add into the MXU matmul epilogue)."""
    if bias is None:
        return apply_op("linear", lambda v, w: v @ w, [_t(x), _t(weight)])
    return apply_op("linear", lambda v, w, b: v @ w + b,
                    [_t(x), _t(weight), _t(bias)])


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    """Dropout (ref phi DropoutKernel). Draws its mask key from the active
    rng scope so jitted programs stay replayable."""
    if not training or p == 0.0:
        return _t(x)
    if p == 1.0:
        return apply_op("dropout", lambda v: jnp.zeros_like(v), [_t(x)])
    key = core_random.split_key()

    def fn(v):
        shape = list(v.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), 0.0).astype(v.dtype)
        return jnp.where(keep, v, 0.0).astype(v.dtype)
    return apply_op("dropout", fn, [_t(x)])


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return _t(x)
    key = core_random.split_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def fn(v):
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        a = (1.0 / ((1 - p) * (1 + p * alpha_p ** 2)) ** 0.5)
        b = -a * alpha_p * p
        return (a * jnp.where(keep, v, alpha_p) + b).astype(v.dtype)
    return apply_op("alpha_dropout", fn, [_t(x)])


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Embedding lookup (ref phi EmbeddingKernel) — a gather on the MXU-free
    path; the TP variant lives in parallel/mp_layers."""
    def fn(i, w):
        out = jnp.take(w, i, axis=0)
        if padding_idx is not None:
            mask = (i == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return apply_op("embedding", fn, [_t(x), _t(weight)])


def one_hot(x, num_classes, name=None):
    return apply_op("one_hot",
                    lambda i: jax.nn.one_hot(i, num_classes), [_t(x)])


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    if prior_dist is not None:
        return apply_op("label_smooth",
                        lambda l, p: (1 - epsilon) * l + epsilon * p,
                        [_t(label), _t(prior_dist)])
    return apply_op("label_smooth",
                    lambda l: (1 - epsilon) * l + epsilon / l.shape[-1],
                    [_t(label)])


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(v):
        if p == 2:
            n = jnp.sqrt(jnp.sum(jnp.square(v), axis=axis, keepdims=True))
        else:
            n = jnp.sum(jnp.abs(v) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return v / jnp.maximum(n, epsilon)
    return apply_op("normalize", fn, [_t(x)])


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def fn(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)
    return apply_op("cosine_similarity", fn, [_t(x1), _t(x2)])


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    return _pad_op(x, pad, mode=mode, value=value, data_format=data_format)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    """Resize (ref phi InterpolateKernel) via jax.image.resize."""
    x = _t(x)
    nd = x.ndim
    channel_last = data_format[-1] == "C"
    spatial = list(range(1, nd - 1)) if channel_last else list(range(2, nd))
    in_sizes = [x.shape[i] for i in spatial]
    if size is not None:
        if isinstance(size, Tensor):
            size = size.tolist()
        out_sizes = [int(s) for s in size]
    else:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * len(spatial)
        out_sizes = [int(s * f) for s, f in zip(in_sizes, scale_factor)]
    method = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
              "bicubic": "cubic", "trilinear": "linear", "area": "linear"}[mode]

    def fn(v):
        full = list(v.shape)
        for dim, s in zip(spatial, out_sizes):
            full[dim] = s
        return jax.image.resize(v, tuple(full), method=method)
    return apply_op("interpolate", fn, [x])


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = v.transpose(0, 1, 4, 2, 5, 3)
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h * r, w * r, c // (r * r))
    return apply_op("pixel_shuffle", fn, [_t(x)])


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c, h // r, r, w // r, r)
            v = v.transpose(0, 1, 3, 5, 2, 4)
            return v.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = v.shape
        v = v.reshape(n, h // r, r, w // r, r, c)
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h // r, w // r, c * r * r)
    return apply_op("pixel_unshuffle", fn, [_t(x)])


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, groups, c // groups, h, w)
            return v.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, groups, c // groups)
        return v.transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)
    return apply_op("channel_shuffle", fn, [_t(x)])


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (ref phi UnfoldKernel)."""
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    dh, dw = _pair(dilations)
    p = _pair(paddings) if isinstance(paddings, int) or len(paddings) == 2 \
        else tuple(paddings)
    if len(p) == 2:
        pt, pb, pl, pr = p[0], p[0], p[1], p[1]
    else:
        pt, pb, pl, pr = p

    def fn(v):
        n, c, h, w = v.shape
        v = jnp.pad(v, [(0, 0), (0, 0), (pt, pb), (pl, pr)])
        oh = (v.shape[2] - (dh * (kh - 1) + 1)) // sh + 1
        ow = (v.shape[3] - (dw * (kw - 1) + 1)) // sw + 1
        patches = jax.lax.conv_general_dilated_patches(
            v, (kh, kw), (sh, sw), "VALID", rhs_dilation=(dh, dw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return patches.reshape(n, c * kh * kw, oh * ow)
    return apply_op("unfold", fn, [_t(x)])


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im — adjoint of unfold, implemented via the VJP of unfold so the
    pair stays exactly mutually adjoint."""
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)

    def fn(col):
        n = col.shape[0]
        c = col.shape[1] // (kh * kw)

        def unfold_pure(img):
            t = unfold(Tensor(img), kernel_sizes, strides, paddings, dilations)
            return t._value
        img0 = jnp.zeros((n, c, oh, ow), col.dtype)
        _, vjp = jax.vjp(unfold_pure, img0)
        (out,) = vjp(col)
        return out
    return apply_op("fold", fn, [_t(x)])


def affine_grid(theta, out_shape, align_corners=True, name=None):
    def fn(th):
        n, _, _ = th.shape
        _, _, h, w = out_shape
        ys = jnp.linspace(-1, 1, h) if align_corners else \
            (jnp.arange(h) * 2 + 1) / h - 1
        xs = jnp.linspace(-1, 1, w) if align_corners else \
            (jnp.arange(w) * 2 + 1) / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1).reshape(1, h * w, 3)
        grid = base @ jnp.swapaxes(th, 1, 2)
        return grid.reshape(n, h, w, 2)
    return apply_op("affine_grid", fn, [_t(theta)])


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    def fn(v, g):
        n, c, h, w = v.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            ix = (gx + 1) * (w - 1) / 2
            iy = (gy + 1) * (h - 1) / 2
        else:
            ix = ((gx + 1) * w - 1) / 2
            iy = ((gy + 1) * h - 1) / 2
        if mode == "nearest":
            ix_r, iy_r = jnp.round(ix), jnp.round(iy)

            def nearest_one(img, yy, xx):
                valid = (xx >= 0) & (xx < w) & (yy >= 0) & (yy < h)
                xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
                yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
                return jnp.where(valid[None], img[:, yc, xc], 0.0)
            return jax.vmap(nearest_one)(v, iy_r, ix_r)
        x0 = jnp.floor(ix)
        y0 = jnp.floor(iy)
        x1, y1 = x0 + 1, y0 + 1

        def sample(img, yy, xx):
            valid = (xx >= 0) & (xx < w) & (yy >= 0) & (yy < h)
            xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            # img: (c,h,w); yy/xx: (ho,wo)
            vals = img[:, yc, xc]
            return jnp.where(valid[None], vals, 0.0)

        def per_image(img, yy0, xx0, yy1, xx1, ixx, iyy):
            Ia = sample(img, yy0, xx0)
            Ib = sample(img, yy1, xx0)
            Ic = sample(img, yy0, xx1)
            Id = sample(img, yy1, xx1)
            wa = (xx1 - ixx) * (yy1 - iyy)
            wb = (xx1 - ixx) * (iyy - yy0)
            wc = (ixx - xx0) * (yy1 - iyy)
            wd = (ixx - xx0) * (iyy - yy0)
            return Ia * wa + Ib * wb + Ic * wc + Id * wd
        return jax.vmap(per_image)(v, y0, x0, y1, x1, ix, iy)
    return apply_op("grid_sample", fn, [_t(x), _t(grid)])


def bilinear(x1, x2, weight, bias=None, name=None):
    def fn(a, b, w, *rest):
        out = jnp.einsum("bm,omn,bn->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out
    args = [_t(x1), _t(x2), _t(weight)]
    if bias is not None:
        args.append(_t(bias))
    return apply_op("bilinear", fn, args)


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):  # noqa: A002
    """Batch diagonal embed (ref phi DiagEmbedKernel)."""
    def fn(v):
        n = v.shape[-1] + abs(offset)
        base = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        i = jnp.arange(v.shape[-1])
        r = i + max(-offset, 0)
        c = i + max(offset, 0)
        out = base.at[..., r, c].set(v)
        nd = out.ndim
        d1, d2 = dim1 % nd, dim2 % nd
        # move the two trailing matrix dims to (dim1, dim2)
        perm = [ax for ax in range(nd) if ax not in (nd - 2, nd - 1)]
        order = sorted([(d1, nd - 2), (d2, nd - 1)])
        for pos, src in order:
            perm.insert(pos, src)
        return jnp.transpose(out, perm)
    return apply_op("diag_embed", fn, [_t(input)])


def zeropad2d(x, padding, data_format="NCHW", name=None):
    l, r, top, bot = (padding.tolist() if isinstance(padding, Tensor)
                      else list(padding))
    def fn(v):
        if data_format == "NCHW":
            cfg = [(0, 0), (0, 0), (top, bot), (l, r)]
        else:
            cfg = [(0, 0), (top, bot), (l, r), (0, 0)]
        return jnp.pad(v, cfg)
    return apply_op("zeropad2d", fn, [_t(x)])


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """Temporal Shift Module op (ref phi TemporalShiftKernel): shift a
    fraction of channels forward/backward along the segment (time) axis."""
    def fn(v):
        if data_format == "NHWC":
            v = jnp.moveaxis(v, -1, 1)
        nt, c, h, w = v.shape
        n = nt // seg_num
        v5 = v.reshape(n, seg_num, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        back = jnp.pad(v5[:, 1:, :c1], [(0, 0), (0, 1), (0, 0), (0, 0), (0, 0)])
        fwd = jnp.pad(v5[:, :-1, c1:c2], [(0, 0), (1, 0), (0, 0), (0, 0), (0, 0)])
        keep = v5[:, :, c2:]
        out = jnp.concatenate([back, fwd, keep], axis=2)
        out = out.reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out
    return apply_op("temporal_shift", fn, [_t(x)])


def gather_tree(ids, parents):
    """Backtrack beam-search parent pointers into full sequences
    (ref phi GatherTreeKernel). ids/parents: (T, B, beam)."""
    def fn(i, par):
        T = i.shape[0]
        def step(carry, t):
            beams = carry  # (B, beam) beam index at time t+1
            out = jnp.take_along_axis(i[t], beams, axis=-1)
            nxt = jnp.take_along_axis(par[t], beams, axis=-1)
            return nxt, out
        init = jnp.broadcast_to(jnp.arange(i.shape[-1]), i.shape[1:])
        _, outs = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return jnp.flip(outs, 0)
    from ...core import autograd as _ag
    with _ag.no_grad():
        return apply_op("gather_tree", fn, [_t(ids), _t(parents)])


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention with a CSR connectivity pattern (ref
    ``operators/sparse_attention_op.cu``). Computed as dense attention with
    a -inf mask built from the CSR pattern — XLA fuses the masking; a Pallas
    blocked kernel (incubate.flash_attention) is the long-context path."""
    def fn(q, k, v, off, cols):
        b, h, s, d = q.shape
        logits = jnp.einsum("bhsd,bhtd->bhst", q, k) / (d ** 0.5)
        pos = jnp.arange(cols.shape[-1])

        def one_mask(off_r, cols_r):
            # row id of each nnz: searchsorted over cumulative offsets
            row = jnp.clip(jnp.searchsorted(off_r, pos, side="right") - 1,
                           0, s - 1)
            return jnp.zeros((s, s), bool).at[row, cols_r].set(True)

        mask = jax.vmap(jax.vmap(one_mask))(off, cols)  # (b, h, s, s)
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits, -1)
        return jnp.einsum("bhst,bhtd->bhsd", probs, v)
    return apply_op("sparse_attention", fn,
                    [_t(query), _t(key), _t(value),
                     _t(sparse_csr_offset), _t(sparse_csr_columns)])


def gather_tree(ids, parents):
    """Beam-search backtrace (ref ``phi/kernels/gather_tree_kernel.h``;
    public ``paddle.nn.functional.gather_tree``): walk parent pointers from
    the last step back to the first, emitting the full id sequence of every
    final beam.  Inputs are (max_time, batch, beam_width) int tensors; the
    walk is a reverse ``lax.scan`` carrying the selected beam indices."""
    def fn(idv, parv):
        t_len, b, w = idv.shape

        def step(beams, t):
            picked = jnp.take_along_axis(idv[t], beams, axis=1)
            beams_next = jnp.take_along_axis(parv[t], beams, axis=1)
            return beams_next, picked

        init = jnp.broadcast_to(jnp.arange(w, dtype=parv.dtype), (b, w))
        _, outs = jax.lax.scan(step, init,
                               jnp.arange(t_len - 1, -1, -1))
        return outs[::-1]

    return apply_op("gather_tree", fn, [_t(ids), _t(parents)])
