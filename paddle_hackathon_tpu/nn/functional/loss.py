"""Loss functionals (ref ``python/paddle/nn/functional/loss.py``; kernels ref
``paddle/phi/kernels/gpu/cross_entropy_kernel.cu`` etc.).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.autograd import apply_op
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def fused_softmax_ce_rows(logits, labels_i, axis=-1):
    """Per-row -log softmax(logits)[label] as f32: logsumexp - gathered logit.

    Gathering from the raw logits (not from a log-softmax array) lets XLA
    fuse the logsumexp reduction into the logits producer instead of
    materialising a full [rows, V] log-softmax — at LM vocab sizes that
    buffer is the single largest HBM round-trip in the loss.
    """
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=axis)
    tgt = jnp.take_along_axis(
        logits, jnp.expand_dims(labels_i, axis), axis=axis
    ).squeeze(axis).astype(jnp.float32)
    return lse - tgt


def cross_entropy(input, label, weight=None, ignore_index=-100,  # noqa: A002
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    """Softmax cross entropy (ref ``CrossEntropyWithSoftmaxKernel``).

    Hard labels use the fused logsumexp-gather form (f32 accumulation, no
    materialised log-softmax); soft/smoothed labels need the full
    log-probability matrix and keep the log_softmax composition.
    """
    def fn(logits, lbl, *rest):
        fused = use_softmax and not soft_label and label_smoothing == 0.0
        lp = None
        if not fused:
            lp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else \
                jnp.log(jnp.maximum(logits, 1e-30))
        if soft_label:
            tgt = lbl
            if label_smoothing > 0.0:
                k = lp.shape[axis]
                tgt = tgt * (1 - label_smoothing) + label_smoothing / k
            loss = -jnp.sum(tgt * lp, axis=axis)
        else:
            lbl_i = lbl.astype(jnp.int32)
            if lbl_i.ndim == logits.ndim:
                lbl_i = jnp.squeeze(lbl_i, axis=axis)
            if label_smoothing > 0.0:
                k = lp.shape[axis]
                onehot = jax.nn.one_hot(lbl_i, k, axis=axis, dtype=lp.dtype)
                tgt = onehot * (1 - label_smoothing) + label_smoothing / k
                loss = -jnp.sum(tgt * lp, axis=axis)
            elif fused:
                loss = fused_softmax_ce_rows(logits, lbl_i, axis=axis)
            else:
                loss = -jnp.take_along_axis(
                    lp, jnp.expand_dims(lbl_i, axis), axis=axis
                ).squeeze(axis)
            # accumulate the masked sum / token count in f32 even when the
            # logits (and lp) are bf16 — the reductions, not the per-row
            # values, are where low-precision accumulation visibly drifts
            loss = loss.astype(jnp.float32)
            mask = (lbl_i != ignore_index)
            loss = jnp.where(mask, loss, 0.0)
            out_dtype = logits.dtype if jnp.issubdtype(
                logits.dtype, jnp.floating) else loss.dtype
            if rest:
                w = jnp.take(rest[0], jnp.maximum(lbl_i, 0), axis=0)
                loss = loss * jnp.where(mask, w, 0.0)
                if reduction == "mean":
                    return (jnp.sum(loss) / jnp.maximum(
                        jnp.sum(jnp.where(mask, w.astype(loss.dtype), 0.0)),
                        1e-12)).astype(out_dtype)
            elif reduction == "mean":
                return (jnp.sum(loss) / jnp.maximum(
                    jnp.sum(mask.astype(loss.dtype)), 1.0)).astype(out_dtype)
            return _reduce(loss, reduction).astype(out_dtype)
        return _reduce(loss, reduction)

    args = [_t(input), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    return apply_op("cross_entropy", fn, args)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    loss = apply_op("unsqueeze", lambda v: jnp.expand_dims(v, axis), [loss])
    if return_softmax:
        from .activation import softmax as softmax_fn
        return loss, softmax_fn(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100,  # noqa: A002
             reduction="mean", name=None):
    def fn(lp, lbl, *rest):
        lbl_i = lbl.astype(jnp.int32)
        loss = -jnp.take_along_axis(
            lp, jnp.expand_dims(lbl_i, 1), axis=1).squeeze(1)
        mask = (lbl_i != ignore_index)
        loss = jnp.where(mask, loss, 0.0)
        if rest:
            w = jnp.take(rest[0], jnp.maximum(lbl_i, 0), axis=0)
            loss = loss * jnp.where(mask, w, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(mask, w, 0.0)), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(mask.astype(lp.dtype)), 1.0)
        return _reduce(loss, reduction)
    args = [_t(input), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    return apply_op("nll_loss", fn, args)


def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return apply_op("mse_loss",
                    lambda a, b: _reduce(jnp.square(a - b), reduction),
                    [_t(input), _t(label)])


def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return apply_op("l1_loss",
                    lambda a, b: _reduce(jnp.abs(a - b), reduction),
                    [_t(input), _t(label)])


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)
    return apply_op("smooth_l1_loss", fn, [_t(input), _t(label)])


def binary_cross_entropy(input, label, weight=None, reduction="mean",  # noqa: A002
                         name=None):
    def fn(p, y, *rest):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-7)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if rest:
            loss = loss * rest[0]
        return _reduce(loss, reduction)
    args = [_t(input), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    return apply_op("bce", fn, args)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    has_w, has_pw = weight is not None, pos_weight is not None

    def fn(z, y, *rest):
        i = 0
        w = None
        if has_w:
            w = rest[i]
            i += 1
        pw = rest[i] if has_pw else None
        # stable: max(z,0) - z*y + log(1+exp(-|z|))
        base = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if pw is not None:
            log_sig = jax.nn.log_sigmoid(z)
            log_sig_neg = jax.nn.log_sigmoid(-z)
            base = -(pw * y * log_sig + (1 - y) * log_sig_neg)
        if w is not None:
            base = base * w
        return _reduce(base, reduction)
    args = [_t(logit), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    if pos_weight is not None:
        args.append(_t(pos_weight))
    return apply_op("bce_with_logits", fn, args)


def kl_div(input, label, reduction="mean", name=None):  # noqa: A002
    def fn(lp, y):
        loss = y * (jnp.log(jnp.maximum(y, 1e-30)) - lp)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)
    return apply_op("kl_div", fn, [_t(input), _t(label)])


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",  # noqa: A002
                         name=None):
    def fn(x, y):
        loss = jnp.where(y == 1, x, jnp.maximum(0.0, margin - x))
        return _reduce(loss, reduction)
    return apply_op("hinge_embedding_loss", fn, [_t(input), _t(label)])


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",  # noqa: A002
                        name=None):
    def fn(a, b, y):
        loss = jnp.maximum(0.0, -y * (a - b) + margin)
        return _reduce(loss, reduction)
    return apply_op("margin_ranking_loss", fn, [_t(input), _t(other), _t(label)])


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    def fn(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply_op("cosine_embedding_loss", fn,
                    [_t(input1), _t(input2), _t(label)])


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2,  # noqa: A002
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def fn(a, pos, neg):
        dp = jnp.sum(jnp.abs(a - pos) ** p, -1) ** (1.0 / p)
        dn = jnp.sum(jnp.abs(a - neg) ** p, -1) ** (1.0 / p)
        if swap:
            dsn = jnp.sum(jnp.abs(pos - neg) ** p, -1) ** (1.0 / p)
            dn = jnp.minimum(dn, dsn)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)
    return apply_op("triplet_margin_loss", fn,
                    [_t(input), _t(positive), _t(negative)])


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss (ref ``warpctc_op``) — forward-backward in log space via scan."""
    def fn(lp, lbl, in_len, lbl_len):
        # lp: (T, B, C) paddle layout
        T, B, C = lp.shape
        S = lbl.shape[1]
        ext = jnp.full((B, 2 * S + 1), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lbl.astype(jnp.int32))
        neg_inf = jnp.asarray(-1e30, lp.dtype)
        alpha0 = jnp.full((B, 2 * S + 1), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        alpha0 = alpha0.at[:, 1].set(
            jnp.take_along_axis(lp[0], ext[:, 1:2], axis=1)[:, 0])

        def step(alpha, lp_t):
            shift1 = jnp.concatenate(
                [jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            shift2 = jnp.concatenate(
                [jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
            same = jnp.concatenate(
                [jnp.full((B, 2), True),
                 ext[:, 2:] == ext[:, :-2]], axis=1)
            cand = jnp.where(same,
                             jnp.logaddexp(alpha, shift1),
                             jnp.logaddexp(jnp.logaddexp(alpha, shift1), shift2))
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return cand + emit, None

        def scan_step(carry, t):
            alpha = carry
            new_alpha, _ = step(alpha, lp[t])
            alpha = jnp.where((t < in_len)[:, None], new_alpha, alpha)
            return alpha, None

        alpha, _ = jax.lax.scan(scan_step, alpha0, jnp.arange(1, T))
        last = 2 * lbl_len.astype(jnp.int32)
        a_last = jnp.take_along_axis(alpha, last[:, None], axis=1)[:, 0]
        a_prev = jnp.take_along_axis(
            alpha, jnp.maximum(last - 1, 0)[:, None], axis=1)[:, 0]
        ll = jnp.logaddexp(a_last, a_prev)
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lbl_len.astype(lp.dtype), 1.0))
        return _reduce(loss, reduction)
    return apply_op("ctc_loss", fn, [_t(log_probs), _t(labels),
                                     _t(input_lengths), _t(label_lengths)])


def square_error_cost(input, label):  # noqa: A002
    return apply_op("square_error_cost",
                    lambda a, b: jnp.square(a - b), [_t(input), _t(label)])


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def fn(z, y, *rest):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if rest:
            loss = loss / rest[0]
        return _reduce(loss, reduction)
    args = [_t(logit), _t(label)]
    if normalizer is not None:
        args.append(_t(normalizer))
    return apply_op("sigmoid_focal_loss", fn, args)


def dice_loss(input, label, epsilon=1e-5, name=None):  # noqa: A002
    """Dice loss for segmentation (ref phi DiceLossKernel): label is
    int class ids with trailing dim 1."""
    def fn(x, y):
        y1 = jax.nn.one_hot(y.squeeze(-1), x.shape[-1], dtype=x.dtype)
        red = tuple(range(1, x.ndim))
        inter = jnp.sum(x * y1, axis=red)
        union = jnp.sum(x, axis=red) + jnp.sum(y1, axis=red)
        return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))
    return apply_op("dice_loss", fn, [_t(input), _t(label)])


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    """Negative log loss for binary probability input (ref log_loss_op)."""
    def fn(x, y):
        return -y * jnp.log(x + epsilon) - (1.0 - y) * jnp.log(1.0 - x + epsilon)
    return apply_op("log_loss", fn, [_t(input), _t(label)])


def soft_margin_loss(input, label, reduction="mean", name=None):  # noqa: A002
    def fn(x, y):
        return _reduce(jnp.log1p(jnp.exp(-y * x)), reduction)
    return apply_op("soft_margin_loss", fn, [_t(input), _t(label)])


def multi_label_soft_margin_loss(input, label, weight=None,  # noqa: A002
                                 reduction="mean", name=None):
    def fn(x, y, *w):
        loss = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        if w:
            loss = loss * w[0]
        return _reduce(jnp.mean(loss, axis=-1), reduction)
    args = [_t(input), _t(label)] + ([_t(weight)] if weight is not None else [])
    return apply_op("multi_label_soft_margin_loss", fn, args)


def triplet_margin_with_distance_loss(input, positive, negative,  # noqa: A002
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean", name=None):
    dfn = distance_function
    if dfn is None:
        def dfn(a, b):
            from ...ops import linalg as _lin
            return _lin.norm(a - b, p=2, axis=-1)
    dp = dfn(_t(input), _t(positive))
    dn = dfn(_t(input), _t(negative))
    if swap:
        dpn = dfn(_t(positive), _t(negative))
        dn = apply_op("minimum", jnp.minimum, [_t(dn), _t(dpn)])
    def fn(a, b):
        return _reduce(jnp.maximum(a - b + margin, 0.0), reduction)
    return apply_op("triplet_margin_with_distance_loss", fn, [_t(dp), _t(dn)])


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """N-pair loss (ref npair_loss in python/paddle/nn/functional/loss.py):
    softmax-CE over anchor·positiveᵀ similarity with same-label targets."""
    def fn(a, p, y):
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, -1)) + jnp.mean(jnp.sum(p * p, -1))) * 0.25
        sim = a @ p.T
        same = (y[:, None] == y[None, :]).astype(a.dtype)
        tgt = same / jnp.sum(same, -1, keepdims=True)
        ce = jnp.mean(jnp.sum(-tgt * jax.nn.log_softmax(sim, -1), -1))
        return ce + reg
    return apply_op("npair_loss", fn, [_t(anchor), _t(positive), _t(labels)])


def hsigmoid_loss(input, label, num_classes, weight, bias=None,  # noqa: A002
                  path_table=None, path_code=None, is_sparse=False, name=None):
    """Hierarchical sigmoid loss (ref phi HSigmoidLossKernel). Default
    complete-binary-tree coding over ``num_classes`` leaves; custom trees via
    path_table (node ids per step) + path_code (0/1 branch per step)."""
    import numpy as np_

    code_len = max(int(np_.ceil(np_.log2(max(num_classes, 2)))), 1)
    if path_table is None:
        # complete binary tree: internal node ids 0..num_classes-2; leaf c's
        # path from root follows the bits of (c + num_classes) >> k.
        # Shorter-than-code_len paths are padded with node id -1, which the
        # kernel masks out (the reference masks by per-leaf code length).
        tab, code = [], []
        for c in range(num_classes):
            node, bits = [], []
            idx = c + num_classes  # heap position of the leaf
            while idx > 1:
                parent = idx // 2
                node.append(parent - 1)      # internal node id
                bits.append(idx & 1)         # which child we are
                idx = parent
            node = node[::-1] + [-1] * (code_len - len(node))
            bits = bits[::-1] + [0] * (code_len - len(bits))
            tab.append(node[:code_len])
            code.append(bits[:code_len])
        path_table = Tensor(jnp.asarray(tab, jnp.int32))
        path_code = Tensor(jnp.asarray(code, jnp.int32))

    def fn(x, y, w, tab, code, *b):
        y = y.reshape(-1)
        nodes = tab[y]                         # (B, L) internal node ids
        valid = (nodes >= 0).astype(x.dtype)   # padded steps contribute 0
        nodes = jnp.maximum(nodes, 0)
        bits = code[y].astype(x.dtype)         # (B, L) 0/1
        wv = w[nodes]                          # (B, L, D)
        logits = jnp.einsum("bld,bd->bl", wv, x)
        if b:
            logits = logits + b[0].reshape(-1)[nodes]
        # P(branch) = sigmoid(logit) if bit==1 else sigmoid(-logit)
        sgn = 2.0 * bits - 1.0
        return jnp.mean(-jnp.sum(jax.nn.log_sigmoid(sgn * logits) * valid, -1))

    args = [_t(input), _t(label), _t(weight), _t(path_table), _t(path_code)]
    if bias is not None:
        args.append(_t(bias))
    return apply_op("hsigmoid_loss", fn, args)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean", name=None):
    """ArcFace/CosFace-style margin softmax CE (ref
    ``operators/margin_cross_entropy_op.cu``). ``logits`` are cosines; the
    target class angle is transformed cos(m1*θ + m2) - m3, then scaled.
    TP vocab-sharded variant: shard logits over the model axis with pjit —
    the softmax is computed globally by XLA."""
    def fn(lg, y):
        y = y.reshape(-1)
        onehot = jax.nn.one_hot(y, lg.shape[-1], dtype=lg.dtype)
        theta = jnp.arccos(jnp.clip(lg, -1.0 + 1e-7, 1.0 - 1e-7))
        tgt = jnp.cos(margin1 * theta + margin2) - margin3
        out = jnp.where(onehot > 0, tgt, lg) * scale
        logp = jax.nn.log_softmax(out, -1)
        loss = -jnp.sum(onehot * logp, -1)
        return _reduce(loss, reduction), jnp.exp(logp)
    loss, sm = apply_op("margin_cross_entropy", fn,
                        [_t(logits), _t(label)], n_outputs=2)
    return (loss, sm) if return_softmax else loss


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers (ref class_center_sample_op): returns
    (remapped_label, sampled_class_indices). Positive classes always kept;
    negatives fill up to num_samples deterministically from the generator."""
    from ...core import random as core_random
    lab = _t(label)
    y = lab._value.reshape(-1)
    pos = jnp.unique(y, size=min(int(y.size), num_classes),
                     fill_value=num_classes)
    key = core_random.split_key()
    perm = jax.random.permutation(key, num_classes)
    ispos = jnp.isin(perm, pos)
    order = jnp.argsort(~ispos, stable=True)  # positives first, then random negs
    sampled = jnp.sort(perm[order][:num_samples])
    remap = jnp.searchsorted(sampled, y)
    from ...core import autograd as _ag
    with _ag.no_grad():
        return (Tensor(remap.reshape(lab._value.shape).astype(y.dtype)),
                Tensor(sampled.astype(y.dtype)))
